"""mnt-lint v4: the call graph, the per-function summaries and their
fixpoint, the interprocedural rule upgrades, and the callee-aware
result cache.

Structure mirrors the layer being pinned:

- call-graph resolution (name/alias/self/base-class/attr-ctor) and the
  canonicalizer;
- summary extraction + fixpoint over diamond / recursive / mutually
  recursive chains, with the soundness defaults for unresolved calls;
- one positive and one negative per upgraded or new rule, exercised
  through ``check_source`` so the whole engine path runs;
- the seeded-bug fixture (tests/data/lint/interproc_seeded.py): PR
  11's three worked-example bugs moved one helper level down must fail
  v4 and pass v3 — the acceptance demonstration for ISSUE 17;
- ``--cache`` summary-dependency invalidation in a real git repo: an
  edit to ONLY the callee must re-lint the caller.
"""

from __future__ import annotations

import ast
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from manatee_tpu.lint import Config, check_source
from manatee_tpu.lint.callgraph import module_name
from manatee_tpu.lint.summaries import SummaryDB, is_blocking_name

REPO = Path(__file__).parent.parent
SEEDED = Path(__file__).parent / "data" / "lint" / "interproc_seeded.py"


def db_of(*files, config: Config | None = None) -> SummaryDB:
    """SummaryDB over (path, source) pairs (dedented)."""
    cfg = config or Config()
    srcs = []
    for path, src in files:
        text = textwrap.dedent(src)
        srcs.append((path, text, ast.parse(text)))
    return SummaryDB.build_from_sources(srcs, cfg)


def lint(src: str, config: Config | None = None,
         path: str = "snippet.py"):
    return check_source(textwrap.dedent(src), path, config)


def rules_of(src: str, config: Config | None = None,
             path: str = "snippet.py") -> set:
    return {f.rule for f in lint(src, config, path).findings}


# ---- call-graph resolution ----

def test_module_name_shapes():
    assert module_name("manatee_tpu/pg/manager.py") \
        == "manatee_tpu.pg.manager"
    assert module_name("manatee_tpu/obs/__init__.py") == "manatee_tpu.obs"
    assert module_name("tools/lint") == "tools.lint"


def test_resolve_module_function_and_from_import():
    db = db_of(
        ("a.py", """\
            def work():
                pass
        """),
        ("b.py", """\
            from a import work

            def go():
                work()
        """))
    fd = db.graph.resolve(None, "b.py", "work")
    assert fd is not None and fd.fqn == "a:work"


def test_resolve_module_alias():
    db = db_of(
        ("a.py", "def work():\n    pass\n"),
        ("b.py", "import a as aa\n\ndef go():\n    aa.work()\n"))
    fd = db.graph.resolve(None, "b.py", "aa.work")
    assert fd is not None and fd.fqn == "a:work"


def test_resolve_self_method_and_base_class():
    db = db_of(
        ("base.py", """\
            class Base:
                def ground(self):
                    pass
        """),
        ("sub.py", """\
            from base import Base

            class Sub(Base):
                def top(self):
                    self.ground()
        """))
    caller = db.graph.defs["sub:Sub.top"]
    fd = db.graph.resolve(caller, "sub.py", "self.ground")
    assert fd is not None and fd.fqn == "base:Base.ground"


def test_resolve_attr_ctor_one_level():
    db = db_of(("m.py", """\
        class Engine:
            def rev(self):
                pass

        class Car:
            def __init__(self):
                self.engine = Engine()

            def drive(self):
                self.engine.rev()
    """))
    caller = db.graph.defs["m:Car.drive"]
    fd = db.graph.resolve(caller, "m.py", "self.engine.rev")
    assert fd is not None and fd.fqn == "m:Engine.rev"
    # an attribute ALSO assigned from something else loses the fact
    db2 = db_of(("m.py", """\
        class Engine:
            def rev(self):
                pass

        class Car:
            def __init__(self):
                self.engine = Engine()

            def swap(self, other):
                self.engine = other

            def drive(self):
                self.engine.rev()
    """))
    caller2 = db2.graph.defs["m:Car.drive"]
    assert db2.graph.resolve(caller2, "m.py", "self.engine.rev") is None


def test_canonical_sees_through_from_import():
    db = db_of(("m.py", """\
        from time import sleep

        def nap():
            sleep(1)
    """))
    assert db.graph.canonical("m.py", "sleep") == "time.sleep"
    assert is_blocking_name(db.graph.canonical("m.py", "sleep"), None,
                            Config()) == "time.sleep"


def test_unresolved_stays_unresolved():
    db = db_of(("m.py", "def f(x):\n    x.quack()\n"))
    caller = db.graph.defs["m:f"]
    assert db.graph.resolve(caller, "m.py", "x.quack") is None
    assert db.unresolved_edges >= 1


# ---- fixpoint: chains, cycles, soundness defaults ----

DIAMOND = ("m.py", """\
    import time

    def bottom():
        time.sleep(1)

    def left():
        bottom()

    def right():
        bottom()

    def top():
        left()
        right()
""")


def test_may_block_diamond():
    db = db_of(DIAMOND)
    for fn in ("bottom", "left", "right", "top"):
        assert db.summaries["m:%s" % fn].may_block, fn
    chain = db.chain("m:top")
    assert chain[-1].startswith("time.sleep")
    assert len(chain) <= 3


def test_may_block_self_recursion_converges():
    db = db_of(("m.py", """\
        import time

        def f(n):
            if n:
                f(n - 1)
            time.sleep(1)
    """))
    assert db.summaries["m:f"].may_block
    assert db.rounds < 10


def test_may_block_mutual_recursion_converges():
    db = db_of(("m.py", """\
        import time

        def ping(n):
            if n:
                pong(n - 1)

        def pong(n):
            time.sleep(1)
            if n:
                ping(n - 1)
    """))
    assert db.summaries["m:ping"].may_block
    assert db.summaries["m:pong"].may_block
    assert db.rounds < 10


def test_to_thread_breaks_the_block_edge():
    # the helper is PASSED to to_thread, not called: no block edge
    db = db_of(("m.py", """\
        import asyncio
        import time

        def helper():
            time.sleep(1)

        async def go():
            await asyncio.to_thread(helper)
    """))
    assert db.summaries["m:helper"].may_block
    assert not db.summaries["m:go"].may_block


def test_awaited_blocking_coroutine_still_blocks():
    # awaiting an async callee that blocks inline still stalls the
    # loop: the await is not a thread hop
    db = db_of(("m.py", """\
        import time

        async def bad():
            time.sleep(1)

        async def caller():
            await bad()
    """))
    assert db.summaries["m:caller"].may_block


def test_may_suspend_proven_inline_coroutine():
    db = db_of(("m.py", """\
        class C:
            async def note(self):
                self.x = 1

            async def outer(self):
                await self.note()
    """))
    assert not db.summaries["m:C.note"].may_suspend
    assert not db.summaries["m:C.outer"].may_suspend


def test_may_suspend_unresolved_await_is_sound():
    # `await asyncio.sleep(0)` resolves to nothing — the sound default
    # is that an unresolvable awaited call MAY suspend
    db = db_of(("m.py", """\
        import asyncio

        async def napper():
            await asyncio.sleep(0)

        async def outer():
            await napper()
    """))
    assert db.summaries["m:napper"].may_suspend
    assert db.summaries["m:outer"].may_suspend


def test_swallows_cancellation_propagates_through_await():
    db = db_of(("m.py", """\
        async def eats(coro):
            try:
                await coro
            except Exception:
                return None

        async def trusts(coro):
            await eats(coro)
    """))
    assert db.summaries["m:eats"].swallows
    assert db.summaries["m:trusts"].swallows
    # re-raising arms are not swallows
    db2 = db_of(("m.py", """\
        async def honest(coro):
            try:
                await coro
            except Exception:
                raise
    """))
    assert not db2.summaries["m:honest"].swallows


def test_returns_resource_bound_and_direct():
    db = db_of(("m.py", """\
        def via_local(path):
            fh = open(path)
            return fh

        def direct(path):
            return open(path, "rb")

        def attr_only(proc):
            return proc.returncode
    """))
    assert db.summaries["m:via_local"].returns_resource
    assert db.summaries["m:direct"].returns_resource
    assert not db.summaries["m:attr_only"].returns_resource


def test_returns_resource_propagates_through_wrapper():
    db = db_of(("m.py", """\
        def inner(path):
            return open(path)

        def outer(path):
            return inner(path)
    """))
    assert db.summaries["m:outer"].returns_resource


def test_param_effects_closed_escaped_leaked_unknown():
    db = db_of(("m.py", """\
        class C:
            def closes(self, fh):
                fh.close()

            def stores(self, fh):
                self.fh = fh

            def ignores(self, fh):
                print(fh.name)

            def forwards(self, fh):
                self.closes(fh)

            def launders(self, fh):
                mystery(fh)
    """))
    eff = lambda q, p: db.summaries["m:C.%s" % q].param_effects[p]
    assert eff("closes", "fh") == "closed"
    assert eff("stores", "fh") == "escaped"
    assert eff("ignores", "fh") == "leaked"
    # passed to a resolved callee that protects it -> protected;
    # passed to an UNRESOLVED callee -> unknown (protective default)
    assert eff("forwards", "fh") == "unknown"
    assert eff("launders", "fh") == "unknown"


def test_required_held_from_caller_locksets():
    db = db_of(("m.py", """\
        class C:
            async def a(self):
                async with self._lock:
                    self._mut()

            async def b(self):
                async with self._lock:
                    self._mut()

            def _mut(self):
                self.items = []
    """))
    assert "self._lock" in db.summaries["m:C._mut"].required_held
    # one caller without the lock drops the guarantee
    db2 = db_of(("m.py", """\
        class C:
            async def a(self):
                async with self._lock:
                    self._mut()

            async def b(self):
                self._mut()

            def _mut(self):
                self.items = []
    """))
    assert not db2.summaries["m:C._mut"].required_held


def test_blocking_by_design_masks_reporting_not_derivation():
    cfg = Config(blocking_by_design=frozenset({"m.py::C._sync_flush"}))
    db = db_of(("m.py", """\
        import time

        class C:
            def _sync_flush(self):
                time.sleep(1)

            def outer(self):
                self._sync_flush()
    """), config=cfg)
    flush = db.summaries["m:C._sync_flush"]
    outer = db.summaries["m:C.outer"]
    # the runtime stall contract still derives the block...
    assert flush.may_block and outer.may_block
    # ...but neither end of the chain is reportable
    assert not flush.reportable_block
    assert not outer.reportable_block
    # a caller that blocks on its own stays reportable
    db2 = db_of(("m.py", """\
        import time

        class C:
            def _sync_flush(self):
                time.sleep(1)

            def outer(self):
                time.sleep(2)
                self._sync_flush()
    """), config=cfg)
    assert db2.summaries["m:C.outer"].reportable_block


# ---- upgraded/new rules: one positive + one negative each ----

def test_transitive_blocking_positive_with_chain():
    res = lint("""\
        import time

        def step():
            time.sleep(5)

        def middle():
            step()

        async def tick():
            middle()
    """)
    hits = [f for f in res.findings
            if f.rule == "transitive-blocking-in-async"]
    assert len(hits) == 1 and hits[0].line == 10
    assert "middle" in hits[0].msg and "time.sleep" in hits[0].msg


def test_transitive_blocking_negative_to_thread():
    assert "transitive-blocking-in-async" not in rules_of("""\
        import asyncio
        import time

        def step():
            time.sleep(5)

        async def tick():
            await asyncio.to_thread(step)
    """)


def test_transitive_blocking_direct_hits_stay_with_v1_rules():
    # a spelled-out time.sleep belongs to blocking-call-in-async, not
    # the transitive rule (one finding, not two)
    res = lint("""\
        import time

        async def tick():
            time.sleep(5)
    """)
    rules = [f.rule for f in res.findings]
    assert rules.count("blocking-call-in-async") == 1
    assert "transitive-blocking-in-async" not in rules


def test_transitive_blocking_by_design_quiet():
    cfg = Config(blocking_by_design=frozenset(
        {"snippet.py::_flush_now"}))
    src = """\
        import time

        def _flush_now():
            time.sleep(1)

        async def tick():
            _flush_now()
    """
    assert "transitive-blocking-in-async" in rules_of(src)
    assert "transitive-blocking-in-async" not in rules_of(src, cfg)


def test_blocking_call_canonicalized_through_import():
    assert "blocking-call-in-async" in rules_of("""\
        from time import sleep

        async def tick():
            sleep(1)
    """)
    # a project function named sleep is not time.sleep
    assert "blocking-call-in-async" not in rules_of("""\
        def sleep(n):
            pass

        async def tick():
            sleep(1)
    """)


def test_swallow_transitively_positive_and_negative():
    res = lint("""\
        async def eats(coro):
            try:
                await coro
            except Exception:
                return None

        async def trusts(coro):
            await eats(coro)
    """)
    hits = [f for f in res.findings
            if f.rule == "cancellation-swallowed-transitively"]
    assert len(hits) == 1 and hits[0].line == 8
    assert "eats" in hits[0].msg
    assert "cancellation-swallowed-transitively" not in rules_of("""\
        async def honest(coro):
            try:
                await coro
            except Exception:
                raise

        async def trusts(coro):
            await honest(coro)
    """)


def test_atomic_break_hidden_in_helpers():
    src = """\
        class C:
            def _read(self, ds):
                return self._store.load_meta(ds)

            def _put(self, ds, meta):
                self._store.save_meta(ds, meta)

            async def set_prop(self, ds, k, v):
                meta = self._read(ds)
                %s
                meta[k] = v
                self._put(ds, meta)
    """
    assert "atomic-section-broken" in rules_of(src % "await g()")
    assert "atomic-section-broken" not in rules_of(src % "pass")
    # v3 cannot see it: the helpers hide both halves
    assert "atomic-section-broken" not in rules_of(
        src % "await g()", Config(interproc=False))


def test_atomic_inline_coroutine_await_not_a_break():
    # an await of a project coroutine PROVEN never to suspend is not
    # an interleave point — and the same body with a real suspension
    # in the callee turns back into a finding
    src = """\
        class C:
            async def note(self):
                %s

            async def bump(self):
                cur = self.counter
                await self.note()
                self.counter = cur + 1
    """
    assert "atomic-section-broken" not in rules_of(src % "self.seen = 1")
    assert "atomic-section-broken" in rules_of(
        src % "await asyncio.sleep(0)")


def test_declared_region_tolerates_inline_await():
    begin = "# mnt-lint: " + "atomic-section"
    end = "# mnt-lint: " + "end-atomic-section"
    res = lint("""\
        class C:
            async def note(self):
                self.seen = 1

            async def f(self):
                %s
                a = self.x
                await self.note()
                self.y = a
                %s
    """ % (begin, end))
    assert "atomic-section-broken" not in {f.rule for f in res.findings}


def test_lockset_required_held_exempts_private_helper():
    src = """\
        class C:
            async def a(self):
                async with self._lock:
                    self.items = self.items + [1]

            async def b(self):
                async with self._lock:
                    self.items = []

            async def _mut(self):
                n = self.items
                await g()
                self.items = n + [2]

            async def run%s(self):
                %s
                    await self._mut()
    """
    guarded = src % ("", "async with self._lock:")
    assert "lockset-inconsistent" not in rules_of(guarded)
    # an unguarded caller voids required_held: the window reports
    unguarded = src % ("", "if True:")
    assert "lockset-inconsistent" in rules_of(unguarded)


def test_cancel_acquire_through_helper():
    src = """\
        class C:
            def _open_segment(self, path):
                return open(path, "rb")

            async def stream(self, path, sink):
                fh = self._open_segment(path)
                %s
    """
    bad = src % "await sink.ready()\n        fh.close()"
    res = lint(bad)
    assert "cancel-unsafe-acquire" in {f.rule for f in res.findings}
    assert "cancel-unsafe-acquire" not in rules_of(
        bad, Config(interproc=False))
    good = src % ("try:\n            await sink.ready()\n"
                  "        finally:\n            fh.close()")
    assert "cancel-unsafe-acquire" not in rules_of(good)


def test_cancel_leaky_pass_is_not_a_transfer():
    # v3 treated ANY call argument as an ownership transfer; a callee
    # whose summary proves the parameter is ignored is not one
    src = """\
        def _note(fh):
            print("opened")

        async def f(path):
            fh = open(path)
            _note(fh)
            await g()
            fh.close()
    """
    assert "cancel-unsafe-acquire" in rules_of(src)
    assert "cancel-unsafe-acquire" not in rules_of(
        src, Config(interproc=False))
    # a callee that CLOSES the handle ends the window
    assert "cancel-unsafe-acquire" not in rules_of("""\
        def _discard(fh):
            fh.close()

        async def f(path):
            fh = open(path)
            _discard(fh)
            await g()
    """)


# ---- the seeded-bug acceptance fixture ----

def test_seeded_bugs_fail_v4_pass_v3():
    text = SEEDED.read_text()
    v4 = check_source(text, str(SEEDED), Config())
    got = sorted({(f.line, f.rule) for f in v4.findings})
    by_rule = sorted(r for _, r in got)
    assert by_rule.count("atomic-section-broken") == 1      # MetaClobber
    assert by_rule.count("cancel-unsafe-acquire") == 2      # both leaks
    assert "transitive-blocking-in-async" in by_rule        # the fd open
    v3 = check_source(text, str(SEEDED), Config(interproc=False))
    assert v3.findings == []


# ---- callee-aware cache invalidation (real git repo, subprocess) ----

CALLER_SRC = """\
import helper


async def tick():
    helper.work()
"""

HELPER_BLOCKS = "import time\n\n\ndef work():\n    time.sleep(1)\n"
HELPER_CLEAN = "def work():\n    return 1\n"


def run_lint(tmp_repo, *args):
    return subprocess.run(
        [sys.executable, str(REPO / "tools" / "lint"), *args],
        cwd=tmp_repo, capture_output=True, text=True)


@pytest.fixture
def tmp_repo(tmp_path):
    def git(*args):
        subprocess.run(["git", *args], cwd=tmp_path, check=True,
                       capture_output=True)
    git("init", "-q")
    git("config", "user.email", "t@t")
    git("config", "user.name", "t")
    (tmp_path / "caller.py").write_text(CALLER_SRC)
    (tmp_path / "helper.py").write_text(HELPER_BLOCKS)
    git("add", ".")
    git("commit", "-qm", "seed")
    return tmp_path


def _cache_stats(stderr: str) -> tuple:
    part = stderr.split("cache: ")[1]
    return (int(part.split(" hits")[0]),
            int(part.split(", ")[1].split(" misses")[0]))


def test_cache_invalidated_by_callee_only_change(tmp_repo):
    r1 = run_lint(tmp_repo, ".", "--cache")
    assert r1.returncode == 1
    assert "transitive-blocking-in-async" in r1.stdout
    assert _cache_stats(r1.stderr) == (0, 2)
    # no-op re-run: both files served from cache, same verdict
    r2 = run_lint(tmp_repo, ".", "--cache")
    assert r2.returncode == 1
    assert _cache_stats(r2.stderr) == (2, 0)
    # edit ONLY the callee: the caller's bytes are unchanged, but its
    # recorded summary dependency no longer matches — both re-lint and
    # the caller's finding dissolves
    (tmp_repo / "helper.py").write_text(HELPER_CLEAN)
    r3 = run_lint(tmp_repo, ".", "--cache")
    assert r3.returncode == 0
    assert _cache_stats(r3.stderr) == (0, 2)
    # and the now-clean verdict caches normally again
    r4 = run_lint(tmp_repo, ".", "--cache")
    assert r4.returncode == 0
    assert _cache_stats(r4.stderr) == (2, 0)


def test_facts_cache_hits_on_noop_rerun(tmp_repo):
    stats = tmp_repo / "stats.json"
    run_lint(tmp_repo, ".", "--cache", "--stats", str(stats))
    cold = json.loads(stats.read_text())
    assert cold["summaries"]["facts_cache"] == {"hits": 0, "misses": 2}
    run_lint(tmp_repo, ".", "--cache", "--stats", str(stats))
    warm = json.loads(stats.read_text())
    # the no-op re-run must not re-extract a single file: this is the
    # guard against the fixpoint going quadratic in CI (ISSUE 17)
    assert warm["summaries"]["facts_cache"] == {"hits": 2, "misses": 0}
    assert warm["result_cache"] == {"hits": 2, "misses": 0}
    assert warm["summaries"]["functions"] == 2
    assert warm["wall_ms"] >= 0


def test_stats_shape_without_cache(tmp_repo):
    stats = tmp_repo / "stats.json"
    run_lint(tmp_repo, ".", "--stats", str(stats))
    data = json.loads(stats.read_text())
    assert data["result_cache"] is None
    s = data["summaries"]
    assert s["modules"] == 2 and s["functions"] == 2
    assert s["may_block"] == 2          # helper.work + caller.tick
    assert s["resolved_edges"] == 1     # caller -> helper
    assert s["fixpoint_rounds"] >= 1
