"""On-disk metric history (obs/history.py): segment-ring rotation and
retention, since-pagination across segments, crash recovery of a torn
final segment (including the writer's truncate-on-resume), the
/history endpoint contract, and `manatee-adm doctor`'s verdict for
each damage class."""

import asyncio
import json

import pytest

from manatee_tpu.doctor import check_history, summarize
from manatee_tpu.obs.history import (
    MetricsHistory,
    HistoryRecorder,
    dump_registry,
    history_http_reply,
    list_segments,
    parse_segment_name,
    read_records,
    segment_name,
)
from manatee_tpu.obs.metrics import Registry


def run(coro):
    return asyncio.run(coro)


def mk(directory, **kw) -> MetricsHistory:
    # a private registry so parallel test files cannot perturb the
    # snapshot contents under us
    kw.setdefault("registry", Registry())
    return MetricsHistory(directory, **kw)


def append_n(h: MetricsHistory, n: int) -> None:
    async def go():
        for _ in range(n):
            await h.append()
    run(go())


def seqs(directory) -> list[int]:
    return [r["seq"] for r in read_records(directory)]


def levels(findings, check):
    return [f["level"] for f in findings if f["check"] == check]


# ---- writer/reader units ----

def test_segment_names_roundtrip(tmp_path):
    assert parse_segment_name(segment_name(7)) == 7
    assert parse_segment_name("history-0000000000000042.jsonl") == 42
    assert parse_segment_name("history-notanumber.jsonl") is None
    assert parse_segment_name("other-0000000000000001.jsonl") is None
    assert parse_segment_name("history-1.txt") is None


def test_dump_registry_shapes(tmp_path):
    reg = Registry()
    reg.counter("reqs_total", "requests", ("code",)).inc(code="200")
    reg.gauge("depth", "queue depth").set(3)
    reg.histogram("dur_seconds", "latency").observe(0.12)
    snap = dump_registry(reg)
    assert snap["reqs_total"]["kind"] == "counter"
    assert snap["depth"]["samples"] == [[{}, 3]]
    # histograms persist count/sum only — never the bucket vector
    [(labels, s)] = snap["dur_seconds"]["series"]
    assert set(s) == {"count", "sum"}
    assert s["count"] == 1


def test_rotation_and_ring_wrap(tmp_path):
    h = mk(tmp_path, segment_records=3, keep_segments=2)
    append_n(h, 10)
    h.close()
    # rotation every 3 records names segments 1, 4, 7, 10 — and the
    # retention budget of 2 dropped the two oldest
    assert [parse_segment_name(p)
            for p in list_segments(tmp_path)] == [7, 10]
    assert seqs(tmp_path) == [7, 8, 9, 10]
    # a wrapped ring is still doctor-clean: continuity is judged over
    # the RETAINED records
    assert summarize(check_history(tmp_path))["ok"]


def test_since_pagination_across_segments(tmp_path):
    h = mk(tmp_path, segment_records=2)
    append_n(h, 7)
    h.close()
    assert [r["seq"] for r in h.records(since=3)] == [4, 5, 6, 7]
    # limit keeps the NEWEST n, and -0 must not slice the whole list
    assert [r["seq"] for r in h.records(since=3, limit=2)] == [6, 7]
    assert h.records(limit=0) == []
    body, status = history_http_reply(h, {"since": "3", "limit": "2"})
    assert status == 200
    assert [r["seq"] for r in body["records"]] == [6, 7]


def test_http_reply_contract(tmp_path):
    body, status = history_http_reply(None, {})
    assert status == 404 and "error" in body
    h = mk(tmp_path)
    body, status = history_http_reply(h, {"since": "bogus"})
    assert status == 400
    append_n(h, 2)
    h.close()
    body, status = history_http_reply(h, {})
    assert status == 200
    assert body["dir"] == str(h.dir)
    assert [r["seq"] for r in body["records"]] == [1, 2]


def test_recorder_appends_periodically(tmp_path):
    async def go():
        h = mk(tmp_path, segment_records=100)
        rec = HistoryRecorder(h, interval=0.02)
        rec.start()
        await asyncio.sleep(0.15)
        await rec.stop()
    run(go())
    assert len(seqs(tmp_path)) >= 2
    assert summarize(check_history(tmp_path))["ok"]


# ---- crash recovery ----

def test_torn_tail_truncated_on_resume(tmp_path):
    h = mk(tmp_path, segment_records=4)
    append_n(h, 5)                  # segments 1 (recs 1-4) and 5
    h.close()
    last = list_segments(tmp_path)[-1]
    with open(last, "ab") as fh:    # crash mid-append: a torn line
        fh.write(b'{"seq": 6, "ts"')
    # the reader skips it ...
    assert seqs(tmp_path) == [1, 2, 3, 4, 5]
    # ... the doctor notes it without calling it damage ...
    rep = summarize(check_history(tmp_path))
    assert rep["ok"] and levels(rep["findings"],
                                "history-torn-tail") == ["note"]
    # ... and a resumed writer truncates it, then resumes seq
    # continuity from the last DURABLE record
    h2 = mk(tmp_path, segment_records=4)
    assert b'"seq": 6' not in last.read_bytes()
    assert last.read_bytes().endswith(b"\n")
    append_n(h2, 1)
    h2.close()
    assert seqs(tmp_path) == [1, 2, 3, 4, 5, 6]
    assert summarize(check_history(tmp_path))["ok"]


def test_missing_final_newline_is_completed_on_resume(tmp_path):
    # the crash ate only the "\n": the record IS durable, and a blind
    # append would fuse the next record onto its line
    h = mk(tmp_path, segment_records=10)
    append_n(h, 3)
    h.close()
    last = list_segments(tmp_path)[-1]
    raw = last.read_bytes()
    assert raw.endswith(b"\n")
    last.write_bytes(raw[:-1])
    h2 = mk(tmp_path, segment_records=10)
    append_n(h2, 1)
    h2.close()
    assert seqs(tmp_path) == [1, 2, 3, 4]
    assert summarize(check_history(tmp_path))["ok"]


def test_torn_only_line_of_fresh_segment(tmp_path):
    # crash between rotate and the first durable append: the fresh
    # segment holds ONLY the torn line; the resumed writer empties it
    # and the next append re-opens it under the SAME (correct) name
    h = mk(tmp_path, segment_records=4)
    append_n(h, 4)                  # segment 1 exactly full
    h.close()
    torn = tmp_path / segment_name(5)
    torn.write_bytes(b'{"seq": 5,')
    h2 = mk(tmp_path, segment_records=4)
    append_n(h2, 1)
    h2.close()
    assert seqs(tmp_path) == [1, 2, 3, 4, 5]
    recs = read_records(tmp_path)
    assert recs[-1]["seq"] == 5
    rep = summarize(check_history(tmp_path))
    assert rep["ok"] and rep["damage"] == 0, rep


# ---- doctor verdicts per damage class ----

def healthy_ring(tmp_path, *, segment_records=2, n=5) -> None:
    h = mk(tmp_path, segment_records=segment_records)
    append_n(h, n)
    h.close()


def test_doctor_missing_and_empty_dirs(tmp_path):
    rep = summarize(check_history(tmp_path / "nope"))
    assert rep["ok"] and rep["warnings"] == 1
    assert levels(rep["findings"],
                  "history-dir-missing") == ["warning"]
    (tmp_path / "empty").mkdir()
    rep = summarize(check_history(tmp_path / "empty"))
    assert rep["ok"] and levels(rep["findings"],
                                "history-empty") == ["note"]


def test_doctor_healthy_ring_is_silent(tmp_path):
    healthy_ring(tmp_path)
    assert check_history(tmp_path) == []


def test_doctor_mid_stream_corruption_is_damage(tmp_path):
    healthy_ring(tmp_path, segment_records=4, n=4)
    seg = list_segments(tmp_path)[0]
    lines = seg.read_bytes().splitlines()
    lines[1] = b"GARBAGE NOT JSON"
    seg.write_bytes(b"\n".join(lines) + b"\n")
    rep = summarize(check_history(tmp_path))
    assert not rep["ok"]
    assert levels(rep["findings"], "history-corrupt") == ["damage"]


def test_doctor_seq_gap_is_damage(tmp_path):
    healthy_ring(tmp_path, segment_records=2, n=5)  # segs 1, 3, 5
    mid = [p for p in list_segments(tmp_path)
           if parse_segment_name(p) == 3]
    mid[0].unlink()
    rep = summarize(check_history(tmp_path))
    assert not rep["ok"]
    assert levels(rep["findings"], "history-gap") == ["damage"]


def test_doctor_misnamed_segment_is_damage(tmp_path):
    healthy_ring(tmp_path, segment_records=10, n=2)  # one segment, 1
    seg = list_segments(tmp_path)[0]
    seg.rename(seg.with_name(segment_name(2)))
    rep = summarize(check_history(tmp_path))
    assert not rep["ok"]
    assert levels(rep["findings"], "history-misnamed") == ["damage"]


def test_doctor_notes_oddities(tmp_path):
    healthy_ring(tmp_path, segment_records=10, n=2)
    (tmp_path / "history-garbagename.jsonl").write_text("x\n")
    (tmp_path / segment_name(3)).write_bytes(b"")
    rep = summarize(check_history(tmp_path))
    assert rep["ok"] and rep["damage"] == 0
    assert levels(rep["findings"],
                  "history-unrecognized-name") == ["note"]
    assert levels(rep["findings"],
                  "history-empty-segment") == ["note"]


def test_doctor_cli_history_dir(tmp_path):
    """`manatee-adm doctor --history-dir` end to end: the offline
    verdict with the CLI's exit-code/JSON contract."""
    import subprocess
    import sys

    healthy_ring(tmp_path)
    cp = subprocess.run(
        [sys.executable, "-m", "manatee_tpu.cli", "doctor",
         "--history-dir", str(tmp_path), "-j"],
        capture_output=True, text=True, timeout=60)
    assert cp.returncode == 0, (cp.stdout, cp.stderr)
    body = json.loads(cp.stdout)
    assert body["ok"] and body["damage"] == 0
    # damage exits nonzero
    seg = list_segments(tmp_path)[0]
    lines = seg.read_bytes().splitlines()
    lines[0] = b"NOT JSON"
    seg.write_bytes(b"\n".join(lines) + b"\n")
    cp = subprocess.run(
        [sys.executable, "-m", "manatee_tpu.cli", "doctor",
         "--history-dir", str(tmp_path), "-j"],
        capture_output=True, text=True, timeout=60)
    assert cp.returncode != 0
    body = json.loads(cp.stdout)
    assert not body["ok"] and body["damage"] >= 1


def test_append_failpoint_error_does_not_advance_seq(tmp_path,
                                                     monkeypatch):
    """An error armed at obs.history.append must surface to the
    caller (the recorder logs and continues) without burning a seq —
    the ring's continuity invariant survives fault drills."""
    from manatee_tpu import faults
    from manatee_tpu.faults import FaultRegistry

    reg = FaultRegistry()
    monkeypatch.setattr(faults, "_REGISTRY", reg)
    h = mk(tmp_path, segment_records=10)
    append_n(h, 2)

    async def go():
        reg.arm_spec("obs.history.append=error,count=1")
        with pytest.raises(faults.FaultError):
            await h.append()
        await h.append()
    run(go())
    h.close()
    assert seqs(tmp_path) == [1, 2, 3]
    assert summarize(check_history(tmp_path))["ok"]
