"""Durable-before-ack coordination commits (VERDICT r4 #1).

ZooKeeper fsyncs its transaction log on a quorum BEFORE acknowledging —
that is the guarantee manatee's deposed/generation records ride on
(/root/reference/lib/zookeeperMgr.js:605-630,
/root/reference/docs/xlog-diverge.md:1-31).  These tests pin the same
contract for coordd: an acknowledged mutation is on disk (fsynced op
log) before the ack leaves the server, so a SIGKILL the instant after
the ack — the old 50 ms debounce window — can no longer roll back
acked cluster state.
"""

import asyncio
import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

from manatee_tpu.coord.api import Op
from manatee_tpu.coord.client import NetCoord
from manatee_tpu.coord.server import CoordServer

REPO = Path(__file__).resolve().parent.parent


def oplog_bytes(data_dir: Path) -> int:
    return sum(p.stat().st_size
               for p in data_dir.glob("coordd-oplog-*.jsonl"))


def oplog_seqs(data_dir: Path) -> list[int]:
    out = []
    for p in sorted(data_dir.glob("coordd-oplog-*.jsonl")):
        out += [json.loads(line)["seq"]
                for line in p.read_text().splitlines() if line]
    return out


def run(coro):
    return asyncio.run(coro)


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


async def crash(server: CoordServer) -> None:
    """Abandon a server WITHOUT stop(): no final snapshot flush, no
    clean teardown — only what was already durably on disk survives,
    exactly like a SIGKILL."""
    for conn in list(server._conns):
        conn.sever()
    for t in (server._expiry_task, server._follow_task,
              server._probe_task, server._compact_task):
        if t:
            t.cancel()
    if server._server:
        server._server.close()
        await server._server.wait_closed()


def test_acked_write_survives_crash_without_snapshot(tmp_path):
    """The old failure mode: ack, then crash before the debounced
    snapshot lands.  With the op log the acked write must be there on
    restart even though NO snapshot was ever written."""
    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/state", b"gen7")
        await c.set("/state", b"gen8", 0)
        await c.close()
        await crash(server)

        # no compaction ever ran: the log alone must carry the writes
        assert not (tmp_path / "coordd-tree.json").exists()
        assert oplog_bytes(tmp_path) > 0

        reborn = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        data, version = reborn.tree.get("/state")
        assert data == b"gen8" and version == 1
        assert reborn._seq == 2
    run(go())


def test_put_cluster_state_survives_sigkill_after_ack(tmp_path):
    """The done-criterion scenario over the REAL daemon: a
    putClusterState-shaped transaction (history create + state CAS) is
    acked, the coordd process is SIGKILLed immediately (well inside the
    old debounce window), and the write survives restart."""
    port = free_port()
    data_dir = tmp_path / "coord-data"
    logf = open(tmp_path / "coordd.log", "ab")
    env = dict(os.environ, PYTHONPATH=str(REPO))
    argv = [sys.executable, "-m", "manatee_tpu.coord.server",
            "--port", str(port), "--data-dir", str(data_dir),
            "--tick", "0.1"]

    async def wait_port():
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            try:
                r, w = await asyncio.wait_for(
                    asyncio.open_connection("127.0.0.1", port), 1.0)
                w.close()
                return
            except (OSError, asyncio.TimeoutError):
                await asyncio.sleep(0.05)
        raise RuntimeError("coordd never came up")

    async def go():
        proc = await asyncio.to_thread(
            subprocess.Popen, argv, stdout=logf, stderr=logf, env=env,
                                start_new_session=True)
        try:
            await wait_port()
            c = NetCoord("127.0.0.1:%d" % port, session_timeout=5)
            await c.connect()
            await c.mkdirp("/manatee/1/history")
            state = json.dumps({"generation": 3,
                                "deposed": [{"id": "old-primary"}]})
            await c.create("/manatee/1/state", b"{}")
            _, ver = await c.get("/manatee/1/state")
            await c.multi([
                Op.create("/manatee/1/history/3-", state.encode(),
                          sequential=True),
                Op.set("/manatee/1/state", state.encode(), ver),
            ])
            # the ack has returned: kill NOW, inside what used to be
            # the 50 ms debounce window
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=5)
            await c.close()
        finally:
            if proc.poll() is None:
                os.killpg(proc.pid, signal.SIGKILL)
                proc.wait(timeout=5)

        proc = await asyncio.to_thread(
            subprocess.Popen, argv, stdout=logf, stderr=logf, env=env,
            start_new_session=True)
        try:
            await wait_port()
            c = NetCoord("127.0.0.1:%d" % port, session_timeout=5)
            await c.connect()
            data, _ = await c.get("/manatee/1/state")
            got = json.loads(data.decode())
            # the deposed marker — the record whose loss is a
            # split-brain seed — survived the kill
            assert got["deposed"] == [{"id": "old-primary"}]
            assert got["generation"] == 3
            hist = await c.get_children("/manatee/1/history")
            assert len(hist) == 1
            await c.close()
        finally:
            os.killpg(proc.pid, signal.SIGKILL)
            proc.wait(timeout=5)

    run(go())
    logf.close()


def test_compaction_truncates_log_and_recovery_uses_both(tmp_path):
    """snapshot_every ops trigger a compaction snapshot, after which the
    log restarts empty; recovery = snapshot + replay of the tail."""
    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path),
                             snapshot_every=8)
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/state", b"v0")
        for i in range(8):            # reaches snapshot_every
            await c.set("/state", b"v%d" % (i + 1), i)

        # the debounced compaction lands; the covered segments vanish
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if (tmp_path / "coordd-tree.json").exists() \
                    and oplog_bytes(tmp_path) == 0:
                break
            await asyncio.sleep(0.02)
        assert (tmp_path / "coordd-tree.json").exists()
        assert oplog_bytes(tmp_path) == 0

        # a few more writes land in the fresh log (the replay tail)
        await c.set("/state", b"v9", 8)
        await c.set("/state", b"v10", 9)
        await c.close()
        await crash(server)

        reborn = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        data, version = reborn.tree.get("/state")
        assert data == b"v10" and version == 10
        assert reborn._seq == 11      # 1 create + 10 sets
    run(go())


def test_torn_final_log_line_is_discarded(tmp_path):
    """A crash mid-append leaves a torn last line; it was never acked,
    so recovery must drop it and keep everything before it."""
    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/a", b"1")
        await c.create("/b", b"2")
        await c.close()
        await crash(server)

        seg = sorted(tmp_path.glob("coordd-oplog-*.jsonl"))[-1]
        with open(seg, "ab") as f:
            f.write(b'{"seq": 3, "req": {"op": "create", "pa')  # torn

        reborn = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        assert reborn.tree.get("/a")[0] == b"1"
        assert reborn.tree.get("/b")[0] == b"2"
        assert reborn._seq == 2
    run(go())


def test_follower_logs_before_acking(tmp_path):
    """A follower's sync_op ack means "on my disk", not "in my memory":
    the moment the client's write returns, the leader's log AND at
    least a commit quorum of follower logs must contain it."""
    from tests.test_ensemble import (
        connstr,
        start_ensemble,
        wait_leader_with_quorum,
    )

    async def go():
        dirs = [tmp_path / ("m%d" % i) for i in range(3)]
        servers, members = await start_ensemble(
            data_dirs=[str(d) for d in dirs])
        try:
            assert await wait_leader_with_quorum(servers[0], 2)
            c = NetCoord(connstr(members), session_timeout=5)
            await c.connect()
            await c.create("/state", b"acked")
            await c.close()

            logs = [oplog_seqs(d) for d in dirs]
            # leader fsynced before acking…
            assert 1 in logs[0]
            # …and so did enough followers for a commit quorum (the
            # leader returns as soon as quorum-1 followers ack, so
            # demand >= 1 of 2, not both)
            assert sum(1 in lg for lg in logs[1:]) >= 1
        finally:
            for s in servers:
                await s.stop()
    run(go())


def test_full_ensemble_sigkill_storm_keeps_acked_state(tmp_path):
    """Whole-ensemble power loss: every member SIGKILLed right after an
    acked write, all restarted from disk — the acked state must be
    what the reborn ensemble serves."""
    n = 3
    ports = [free_port() for _ in range(n)]
    members = ",".join("127.0.0.1:%d" % p for p in ports)
    env = dict(os.environ, PYTHONPATH=str(REPO))
    logf = open(tmp_path / "coordd.log", "ab")

    def spawn(i):
        argv = [sys.executable, "-m", "manatee_tpu.coord.server",
                "--port", str(ports[i]),
                "--data-dir", str(tmp_path / ("m%d" % i)),
                "--tick", "0.1", "--ensemble", members,
                "--ensemble-id", str(i), "--promote-grace", "0.5"]
        return subprocess.Popen(argv, stdout=logf, stderr=logf, env=env,
                                start_new_session=True)

    async def connect_any():
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            c = NetCoord(members, session_timeout=5)
            try:
                await asyncio.wait_for(c.connect(), 2.0)
                return c
            except asyncio.CancelledError:
                raise
            except Exception:
                try:
                    await c.close()
                except asyncio.CancelledError:
                    raise
                except Exception:
                    pass
                await asyncio.sleep(0.2)
        raise RuntimeError("no ensemble leader accepted a session")

    async def go():
        procs = [spawn(i) for i in range(n)]
        try:
            for round_no in range(3):
                payload = b"storm-round-%d" % round_no
                # retry until a commit quorum of followers has attached
                # (the leader refuses mutations before that)
                deadline = time.monotonic() + 20
                while True:
                    c = await connect_any()
                    try:
                        if round_no == 0:
                            await c.create("/state", payload)
                        else:
                            _, ver = await c.get("/state")
                            await c.set("/state", payload, ver)
                        break
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        # ambiguous commit (applied locally, quorum
                        # refused): a retry may see the write already
                        # there — that counts as acked
                        try:
                            data, _ = await c.get("/state")
                            if data == payload:
                                break
                        except asyncio.CancelledError:
                            raise
                        except Exception:
                            pass
                        if time.monotonic() > deadline:
                            raise
                        await asyncio.sleep(0.2)
                    finally:
                        await c.close()
                # acked: kill EVERY member immediately
                for p in procs:
                    if p.poll() is None:
                        os.killpg(p.pid, signal.SIGKILL)
                for p in procs:
                    p.wait(timeout=5)
                procs = [spawn(i) for i in range(n)]
                c = await connect_any()
                data, _ = await c.get("/state")
                assert data == payload, \
                    "acked write lost in round %d" % round_no
                await c.close()
        finally:
            for p in procs:
                if p.poll() is None:
                    os.killpg(p.pid, signal.SIGKILL)
                    p.wait(timeout=5)
    run(go())
    logf.close()


def test_append_failure_falls_back_to_snapshot(tmp_path, monkeypatch):
    """A failed log append must not leave a silent seq gap that poisons
    every later fsynced entry at replay: the server falls back to a
    synchronous snapshot covering the seq (code-review r5 finding)."""
    from manatee_tpu.coord import server as server_mod

    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/state", b"v0")

        real_fsync = os.fsync
        fail = {"on": True}

        def flaky_fsync(fd):
            if fail["on"]:
                fail["on"] = False
                raise OSError(28, "No space left on device")
            return real_fsync(fd)

        monkeypatch.setattr(server_mod.os, "fsync", flaky_fsync)
        await c.set("/state", b"v1", 0)     # append fails -> snapshot
        await c.set("/state", b"v2", 1)     # healthy append again
        await c.close()
        await crash(server)

        # recovery must see BOTH writes — no gap, nothing rolled back
        reborn = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        data, version = reborn.tree.get("/state")
        assert data == b"v2" and version == 2
        assert reborn._seq == 3
    run(go())


def test_stale_epoch_segments_never_replay(tmp_path):
    """Crash window between resync-snapshot install and old-segment
    unlink: pre-resync entries must not replay on top of the adopted
    tree (code-review r5 finding).  Simulated by installing a
    bumped-epoch snapshot at a LOWER seq while divergent old-epoch
    segments remain on disk."""
    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/state", b"diverged-1")
        await c.set("/state", b"diverged-2", 0)
        await c.set("/state", b"diverged-3", 1)
        await c.close()
        await crash(server)
        assert len(oplog_seqs(tmp_path)) == 3

        # the "adopted" tree: seq 2, epoch 1, value from the leader
        from manatee_tpu.coord.model import ZNodeTree
        adopted = ZNodeTree()
        adopted.create("/state", b"leader-truth")
        snap = adopted.to_snapshot()
        snap["seq"] = 2
        snap["epoch"] = 1
        (tmp_path / "coordd-tree.json").write_text(json.dumps(snap))

        reborn = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        data, _ = reborn.tree.get("/state")
        assert data == b"leader-truth"      # divergent seq 3 NOT replayed
        assert reborn._seq == 2
        # the stale segments were cleaned up at startup
        assert oplog_bytes(tmp_path) == 0
    run(go())


def test_mid_log_corruption_refuses_to_start(tmp_path):
    """Corruption that is NOT a torn final line means acked writes
    would be silently rolled back — the server must refuse to start
    (code-review r5 finding)."""
    import pytest

    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/a", b"1")
        await c.create("/b", b"2")
        await c.close()
        await crash(server)

        seg = sorted(tmp_path.glob("coordd-oplog-*.jsonl"))[-1]
        lines = seg.read_bytes().split(b"\n")
        lines[0] = b'{"seq": 1, "req": GARBLED'   # corrupt MIDDLE entry
        seg.write_bytes(b"\n".join(lines))

        with pytest.raises(RuntimeError, match="corrupt"):
            CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
    run(go())


def test_orphaned_snapshot_tmp_cleaned_at_startup(tmp_path):
    """A compaction cancelled mid-write leaks a coordd-tree.json.tmp-*
    file; startup must clean it up (code-review r5 finding)."""
    async def go():
        (tmp_path / "coordd-tree.json.tmp-0-5").write_text("{}")
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        assert not list(tmp_path.glob("coordd-tree.json.tmp*"))
        await server.start()
        await server.stop()
    run(go())


def test_torn_tail_truncated_then_reused_segment_stays_clean(tmp_path):
    """After a torn tail is discarded, the next append may reuse the
    same segment file (same start seq); without truncation the new
    acked entry would concatenate onto the torn bytes and be eaten on
    the NEXT restart (code-review r5 finding)."""
    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/a", b"1")
        await c.close()
        await crash(server)

        seg = sorted(tmp_path.glob("coordd-oplog-*.jsonl"))[-1]
        with open(seg, "ab") as f:
            f.write(b'{"seq": 2, "req": {"op": "cre')       # torn

        # restart 1: torn tail discarded AND truncated; a new acked
        # write lands at seq 2 — possibly in the same segment file
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        assert server._seq == 1
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/b", b"2")
        await c.close()
        await crash(server)

        # restart 2: BOTH acked writes must be there
        reborn = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        assert reborn.tree.get("/a")[0] == b"1"
        assert reborn.tree.get("/b")[0] == b"2"
        assert reborn._seq == 2
    run(go())


def test_corrupt_snapshot_refuses_to_start(tmp_path):
    """A snapshot that exists but cannot be loaded must refuse startup:
    falling back to 'empty' would reset the epoch and delete the log
    segments an operator could recover from (code-review r5 finding)."""
    import pytest

    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path),
                             snapshot_every=1)
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/state", b"v0")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if (tmp_path / "coordd-tree.json").exists():
                break
            await asyncio.sleep(0.02)
        await c.set("/state", b"v1", 0)     # lands in the log tail
        await c.close()
        await crash(server)

        snap = tmp_path / "coordd-tree.json"
        good = snap.read_text()
        n_segments = len(list(tmp_path.glob("coordd-oplog-*.jsonl")))
        # bad JSON, and VALID json of the wrong shape — from_snapshot
        # is lenient and would silently yield an EMPTY tree for the
        # latter (epoch 0 -> segments deleted as stale), so _load_tree
        # must validate the shape itself (code-review r5 high)
        for bad in (good[:40], "{}", "[]", '{"v": 2, "root": {}}',
                    '{"v": 1}', "null",
                    # v1+root but MISSING seq/epoch: loading would
                    # default the epoch to 0 and delete the
                    # real-epoch segments as stale
                    '{"v": 1, "root": {}}'):
            snap.write_text(bad)
            with pytest.raises(RuntimeError, match="refusing to start"):
                CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
            # and it preserved the segments for the operator
            assert len(list(tmp_path.glob("coordd-oplog-*.jsonl"))) \
                == n_segments
    run(go())


def test_append_during_mixed_persist_window_survives(tmp_path,
                                                     monkeypatch):
    """A plain op racing a mixed transaction's whole-log-superseding
    snapshot must not land in a new-epoch segment that dies with a
    crash before the snapshot installs (code-review r5 finding): the
    log fence holds appends until the install completes."""
    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        real_write = server._write_snapshot_tmp

        def slow_write(snap):
            time.sleep(0.25)       # executor thread: widen the window
            return real_write(snap)

        monkeypatch.setattr(server, "_write_snapshot_tmp", slow_write)
        await server.start()
        c1 = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        c2 = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c1.connect()
        await c2.connect()
        await c1.create("/state", b"v0")

        async def mixed():
            # ephemeral inside a transaction -> snapshot-mode persist
            await c1.multi([
                Op.create("/eph", b"e", ephemeral=True),
                Op.set("/state", b"mixed", 0),
            ])

        async def plain():
            await asyncio.sleep(0.1)   # lands inside the write window
            await c2.create("/plain", b"acked")

        await asyncio.gather(mixed(), plain())
        await c1.close()
        await c2.close()
        await crash(server)

        reborn = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        assert reborn.tree.get("/state")[0] == b"mixed"
        assert reborn.tree.get("/plain")[0] == b"acked"   # not lost
    run(go())


def test_sequential_replay_reproduces_acked_names(tmp_path):
    """Ephemeral-sequential creates bump the same per-parent counter
    as persistent ones but are never logged; replay must still mint
    the exact names that were acked (code-review r5 finding)."""
    async def go():
        server = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        await server.start()
        c = NetCoord("127.0.0.1:%d" % server.port, session_timeout=5)
        await c.connect()
        await c.create("/x", b"")
        # ephemeral-sequential (an election join): counter 0 -> 1,
        # NOT logged
        eph = await c.create("/x/e-", b"", ephemeral=True,
                             sequential=True)
        assert eph.endswith("0000000000")
        # persistent-sequential: acked as ...0000000001
        acked = await c.create("/x/n-", b"h", sequential=True)
        assert acked.endswith("0000000001")
        await c.close()
        await crash(server)

        reborn = CoordServer(port=0, tick=0.05, data_dir=str(tmp_path))
        # the acked name exists (naive replay would mint ...0000000000)
        assert reborn.tree.get(acked)[0] == b"h"
    run(go())
