"""Seeded-bug fixtures: PR 11's three worked-example bugs, each moved
ONE call level down into a helper.

Under per-function analysis (``interprocedural: false``) every
function here lints clean — the helper hides the evidence.  The v4
summaries make each one a finding again, and
tests/test_lint_summaries.py pins BOTH directions, so this file is the
machine-checked demonstration that the interprocedural layer closes
the exact regression ISSUE 17 names.

Do not "fix" these: they are deliberately wrong.
"""

import asyncio


class MetaClobber:
    """dirstore's torn-meta bug, helper-hidden: the load and the save
    both live one call down and the await sits between them — a
    concurrent writer lands during the flush and this save reinstates
    the stale meta."""

    def __init__(self, store):
        self._store = store

    def _read_meta(self, dataset):
        return self._store.load_meta(dataset)

    def _put_meta(self, dataset, meta):
        self._store.save_meta(dataset, meta)

    async def set_prop(self, dataset, key, value):
        meta = self._read_meta(dataset)
        await self._store.flush()
        meta[key] = value
        self._put_meta(dataset, meta)


class HalfHandshake:
    """the half-handshaken socket leak: the acquire hides inside an
    async helper that returns the handle pair; a cancellation landing
    on the drain strands the connection forever."""

    async def _connect(self, host, port):
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), 5.0)
        return reader, writer

    async def handshake(self, host, port):
        reader, writer = await self._connect(host, port)
        await writer.drain()
        writer.close()
        return reader


class WalReceiver:
    """the walreceiver fd leak: a sync helper opens the segment file
    and hands the fd back; a cancellation between the open and the
    close leaks it."""

    def _open_segment(self, path):
        return open(path, "rb")

    async def stream(self, path, sink):
        fh = self._open_segment(path)
        await sink.ready()
        sink.push(fh.read())
        fh.close()
