# mnt-lint fixture: the same violation classes as positives.py, each
# silenced with a per-line suppression — the engine must report zero
# findings and account for every suppression.
import asyncio
import os                                 # mnt-lint: disable=unused-import
import time


async def orphan():
    asyncio.create_task(work())           # mnt-lint: disable=orphan-task
    t = asyncio.ensure_future(work())     # mnt-lint: disable=orphan-task
    return t


async def blocking():
    time.sleep(1)     # mnt-lint: disable=blocking-call-in-async
    open("/tmp/x")    # mnt-lint: disable=blocking-io-in-async


async def swallows():
    try:
        await work()
    except Exception:  # mnt-lint: disable=swallowed-cancellation
        pass


async def unreaped():
    t = asyncio.create_task(work())
    t.cancel()                  # mnt-lint: disable=cancel-without-await


async def undisciplined(lock):
    await lock.acquire()        # mnt-lint: disable=lock-discipline
    lock.release()


async def unbounded():
    await asyncio.open_connection("h", 1)  # mnt-lint: disable=all


class TornQuiet:
    async def bump(self):
        cur = self.counter
        await work()
        self.counter = cur + 1  # mnt-lint: disable=atomic-section-broken


class LocksetQuiet:
    async def locked_add(self, item):
        async with self._lock:
            self.items = self.items + [item]

    async def locked_clear(self):
        async with self._lock:
            self.items = []

    async def racy(self):
        n = self.items
        await work()
        self.items = n + [1]  # mnt-lint: disable=lockset-inconsistent,atomic-section-broken


async def cancel_leak(host):
    # the disable names both rules that fire on the acquire line: the
    # unbounded direct await and the cancel-window leak
    r, w = await asyncio.open_connection(  # mnt-lint: disable=cancel-unsafe-acquire,unbounded-wait
        host, 1)
    await w.drain()
    w.close()
    return r
