# mnt-lint fixture: one violation per rule, no USED suppressions (the
# one disable below silences nothing — that is its violation).  The
# engine walk excludes tests/data, so this file is only ever linted by
# tests/test_lint.py passing it explicitly.
import asyncio
import os                                  # unused-import
import time


async def orphan():
    asyncio.create_task(work())            # orphan-task (discarded)
    t = asyncio.ensure_future(work())      # orphan-task (retired API)
    return t


async def blocking():
    time.sleep(1)                          # blocking-call-in-async
    open("/tmp/x")                         # blocking-io-in-async


async def swallows():
    try:
        await work()
    except Exception:                      # swallowed-cancellation
        pass


async def unreaped():
    t = asyncio.create_task(work())
    t.cancel()                             # cancel-without-await


async def undisciplined(lock):
    await lock.acquire()                   # lock-discipline
    lock.release()


async def unbounded():
    await asyncio.open_connection("h", 1)  # unbounded-wait


def leaky(obs):
    obs.span("stage")                      # span-not-closed


async def undrained(writer, chunks):
    for chunk in chunks:
        writer.write(chunk)                # write-without-drain
    await writer.drain()


async def faulty(faults, pick):
    await faults.point(pick())             # faultpoint-unregistered
    await faults.point("no.such.point")    # faultpoint-unregistered
    await faults.point("pg.restore")
    await faults.point("pg.restore")       # faultpoint-unregistered


class Torn:
    async def bump(self):
        cur = self.counter
        await work()
        self.counter = cur + 1             # atomic-section-broken


class Lockset:
    async def locked_add(self, item):
        async with self._lock:
            self.items = self.items + [item]

    async def locked_clear(self):
        async with self._lock:
            self.items = []

    async def racy(self):
        n = self.items
        await work()
        self.items = n + [1]               # lockset-inconsistent (+atomic)


async def cancel_leak(host):
    r, w = await asyncio.open_connection(host, 1)  # cancel-unsafe-acquire
    await w.drain()                        # (the unprotected await)
    w.close()
    return r


def stale():                               # mnt-lint: disable=style
    return None                            # ^ unused-suppression


def shadowed():
    return 1


def shadowed():                            # shadowed-def
    try:
        return 2
    except:                                # bare-except
        pass


def mutable(arg=[]):                       # mutable-default
    return arg
# the line above ends with a tab + this one is deliberately longer than the 100 column style limit ----
