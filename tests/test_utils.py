"""Unit tests for the utility layer.

Mirrors the reference's test/tst.common.js (pgStripMinor table-driven,
:15-76) and test/confParser.test.js (read/write/set, :85-125).
"""

import asyncio

import pytest

from manatee_tpu.utils import ConfFile, ExecError, pg_strip_minor, run, run_sync
from manatee_tpu.utils.confparser import quote_conf_value
from manatee_tpu.utils.validation import ConfigError, validate_config


# ---- pg_strip_minor (test/tst.common.js:15-76 table) ----

@pytest.mark.parametrize("full,major", [
    ("9.2.4", "9.2"),
    ("9.6.3", "9.6"),
    ("9.6", "9.6"),
    ("10.1", "10"),
    ("12.0", "12"),
    ("12", "12"),
    ("14.7", "14"),
])
def test_pg_strip_minor(full, major):
    assert pg_strip_minor(full) == major


@pytest.mark.parametrize("bad", ["", "abc", "9.x", "9..2", ".9", "9.", None, 9])
def test_pg_strip_minor_invalid(bad):
    with pytest.raises((ValueError, TypeError)):
        pg_strip_minor(bad)


def test_pg_strip_minor_pre10_needs_two_components():
    with pytest.raises(ValueError):
        pg_strip_minor("9")


# ---- ConfFile (test/confParser.test.js:85-125) ----

SAMPLE = """\
# PostgreSQL sample
listen_addresses = '*'   # bind all
port = 5432
wal_level = hot_standby
synchronous_commit = remote_write
hot_standby on
shared_buffers = '128MB'
"""


def test_conf_read(tmp_path):
    p = tmp_path / "postgresql.conf"
    p.write_text(SAMPLE)
    conf = ConfFile.read(p)
    assert conf.get("port") == "5432"
    assert conf.get("wal_level") == "hot_standby"
    assert conf.get_unquoted("listen_addresses") == "*"
    # "key value" (no '=') form accepted, like postgres itself
    assert conf.get("hot_standby") == "on"


def test_conf_set_write_roundtrip(tmp_path):
    p = tmp_path / "postgresql.conf"
    p.write_text(SAMPLE)
    conf = ConfFile.read(p)
    conf.set("synchronous_standby_names", quote_conf_value("1 (\"peer\")"))
    conf.set("port", "10001")
    conf.write(p)
    again = ConfFile.read(p)
    assert again.get("port") == "10001"
    assert again.get_unquoted("synchronous_standby_names") == '1 ("peer")'


def test_conf_comment_inside_quotes():
    conf = ConfFile.from_text("primary_conninfo = 'host=x port=5 # not a comment'\n")
    assert conf.get_unquoted("primary_conninfo") == "host=x port=5 # not a comment"


def test_conf_delete_and_contains():
    conf = ConfFile({"a": "1", "b": "2"})
    assert "a" in conf
    conf.delete("a")
    assert "a" not in conf
    assert conf.get("a", "dflt") == "dflt"


def test_quote_conf_value_escapes():
    assert quote_conf_value("it's") == "'it''s'"


# ---- exec wrappers (lib/common.js:148-172 semantics) ----

def test_run_sync_ok():
    res = run_sync(["/bin/echo", "hello"])
    assert res.ok and res.stdout.strip() == "hello"
    assert res.duration_ms >= 0
    assert res.run_id > 0


def test_run_sync_failure_raises():
    with pytest.raises(ExecError) as ei:
        run_sync(["/bin/sh", "-c", "echo oops >&2; exit 3"])
    assert ei.value.result.returncode == 3
    assert "oops" in ei.value.result.stderr


def test_run_sync_empty_env():
    res = run_sync(["/bin/sh", "-c", "echo x$HOME"], empty_env=True)
    assert res.stdout.strip() == "x"


def test_run_async_ok_and_timeout():
    async def go():
        res = await run(["/bin/echo", "async"])
        assert res.stdout.strip() == "async"
        with pytest.raises(ExecError):
            await run(["/bin/sleep", "5"], timeout=0.2)
    asyncio.run(go())


def test_run_output_cap_kills_runaway_child():
    # forkexec-maxBuffer parity (lib/common.js:151): a child that floods
    # stdout must be killed and reported, not buffered without bound —
    # and wait() must not deadlock on the undrained pipes.
    with pytest.raises(ExecError) as ei:
        run_sync(["/bin/sh", "-c", "head -c 10000000 /dev/zero"],
                 max_output=1024 * 1024)
    assert "output exceeded" in ei.value.result.stderr


def test_run_async_stdin():
    async def go():
        res = await run(["/bin/cat"], stdin_data=b"piped")
        assert res.stdout == "piped"
    asyncio.run(go())


# ---- Prometheus exposition round-trip (utils/prom.py) ----
#
# A strict text-format parser: every non-comment line must be
# `name{labels} value`, every sample must be preceded by HELP+TYPE for
# its family, label values must unescape cleanly, and histogram
# families must carry consistent _bucket/_sum/_count triplets.

import re

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{(.*)\})? (\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> dict:
    """{family: {"type", "help", "samples": [(name, labels, value)]}};
    raises AssertionError on any strictness violation."""
    families: dict = {}
    pending_help: dict = {}
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in text.splitlines():
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, help_ = rest.partition(" ")
            pending_help[name] = help_
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            assert mtype in ("counter", "gauge", "histogram",
                             "summary", "untyped"), mtype
            assert name in pending_help, "TYPE before HELP for %s" % name
            assert name not in families, "duplicate TYPE for %s" % name
            families[name] = {"type": mtype,
                              "help": pending_help[name], "samples": []}
            continue
        assert not line.startswith("#"), "unknown comment: %r" % line
        m = _SAMPLE_RE.match(line)
        assert m, "malformed sample line: %r" % line
        name, _, labelstr, value = m.groups()
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[:-len(suffix)] in families:
                base = name[:-len(suffix)]
        assert base in families, "sample %r without TYPE/HELP" % name
        labels = {}
        if labelstr:
            pairs = _LABEL_RE.findall(labelstr)
            rebuilt = ",".join('%s="%s"' % (k, v) for k, v in pairs)
            assert rebuilt == labelstr, \
                "unparseable labels: %r" % labelstr
            for k, v in pairs:
                labels[k] = (v.replace("\\n", "\n")
                             .replace('\\"', '"').replace("\\\\", "\\"))
        float(value)    # must be numeric
        families[base]["samples"].append((name, labels, value))
    # histogram triplet consistency
    for fam, d in families.items():
        if d["type"] != "histogram":
            continue
        by_series: dict = {}
        for name, labels, value in d["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            s = by_series.setdefault(key, {"buckets": [], "sum": None,
                                           "count": None})
            if name.endswith("_bucket"):
                s["buckets"].append((labels["le"], float(value)))
            elif name.endswith("_sum"):
                s["sum"] = float(value)
            elif name.endswith("_count"):
                s["count"] = float(value)
        for key, s in by_series.items():
            assert s["sum"] is not None and s["count"] is not None, \
                "%s%r missing _sum/_count" % (fam, key)
            assert s["buckets"], "%s%r has no buckets" % (fam, key)
            assert s["buckets"][-1][0] == "+Inf", \
                "%s%r lacks a +Inf bucket" % (fam, key)
            counts = [c for _le, c in s["buckets"]]
            assert counts == sorted(counts), \
                "%s%r bucket counts not cumulative" % (fam, key)
            assert counts[-1] == s["count"], \
                "%s%r +Inf bucket != _count" % (fam, key)
    return families


def test_exposition_roundtrip_counters_gauges_and_escaping():
    from manatee_tpu.utils.prom import MetricsBuilder, label_str

    b = MetricsBuilder("m")
    b.metric("role", "gauge", "current role",
             [(label_str(role='we"ird\\peer\nname'), 1)])
    b.metric("writes_total", "counter", "durable writes", 7)
    fams = parse_exposition(b.render())
    assert fams["m_writes_total"]["type"] == "counter"
    (_n, labels, value), = fams["m_role"]["samples"]
    # escaping round-trips: the parser recovers the raw value
    assert labels["role"] == 'we"ird\\peer\nname'
    assert value == "1"


def test_exposition_counter_naming_fix_emits_alias():
    # the naming-convention fix: a counter registered WITHOUT _total is
    # exported under the conventional name AND the old name (deprecated
    # one-release alias), so existing scrapes keep working
    from manatee_tpu.utils.prom import MetricsBuilder

    b = MetricsBuilder("m")
    b.metric("mutations", "counter", "tree mutations", 3)
    fams = parse_exposition(b.render())
    assert fams["m_mutations_total"]["samples"][0][2] == "3"
    assert fams["m_mutations"]["samples"][0][2] == "3"
    assert "DEPRECATED" in fams["m_mutations"]["help"]


def test_exposition_histogram_triplets():
    from manatee_tpu.obs.metrics import Histogram
    from manatee_tpu.utils.prom import MetricsBuilder

    h = Histogram("op_duration_seconds", "op latency", ("op",),
                  buckets=(0.1, 1.0, 10.0))
    h.observe(0.05, op="get")
    h.observe(0.5, op="get")
    h.observe(99.0, op="get")
    h.observe(0.2, op="set")
    b = MetricsBuilder("m")
    b.histogram(h.name, h.help, h.buckets, h.series())
    fams = parse_exposition(b.render())
    fam = fams["m_op_duration_seconds"]
    assert fam["type"] == "histogram"
    get_buckets = {labels["le"]: value for name, labels, value
                   in fam["samples"]
                   if name.endswith("_bucket")
                   and labels.get("op") == "get"}
    assert get_buckets == {"0.1": "1", "1": "2", "10": "2",
                           "+Inf": "3"}
    sums = [float(v) for name, labels, v in fam["samples"]
            if name.endswith("_sum") and labels.get("op") == "get"]
    assert sums == [pytest.approx(99.55)]


def test_exposition_registry_render_is_strict():
    # whatever the process registry accumulates must always satisfy the
    # strict parser — this is the guard every new instrument runs under
    from manatee_tpu.obs import get_registry
    from manatee_tpu.utils.prom import MetricsBuilder

    reg = get_registry()
    reg.counter("roundtrip_test_total", "test counter",
                ("kind",)).inc(kind='tricky"value\\x')
    reg.histogram("roundtrip_test_duration_seconds",
                  "test histogram").observe(0.2)
    b = MetricsBuilder("manatee")
    reg.render_into(b)
    fams = parse_exposition(b.render())
    assert "manatee_roundtrip_test_total" in fams
    assert fams["manatee_roundtrip_test_duration_seconds"]["type"] == \
        "histogram"


def test_registry_naming_enforcement():
    from manatee_tpu.obs.metrics import Counter, Histogram, Registry

    with pytest.raises(ValueError):
        Counter("bad_counter", "no _total suffix")
    with pytest.raises(ValueError):
        Histogram("op_duration_ms", "durations must be _seconds")
    reg = Registry()
    c1 = reg.counter("x_total", "x")
    assert reg.counter("x_total", "x") is c1    # get-or-create
    with pytest.raises(ValueError):
        reg.gauge("x_total", "kind clash")


# ---- bunyan extra-field passthrough (utils/logutil.py) ----

def test_bunyan_generic_extra_passthrough():
    import json as _json
    import logging

    from manatee_tpu.utils.logutil import BunyanFormatter

    fmt = BunyanFormatter("test")
    logger = logging.getLogger("manatee.test.extra")
    rec = logger.makeRecord(
        "manatee.test.extra", logging.INFO, __file__, 1, "hello %s",
        ("world",), None,
        extra={"trace_id": "abcd1234", "peer": "p1", "span": "write",
               "rc": 0, "unjsonable": object()})
    out = _json.loads(fmt.format(rec))
    assert out["msg"] == "hello world"
    assert out["trace_id"] == "abcd1234"
    assert out["peer"] == "p1"
    assert out["span"] == "write"
    assert out["rc"] == 0
    assert isinstance(out["unjsonable"], str)   # repr()'d, not dropped
    # logging internals must NOT leak
    for internal in ("args", "levelno", "msecs", "exc_info"):
        assert internal not in out


def test_trace_filter_stamps_bound_trace():
    import json as _json
    import logging

    from manatee_tpu.obs import bind_trace
    from manatee_tpu.obs.trace import TraceLogFilter
    from manatee_tpu.utils.logutil import BunyanFormatter

    fmt = BunyanFormatter("test")
    filt = TraceLogFilter()
    logger = logging.getLogger("manatee.test.trace")
    rec = logger.makeRecord("manatee.test.trace", logging.INFO,
                            __file__, 1, "traced", (), None)
    with bind_trace("feedbeef12345678"):
        filt.filter(rec)
    out = _json.loads(fmt.format(rec))
    assert out["trace_id"] == "feedbeef12345678"


# ---- config validation ----

def test_validate_config():
    schema = {
        "type": "object",
        "required": ["ip"],
        "properties": {"ip": {"type": "string"}},
    }
    validate_config({"ip": "127.0.0.1"}, schema)
    with pytest.raises(ConfigError) as ei:
        validate_config({"ip": 5}, schema, name="sitter")
    assert "sitter" in str(ei.value)
