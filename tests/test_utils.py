"""Unit tests for the utility layer.

Mirrors the reference's test/tst.common.js (pgStripMinor table-driven,
:15-76) and test/confParser.test.js (read/write/set, :85-125).
"""

import asyncio

import pytest

from manatee_tpu.utils import ConfFile, ExecError, pg_strip_minor, run, run_sync
from manatee_tpu.utils.confparser import quote_conf_value
from manatee_tpu.utils.validation import ConfigError, validate_config


# ---- pg_strip_minor (test/tst.common.js:15-76 table) ----

@pytest.mark.parametrize("full,major", [
    ("9.2.4", "9.2"),
    ("9.6.3", "9.6"),
    ("9.6", "9.6"),
    ("10.1", "10"),
    ("12.0", "12"),
    ("12", "12"),
    ("14.7", "14"),
])
def test_pg_strip_minor(full, major):
    assert pg_strip_minor(full) == major


@pytest.mark.parametrize("bad", ["", "abc", "9.x", "9..2", ".9", "9.", None, 9])
def test_pg_strip_minor_invalid(bad):
    with pytest.raises((ValueError, TypeError)):
        pg_strip_minor(bad)


def test_pg_strip_minor_pre10_needs_two_components():
    with pytest.raises(ValueError):
        pg_strip_minor("9")


# ---- ConfFile (test/confParser.test.js:85-125) ----

SAMPLE = """\
# PostgreSQL sample
listen_addresses = '*'   # bind all
port = 5432
wal_level = hot_standby
synchronous_commit = remote_write
hot_standby on
shared_buffers = '128MB'
"""


def test_conf_read(tmp_path):
    p = tmp_path / "postgresql.conf"
    p.write_text(SAMPLE)
    conf = ConfFile.read(p)
    assert conf.get("port") == "5432"
    assert conf.get("wal_level") == "hot_standby"
    assert conf.get_unquoted("listen_addresses") == "*"
    # "key value" (no '=') form accepted, like postgres itself
    assert conf.get("hot_standby") == "on"


def test_conf_set_write_roundtrip(tmp_path):
    p = tmp_path / "postgresql.conf"
    p.write_text(SAMPLE)
    conf = ConfFile.read(p)
    conf.set("synchronous_standby_names", quote_conf_value("1 (\"peer\")"))
    conf.set("port", "10001")
    conf.write(p)
    again = ConfFile.read(p)
    assert again.get("port") == "10001"
    assert again.get_unquoted("synchronous_standby_names") == '1 ("peer")'


def test_conf_comment_inside_quotes():
    conf = ConfFile.from_text("primary_conninfo = 'host=x port=5 # not a comment'\n")
    assert conf.get_unquoted("primary_conninfo") == "host=x port=5 # not a comment"


def test_conf_delete_and_contains():
    conf = ConfFile({"a": "1", "b": "2"})
    assert "a" in conf
    conf.delete("a")
    assert "a" not in conf
    assert conf.get("a", "dflt") == "dflt"


def test_quote_conf_value_escapes():
    assert quote_conf_value("it's") == "'it''s'"


# ---- exec wrappers (lib/common.js:148-172 semantics) ----

def test_run_sync_ok():
    res = run_sync(["/bin/echo", "hello"])
    assert res.ok and res.stdout.strip() == "hello"
    assert res.duration_ms >= 0
    assert res.run_id > 0


def test_run_sync_failure_raises():
    with pytest.raises(ExecError) as ei:
        run_sync(["/bin/sh", "-c", "echo oops >&2; exit 3"])
    assert ei.value.result.returncode == 3
    assert "oops" in ei.value.result.stderr


def test_run_sync_empty_env():
    res = run_sync(["/bin/sh", "-c", "echo x$HOME"], empty_env=True)
    assert res.stdout.strip() == "x"


def test_run_async_ok_and_timeout():
    async def go():
        res = await run(["/bin/echo", "async"])
        assert res.stdout.strip() == "async"
        with pytest.raises(ExecError):
            await run(["/bin/sleep", "5"], timeout=0.2)
    asyncio.run(go())


def test_run_output_cap_kills_runaway_child():
    # forkexec-maxBuffer parity (lib/common.js:151): a child that floods
    # stdout must be killed and reported, not buffered without bound —
    # and wait() must not deadlock on the undrained pipes.
    with pytest.raises(ExecError) as ei:
        run_sync(["/bin/sh", "-c", "head -c 10000000 /dev/zero"],
                 max_output=1024 * 1024)
    assert "output exceeded" in ei.value.result.stderr


def test_run_async_stdin():
    async def go():
        res = await run(["/bin/cat"], stdin_data=b"piped")
        assert res.stdout == "piped"
    asyncio.run(go())


# ---- config validation ----

def test_validate_config():
    schema = {
        "type": "object",
        "required": ["ip"],
        "properties": {"ip": {"type": "string"}},
    }
    validate_config({"ip": "127.0.0.1"}, schema)
    with pytest.raises(ConfigError) as ei:
        validate_config({"ip": 5}, schema, name="sitter")
    assert "sitter" in str(ei.value)
