"""The JAX array engine (manatee_tpu/state/mc_array.py) against its
differential oracle.

The array engine re-implements the checker world as fixed-shape int32
vectors with pure jnp transition kernels; the ONLY thing that makes it
trustworthy is exact agreement with the replay-based Python explorer.
These tests pin the whole contract:

* the encoding is bijective with the canonical semantic-state quotient
  (encode -> decode == canon.world_canon, digests equal);
* matched-depth runs agree exactly — same reachable semantic states,
  same violation verdicts, same node/transition counters;
* the agreement survives every deliberate rule-weakening (Mutations),
  i.e. vectorization never trades away detection;
* the engine scales: the full depth sweeps run on the multi-device
  host-platform mesh in CI (modelcheck-smoke), where conftest pins
  XLA_FLAGS before jax loads.

Fast P=3 cases run in tier-1; the depth-5 sweep over every config and
the P=4 layouts are ``slow`` + ``modelcheck_smoke`` (the dedicated CI
job).
"""

import asyncio
import random

import pytest

from manatee_tpu.state import canon, mc_array, modelcheck
from manatee_tpu.state.mc_array import Mutations

# P=3 configs share one compiled engine; keeping tier-1 to a single
# layout caps the jit cost the suite pays
_FAST = ("deaths3", "rejoin", "freeze")
_SLOW = tuple(sorted(set(modelcheck.CONFIGS) - set(_FAST)))


def _walk_worlds(name, walks=8, steps=5, seed=11):
    """Root + fixed-seed random-walk worlds for a config."""
    cfg = modelcheck.CONFIGS[name]
    import manatee_tpu.state.machine as machine
    orig, machine._sleep = machine._sleep, modelcheck._fast_sleep
    loop = asyncio.new_event_loop()
    try:
        rng = random.Random(seed)
        for walk in range(walks):
            w = loop.run_until_complete(modelcheck._replay(cfg, ()))
            yield w, cfg
            for _ in range(steps):
                acts = w.enabled()
                if not acts:
                    break
                loop.run_until_complete(w.do(acts[rng.randrange(len(acts))]))
                if w.violations or w.store.violations:
                    break
                yield w, cfg
    finally:
        loop.close()
        machine._sleep = orig


@pytest.mark.parametrize("name", sorted(modelcheck.CONFIGS))
def test_encoding_roundtrip(name):
    """encode -> decode is the identity on the canonical quotient: the
    vector IS the semantic state, which is what licenses byte-level
    dedup standing in for digest dedup."""
    n = 0
    for w, cfg in _walk_worlds(name):
        vec = mc_array.encode_world(w, cfg)
        assert mc_array.decode_canon(vec, cfg) == canon.world_canon(w)
        assert mc_array.digest_vec(vec, cfg) == w.digest()
        n += 1
    assert n > 10


def test_slot_table_is_action_alphabet():
    """Every slot maps back to a well-formed explorer action, in
    enabled() enumeration order (the first-discovery contract)."""
    for name, cfg in modelcheck.CONFIGS.items():
        table = mc_array.slot_table(len(cfg.peers))
        assert len(set(table)) == len(table)
        acts = [mc_array._slot_action(cfg, s) for s in table]
        assert len(set(acts)) == len(acts)
        for slot, a in zip(table, acts):
            assert a[0] == slot[0]
            if len(a) > 1 and a[0] != "promote_async":
                assert a[1] in cfg.peers


@pytest.mark.parametrize("name", _FAST)
def test_differential_fast(name):
    """Tier-1 cut of the oracle contract: depth-3, P=3 configs."""
    pres, jres = mc_array.differential(modelcheck.CONFIGS[name], depth=3)
    assert pres.complete and jres.complete
    assert pres.states == jres.states > 10


@pytest.mark.slow
@pytest.mark.modelcheck_smoke
@pytest.mark.parametrize("name", sorted(modelcheck.CONFIGS))
def test_differential_sweep_depth(name):
    """The full contract at the pytest sweep depth: every config, both
    engines, exact agreement on states, verdicts and counters."""
    from tests.test_model_check import SWEEP_DEPTH
    pres, jres = mc_array.differential(modelcheck.CONFIGS[name],
                                       depth=SWEEP_DEPTH)
    assert pres.complete and jres.complete
    assert pres.ok and jres.ok, (pres.violations[:2], jres.violations[:2])
    assert (pres.states, pres.nodes, pres.transitions) \
        == (jres.states, jres.nodes, jres.transitions)


@pytest.mark.slow
@pytest.mark.modelcheck_smoke
@pytest.mark.parametrize("name,depth,mut", [
    ("behind", 4, Mutations(disable_xlog_guard=True)),
    ("freeze", 4, Mutations(ignore_freeze=True)),
    ("promote", 3, Mutations(deposed_keeps_primary=True)),
    ("deaths3", 3, Mutations(skip_gen_bump=True)),
], ids=["xlog", "freeze", "deposed", "genbump"])
def test_differential_under_mutations(name, depth, mut):
    """Weakened-rule agreement: with a bug seeded into BOTH engines the
    reachable states and the violation verdicts still match exactly —
    the strongest evidence vectorization didn't lose detection."""
    pres, jres = mc_array.differential(modelcheck.CONFIGS[name],
                                       depth=depth, mutations=mut)
    assert pres.violations and jres.violations


def test_divergence_is_a_hard_failure():
    """A seeded one-sided bug (mutating only the Python machine) must
    raise DifferentialError with a replayable minimized trace — the
    oracle cannot silently shrug off disagreement."""
    import manatee_tpu.state.machine as machine
    orig = machine.compare_lsn
    machine.compare_lsn = lambda a, b: 0       # python engine only
    try:
        with pytest.raises(mc_array.DifferentialError):
            mc_array.differential(modelcheck.CONFIGS["behind"], depth=3)
    finally:
        machine.compare_lsn = orig


@pytest.mark.slow
@pytest.mark.modelcheck_smoke
def test_multi_device_step_agrees():
    """When the host-platform mesh has >1 device the shard_map'd step
    must produce the same exploration as the single-device path did at
    the differential depths (the CI job runs this on 8 devices)."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("single-device mesh; scaling covered by bench")
    pres, jres = mc_array.differential(modelcheck.CONFIGS["rejoin"],
                                       depth=4)
    assert pres.states == jres.states
    assert pres.complete and jres.complete
