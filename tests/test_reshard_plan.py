"""Split-plan math + shard-map record tier (manatee_tpu/reshard/plan.py):
the partition invariants validate_map enforces, plan_split's rejection
matrix, the pure apply_split/with_range_state transforms, split-key
selection, and ShardMapStore CAS conflicts over a real CoordServer —
the seam that makes "exactly one authoritative owner per key" hold
when two writers race.
"""

import asyncio

import pytest

from manatee_tpu.reshard.plan import (
    FROZEN,
    KEY_MAX,
    KEY_MIN,
    SERVING,
    ShardMapError,
    ShardMapStore,
    SplitPlan,
    apply_split,
    bootstrap_map,
    choose_split_key,
    in_range,
    key_lt,
    owner_of,
    plan_split,
    range_for_shard,
    validate_map,
    with_range_state,
)


def _map(*ranges, epoch=0):
    return {"fmt": 1, "epoch": epoch, "ranges": list(ranges)}


def _rng(lo, hi, shard, state=SERVING):
    return {"lo": lo, "hi": hi, "shard": shard,
            "shardPath": "/manatee/" + shard, "state": state}


# ---- range primitives ----

def test_key_ordering_and_membership():
    assert key_lt("a", "b")
    assert key_lt("a", None)        # None is +inf
    assert not key_lt("b", "a")
    r = _rng("k40", "k80", "a")
    assert in_range(r, "k40")       # lo inclusive
    assert in_range(r, "k7f")
    assert not in_range(r, "k80")   # hi exclusive
    assert not in_range(r, "k3f")
    last = _rng("k80", KEY_MAX, "b")
    assert in_range(last, "zzzz")   # open top


def test_owner_of_and_range_for_shard():
    m = _map(_rng(KEY_MIN, "k80", "a"), _rng("k80", KEY_MAX, "b"))
    validate_map(m)
    assert owner_of(m, "")["shard"] == "a"
    assert owner_of(m, "k7f")["shard"] == "a"
    assert owner_of(m, "k80")["shard"] == "b"
    assert range_for_shard(m, "b")["lo"] == "k80"
    with pytest.raises(ShardMapError):
        range_for_shard(m, "nope")


# ---- the partition invariant ----

def test_validate_accepts_bootstrap_and_splits():
    validate_map(bootstrap_map("1", "/manatee/1"))
    validate_map(_map(_rng(KEY_MIN, "k40", "a"),
                      _rng("k40", "k80", "b"),
                      _rng("k80", KEY_MAX, "c", state=FROZEN)))


@pytest.mark.parametrize("bad", [
    "not-a-map",
    {"fmt": 2, "epoch": 0, "ranges": [_rng(KEY_MIN, KEY_MAX, "a")]},
    _map(),                                       # no ranges
    # gap: a's hi k40 != b's lo k50
    _map(_rng(KEY_MIN, "k40", "a"), _rng("k50", KEY_MAX, "b")),
    # overlap: a's hi k60 != b's lo k40
    _map(_rng(KEY_MIN, "k60", "a"), _rng("k40", KEY_MAX, "b")),
    # empty range: [k40, k40)
    _map(_rng(KEY_MIN, "k40", "a"), _rng("k40", "k40", "b"),
         _rng("k40", KEY_MAX, "c")),
    # one shard owning two ranges
    _map(_rng(KEY_MIN, "k40", "a"), _rng("k40", KEY_MAX, "a")),
    # first lo not the minimum key
    _map(_rng("k10", KEY_MAX, "a")),
    # last hi not +inf
    _map(_rng(KEY_MIN, "k80", "a")),
    # interior hi of None (a hole to +inf mid-map)
    _map(_rng(KEY_MIN, None, "a"), _rng("k80", KEY_MAX, "b")),
    # unknown state
    _map(_rng(KEY_MIN, KEY_MAX, "a", state="draining")),
], ids=["not-dict", "bad-fmt", "no-ranges", "gap", "overlap",
        "empty-range", "dup-owner", "bad-first-lo", "bad-last-hi",
        "interior-inf", "bad-state"])
def test_validate_rejects_non_partitions(bad):
    with pytest.raises(ShardMapError):
        validate_map(bad)


# ---- plan_split's rejection matrix ----

def test_plan_split_happy_path_and_roundtrip():
    m = bootstrap_map("1", "/manatee/1")
    plan = plan_split(m, "1", ("1", "2"), "k80", "/manatee/2")
    assert (plan.source, plan.target) == ("1", "2")
    assert plan.split_key == "k80"
    assert plan.source_range["lo"] == KEY_MIN
    # order of --into doesn't matter: the non-source name is target
    plan2 = plan_split(m, "1", ("2", "1"), "k80", "/manatee/2")
    assert plan2.target == "2"
    assert SplitPlan.from_dict(plan.to_dict()) == plan


def test_plan_split_rejections():
    m = bootstrap_map("1", "/manatee/1")
    with pytest.raises(ShardMapError, match="same shard twice"):
        plan_split(m, "1", ("1", "1"), "k80", "/manatee/1")
    with pytest.raises(ShardMapError, match="must be the source"):
        plan_split(m, "1", ("2", "3"), "k80", "/manatee/2")
    # split key not strictly inside: at lo, the low half is empty
    with pytest.raises(ShardMapError, match="strictly inside"):
        plan_split(m, "1", ("1", "2"), KEY_MIN, "/manatee/2")
    # target already owns a range
    split = _map(_rng(KEY_MIN, "k80", "1"), _rng("k80", KEY_MAX, "2"))
    with pytest.raises(ShardMapError, match="already owns"):
        plan_split(split, "1", ("1", "2"), "k40", "/manatee/2")
    # key outside the (now bounded) source range
    with pytest.raises(ShardMapError, match="strictly inside"):
        plan_split(split, "1", ("1", "3"), "k90", "/manatee/3")
    # a cutover already in flight freezes planning
    frozen = _map(_rng(KEY_MIN, KEY_MAX, "1", state=FROZEN))
    with pytest.raises(ShardMapError, match="in flight"):
        plan_split(frozen, "1", ("1", "2"), "k80", "/manatee/2")


# ---- the pure transforms ----

def test_apply_split_partitions_and_bumps_epoch():
    m = bootstrap_map("1", "/manatee/1")
    plan = plan_split(m, "1", ("1", "2"), "k80", "/manatee/2")
    out = apply_split(m, plan, state=SERVING)
    validate_map(out)
    assert out["epoch"] == m["epoch"] + 1
    assert [r["shard"] for r in out["ranges"]] == ["1", "2"]
    assert owner_of(out, "k7f")["shard"] == "1"
    assert owner_of(out, "k80")["shard"] == "2"
    # source map untouched (pure transform)
    assert len(m["ranges"]) == 1


def test_apply_split_refuses_moved_goalposts():
    m = bootstrap_map("1", "/manatee/1")
    plan = plan_split(m, "1", ("1", "2"), "k80", "/manatee/2")
    # the map changed underneath: source range shrank past the key
    shrunk = _map(_rng(KEY_MIN, "k40", "1"), _rng("k40", KEY_MAX, "3"),
                  epoch=3)
    with pytest.raises(ShardMapError, match="no longer inside"):
        apply_split(shrunk, plan, state=SERVING)


def test_with_range_state_round_trips():
    m = _map(_rng(KEY_MIN, "k80", "a"), _rng("k80", KEY_MAX, "b"))
    frozen = with_range_state(m, "a", FROZEN)
    assert frozen["epoch"] == 1
    assert range_for_shard(frozen, "a")["state"] == FROZEN
    assert range_for_shard(frozen, "b")["state"] == SERVING
    back = with_range_state(frozen, "a", SERVING)
    assert range_for_shard(back, "a")["state"] == SERVING
    assert back["epoch"] == 2


def test_choose_split_key_median_excludes_lo():
    rng = _rng("k10", "k90", "a")
    # k10 == lo is excluded (it would make the low half empty);
    # out-of-range and non-string samples ignored; dupes collapse
    keys = ["k10", "k20", "k20", "k40", "k60", "k95", None, 7]
    assert choose_split_key(keys, rng) == "k40"
    with pytest.raises(ShardMapError, match="pass --at"):
        choose_split_key(["k10", "k95"], rng)


# ---- ShardMapStore over a real coordination server ----

def _store_world(tmp_path):
    """(server, coord, store) against a throwaway CoordServer."""
    from manatee_tpu.coord.client import NetCoord
    from manatee_tpu.coord.server import CoordServer

    async def go():
        server = CoordServer(port=0, tick=0.05,
                             data_dir=str(tmp_path / "coord"))
        await server.start()
        coord = NetCoord("127.0.0.1", server.port, session_timeout=20)
        await coord.connect()
        return server, coord, ShardMapStore(coord)
    return go


def test_store_init_load_cas_conflict(tmp_path):
    async def go():
        server, coord, store = await _store_world(tmp_path)()
        try:
            with pytest.raises(ShardMapError, match="shardmap init"):
                await store.load()
            await store.init("1", "/manatee/1")
            with pytest.raises(ShardMapError, match="already exists"):
                await store.init("1", "/manatee/1")
            m, ver = await store.load()
            assert m["epoch"] == 0 and len(m["ranges"]) == 1

            # two writers race: the second CAS at the stale version
            # must lose — this IS the one-authoritative-map invariant
            plan = plan_split(m, "1", ("1", "2"), "k80", "/manatee/2")
            ver2 = await store.cas(
                apply_split(m, plan, state=FROZEN), ver)
            assert ver2 != ver
            with pytest.raises(ShardMapError, match="stale"):
                await store.cas(with_range_state(m, "1", FROZEN), ver)
            m2, _ = await store.load()
            assert owner_of(m2, "k80")["shard"] == "2"
        finally:
            await coord.close()
            await server.stop()
    asyncio.run(go())


def test_store_record_create_update_conflict(tmp_path):
    async def go():
        server, coord, store = await _store_world(tmp_path)()
        try:
            rec, ver = await store.load_record()
            assert rec is None and ver == -1
            ver = await store.write_record({"step": "plan"}, ver)
            # a second orchestrator trying a fresh create loses
            with pytest.raises(ShardMapError, match="resume "):
                await store.write_record({"step": "plan"}, -1)
            rec, ver2 = await store.load_record()
            assert rec["step"] == "plan"
            ver3 = await store.write_record({"step": "seed"}, ver2)
            # ...and a stale-version update loses too
            with pytest.raises(ShardMapError, match="two resharders"):
                await store.write_record({"step": "seed"}, ver2)
            assert ver3 != ver2
            await store.delete_record()
            rec, ver = await store.load_record()
            assert rec is None and ver == -1
            await store.delete_record()     # idempotent
        finally:
            await coord.close()
            await server.stop()
    asyncio.run(go())
