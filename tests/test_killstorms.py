"""Kill-storm and pairwise-death scenarios.

Reference parity: test/integ.test.js — pairwise instantaneous deaths
(:1285, :1505, :1720), sequenced deaths (:1925, :2208), and the
MANATEE_207_* no-wait kill storms (:3158-3671).  Convergence budget 30s
per transition (relaxed for full-suite load)."""

import asyncio

from tests.harness import ClusterHarness
from tests.test_integration import converged


def run(coro):
    return asyncio.run(coro)


def test_primary_and_sync_die_together(tmp_path):
    """Pairwise instantaneous death (integ.test.js:1285): only the async
    survives; it cannot take over (it is not the sync), so the cluster
    holds until a peer returns; then the SYNC's return enables takeover.
    We restart both dead peers and require reconvergence."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)
            gen0 = (await cluster.cluster_state())["generation"]

            primary.kill()
            sync.kill()
            # the async must NOT take over
            await asyncio.sleep(cluster.session_timeout + 2.0)
            st = await cluster.cluster_state()
            assert st["primary"]["id"] == primary.ident
            assert st["generation"] == gen0

            # both return; the sync resumes its role, then (with its
            # intact data) the cluster simply resumes
            primary.start()
            sync.start()
            st = await cluster.wait_topology(primary=primary, sync=sync,
                                             timeout=60)
            assert st["generation"] == gen0
            await cluster.wait_writable(primary, "after-double-death",
                                        timeout=60)
        finally:
            await cluster.stop()
    run(go())


def test_sync_and_async_die_together(tmp_path):
    """Pairwise death (integ.test.js:1505): primary survives alone and
    the cluster holds (it cannot appoint a sync with nobody alive).
    When the dead peers return with intact data, the original topology
    resumes — no generation churn — and writes work again."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)
            gen0 = (await cluster.cluster_state())["generation"]

            sync.kill()
            asyncs[0].kill()
            await asyncio.sleep(cluster.session_timeout + 2.0)
            st = await cluster.cluster_state()
            assert st["primary"]["id"] == primary.ident  # no takeover

            sync.start()
            asyncs[0].start()
            # the primary never changes (it never died); whether the
            # generation bumps depends on whether the returning peers'
            # sessions lapsed before they re-registered (a replacement
            # sync appointment is a legitimate bump)
            def recovered(s):
                others = {sync.ident, asyncs[0].ident}
                return (s["primary"]["id"] == primary.ident
                        and s.get("sync") is not None
                        and s["sync"]["id"] in others
                        and {a["id"] for a in s.get("async") or []}
                        == others - {s["sync"]["id"]})
            st = await cluster.wait_for(recovered, 60,
                                        "pair-death recovery")
            assert st["generation"] >= gen0
            assert st["deposed"] == []
            await cluster.wait_writable(primary, "after-pair-death",
                                        timeout=60)
        finally:
            await cluster.stop()
    run(go())


def test_sync_killed_before_replication_established(tmp_path):
    """MANATEE_212 (integ.test.js:2491, :2737): kill the sync the moment
    it is appointed, before replication is established; the primary's
    catch-up wait must not wedge — it appoints a replacement and the
    cluster becomes writable."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            # kill the sync as soon as the bootstrap names it, without
            # waiting for catch-up/writability
            st = await cluster.wait_for(
                lambda s: s.get("sync") is not None, 60, "bootstrap")
            sync = cluster.peer_by_id(st["sync"]["id"])
            sync.kill()

            st = await cluster.wait_for(
                lambda s: s.get("sync") is not None
                and s["sync"]["id"] != sync.ident
                and s["generation"] >= 1,
                60, "replacement sync")
            primary = cluster.peer_by_id(st["primary"]["id"])
            await cluster.wait_writable(primary, "post-212", timeout=60)
        finally:
            await cluster.stop()
    run(go())


def test_sequenced_kill_storm(tmp_path):
    """MANATEE_207-style storm (integ.test.js:3158-3671): kill each
    peer in sequence with no waiting between kills, restart them all,
    and require convergence to a writable cluster."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)

            # storm: no waits between kills
            for p in (asyncs[0], primary, sync):
                p.kill()
            for p in (primary, sync, asyncs[0]):
                p.start()

            st = await cluster.wait_for(
                lambda s: s.get("sync") is not None, 60,
                "post-storm topology")
            new_primary = cluster.peer_by_id(st["primary"]["id"])
            await cluster.wait_writable(new_primary, "after-storm",
                                        timeout=60)
            # no data loss of synchronously-committed writes
            res = await new_primary.pg_query({"op": "select"})
            assert "setup-write" in res["rows"]
        finally:
            await cluster.stop()
    run(go())


def test_primary_and_async_die_together(tmp_path):
    """Pairwise instantaneous death, third combination
    (integ.test.js:1720): the sync takes over immediately (the async's
    absence does not gate takeover), the old primary is deposed, and
    when both dead peers return the deposed one stays deposed while the
    async rejoins the chain."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)
            gen0 = (await cluster.cluster_state())["generation"]

            primary.kill()
            asyncs[0].kill()
            # the sync takes over and deposes the old primary — but with
            # no standby available it correctly HOLDS writes (read-only
            # until a new sync catches up; taking writes now would risk
            # loss on the next failover)
            st = await cluster.wait_topology(primary=sync, timeout=60)
            assert st["generation"] == gen0 + 1
            assert [d["id"] for d in st["deposed"]] == [primary.ident]

            primary.start()
            asyncs[0].start()
            # the async rejoins (as the new sync or async); the deposed
            # ex-primary must NOT re-enter the replication chain
            def recovered(s):
                members = {s["primary"]["id"]}
                if s.get("sync"):
                    members.add(s["sync"]["id"])
                members.update(a["id"] for a in s.get("async") or [])
                return (s["primary"]["id"] == sync.ident
                        and asyncs[0].ident in members
                        and primary.ident not in members
                        and [d["id"] for d in s["deposed"]]
                        == [primary.ident])
            st = await cluster.wait_for(recovered, 60,
                                        "pa-death recovery")
            await cluster.wait_writable(sync, "after-pa-recovery",
                                        timeout=60)
        finally:
            await cluster.stop()
    run(go())


def test_sequenced_deaths_primary_then_primary(tmp_path):
    """First sequenced-death ordering (integ.test.js:1925): kill the
    primary, wait for the takeover to complete, then kill the NEW
    primary; the chain must fail over twice, deposing both."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=4)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster, n=4)

            primary.kill()
            st = await cluster.wait_topology(primary=sync, timeout=60)
            await cluster.wait_writable(sync, "after-first", timeout=60)
            second_sync = cluster.peer_by_id(st["sync"]["id"])

            sync.kill()
            st = await cluster.wait_topology(primary=second_sync,
                                             timeout=60)
            deposed = {d["id"] for d in st["deposed"]}
            assert deposed == {primary.ident, sync.ident}
            await cluster.wait_writable(second_sync, "after-second",
                                        timeout=60)
            # synchronously-committed data survived both failovers
            res = await second_sync.pg_query({"op": "select"})
            assert "setup-write" in res["rows"]
            assert "after-first" in res["rows"]
        finally:
            await cluster.stop()
    run(go())


def test_sequenced_deaths_sync_then_sync(tmp_path):
    """Second sequenced-death ordering (integ.test.js:2208): kill the
    sync, wait for its replacement, then kill the replacement; each
    death appoints the next async with a generation bump and no
    deposals (the primary never changed)."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=4)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster, n=4)

            sync.kill()
            st = await cluster.wait_for(
                lambda s: s.get("sync") is not None
                and s["sync"]["id"] == asyncs[0].ident,
                60, "first replacement sync")
            await cluster.wait_writable(primary, "after-sync-death-1",
                                        timeout=60)

            asyncs[0].kill()
            st = await cluster.wait_for(
                lambda s: s.get("sync") is not None
                and s["sync"]["id"] == asyncs[1].ident,
                60, "second replacement sync")
            assert st["primary"]["id"] == primary.ident
            assert st["deposed"] == []
            await cluster.wait_writable(primary, "after-sync-death-2",
                                        timeout=60)
        finally:
            await cluster.stop()
    run(go())


def test_storm_restart_reverse_order(tmp_path):
    """MANATEE_207 variant: kill every peer with no waiting, restart in
    REVERSE join order (async first) — the cold-start logic must not
    depend on the original ordering, and synchronously-committed writes
    must survive."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)

            for p in (primary, sync, asyncs[0]):
                p.kill()
            for p in (asyncs[0], sync, primary):
                p.start()

            # the pre-storm state node survives in coordd, so a static
            # topology predicate would match the STALE snapshot; follow
            # the state's current primary until a write lands
            import time as _time
            deadline = _time.monotonic() + 90
            new_primary = None
            while _time.monotonic() < deadline:
                st = await cluster.cluster_state()
                if st and st.get("sync") is not None:
                    cand = cluster.peer_by_id(st["primary"]["id"])
                    try:
                        res = await cand.pg_query(
                            {"op": "insert",
                             "value": "after-reverse-storm",
                             "timeout": 3.0}, 5.0)
                        if res.get("ok"):
                            new_primary = cand
                            break
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        pass
                await asyncio.sleep(0.25)
            assert new_primary is not None, \
                "never writable after reverse storm"
            res = await new_primary.pg_query({"op": "select"})
            assert "setup-write" in res["rows"]
        finally:
            await cluster.stop()
    run(go())


def test_storm_primary_flap(tmp_path):
    """MANATEE_207 variant: the primary dies and returns twice in rapid
    succession with no waiting between actions; the cluster must settle
    writable without wedging on the flapping peer's stale sessions."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)

            primary.kill()
            primary.start()
            primary.kill()
            primary.start()

            # depending on kill/session-timeout interleaving the flapper
            # either keeps its role or is deposed mid-flap; follow the
            # state's CURRENT primary until a synchronous write lands
            import time as _time
            deadline = _time.monotonic() + 90
            new_primary = None
            while _time.monotonic() < deadline:
                st = await cluster.cluster_state()
                if st and st.get("sync") is not None:
                    cand = cluster.peer_by_id(st["primary"]["id"])
                    try:
                        res = await cand.pg_query(
                            {"op": "insert", "value": "after-flap",
                             "timeout": 3.0}, 5.0)
                        if res.get("ok"):
                            new_primary = cand
                            break
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        pass
                await asyncio.sleep(0.25)
            assert new_primary is not None, "never writable after flap"
            res = await new_primary.pg_query({"op": "select"})
            assert "setup-write" in res["rows"]
        finally:
            await cluster.stop()
    run(go())


def test_coordd_leader_dies_during_failover(tmp_path):
    """Coordination outage DURING a database failover (VERDICT r1 #6):
    the PG primary and the coordd ensemble leader are SIGKILLed at the
    same instant; peers must re-session to the promoted coordination
    survivor and still complete the database takeover."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3, n_coord=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)
            gen0 = (await cluster.cluster_state())["generation"]

            leader = await cluster.coord_leader_idx()
            primary.kill()
            cluster.kill_coordd(leader)

            st = await cluster.wait_topology(primary=sync, timeout=90)
            # coord failover wipes sessions, so the takeover may land in
            # one bump (async re-registered in time) or two (sync=None
            # takeover, then replacement-sync adoption)
            assert st["generation"] >= gen0 + 1
            assert [d["id"] for d in st["deposed"]] == [primary.ident]
            await cluster.wait_writable(sync, "after-dual-outage",
                                        timeout=90)
            res = await sync.pg_query({"op": "select"})
            assert "setup-write" in res["rows"]
        finally:
            await cluster.stop()
    run(go())


def test_storm_with_full_daemon_trio(tmp_path):
    """VERDICT r4 #3: the reference fixture runs sitter + backupserver
    + snapshotter on every peer in every scenario
    (testManatee.js:99-398).  Run a takeover + kill storm with the
    trio: snapshots must keep flowing and GC to the keep-N bound
    across primary deaths, and the stuck-snapshot fatal alarm must
    stay silent on healthy storage."""
    from manatee_tpu.storage import DirBackend
    from manatee_tpu.storage.base import is_epoch_ms_snapshot

    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3, snapshotter=True,
                                 snapshot_poll=0.5, snapshot_number=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)

            # takeover with the trio running
            primary.kill()
            st = await cluster.wait_topology(primary=sync, timeout=60)
            await cluster.wait_writable(sync, "storm-trio-1",
                                        timeout=60)

            # storm: everyone dies at once, everyone returns (the
            # snapshotters come back with their peers)
            for p in (sync, asyncs[0]):
                p.kill()
            for p in (primary, sync, asyncs[0]):
                p.start()
            st = await cluster.wait_for(
                lambda s: s.get("sync") is not None, 60,
                "post-storm topology")
            new_primary = cluster.peer_by_id(st["primary"]["id"])
            await cluster.wait_writable(new_primary, "storm-trio-2",
                                        timeout=60)

            # let several snapshot + GC cycles run on the converged
            # cluster, then check every live peer's snapshot stream
            await asyncio.sleep(3.0)
            for peer in cluster.peers:
                be = DirBackend(str(peer.root / "store"))
                if not await be.exists("manatee/pg"):
                    continue    # rebuilt/deposed peer without data yet
                snaps = [s for s in await be.list_snapshots("manatee/pg")
                         if is_epoch_ms_snapshot(s.name)]
                # snapshots flowed...
                assert snaps, "%s took no snapshots" % peer.name
                # ...and GC held the keep-N bound (small slack for the
                # cycle in flight)
                assert len(snaps) <= cluster.snapshot_number + 2, \
                    "%s: %d snapshots > keep-%d" \
                    % (peer.name, len(snaps), cluster.snapshot_number)
                slog = (peer.root / "snapshotter.log").read_text()
                assert "snapshots are stuck" not in slog, \
                    "%s: spurious stuck-snapshot alarm" % peer.name
                assert "manual intervention" not in slog
        finally:
            await cluster.stop()
    run(go())
