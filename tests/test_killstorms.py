"""Kill-storm and pairwise-death scenarios.

Reference parity: test/integ.test.js — pairwise instantaneous deaths
(:1285, :1505, :1720), sequenced deaths (:1925, :2208), and the
MANATEE_207_* no-wait kill storms (:3158-3671).  Convergence budget 30s
per transition (relaxed for full-suite load)."""

import asyncio

from tests.harness import ClusterHarness
from tests.test_integration import converged


def run(coro):
    return asyncio.run(coro)


def test_primary_and_sync_die_together(tmp_path):
    """Pairwise instantaneous death (integ.test.js:1285): only the async
    survives; it cannot take over (it is not the sync), so the cluster
    holds until a peer returns; then the SYNC's return enables takeover.
    We restart both dead peers and require reconvergence."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)
            gen0 = (await cluster.cluster_state())["generation"]

            primary.kill()
            sync.kill()
            # the async must NOT take over
            await asyncio.sleep(cluster.session_timeout + 2.0)
            st = await cluster.cluster_state()
            assert st["primary"]["id"] == primary.ident
            assert st["generation"] == gen0

            # both return; the sync resumes its role, then (with its
            # intact data) the cluster simply resumes
            primary.start()
            sync.start()
            st = await cluster.wait_topology(primary=primary, sync=sync,
                                             timeout=60)
            assert st["generation"] == gen0
            await cluster.wait_writable(primary, "after-double-death",
                                        timeout=60)
        finally:
            await cluster.stop()
    run(go())


def test_sync_and_async_die_together(tmp_path):
    """Pairwise death (integ.test.js:1505): primary survives alone and
    the cluster holds (it cannot appoint a sync with nobody alive).
    When the dead peers return with intact data, the original topology
    resumes — no generation churn — and writes work again."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)
            gen0 = (await cluster.cluster_state())["generation"]

            sync.kill()
            asyncs[0].kill()
            await asyncio.sleep(cluster.session_timeout + 2.0)
            st = await cluster.cluster_state()
            assert st["primary"]["id"] == primary.ident  # no takeover

            sync.start()
            asyncs[0].start()
            # the primary never changes (it never died); whether the
            # generation bumps depends on whether the returning peers'
            # sessions lapsed before they re-registered (a replacement
            # sync appointment is a legitimate bump)
            def recovered(s):
                others = {sync.ident, asyncs[0].ident}
                return (s["primary"]["id"] == primary.ident
                        and s.get("sync") is not None
                        and s["sync"]["id"] in others
                        and {a["id"] for a in s.get("async") or []}
                        == others - {s["sync"]["id"]})
            st = await cluster.wait_for(recovered, 60,
                                        "pair-death recovery")
            assert st["generation"] >= gen0
            assert st["deposed"] == []
            await cluster.wait_writable(primary, "after-pair-death",
                                        timeout=60)
        finally:
            await cluster.stop()
    run(go())


def test_sync_killed_before_replication_established(tmp_path):
    """MANATEE_212 (integ.test.js:2491, :2737): kill the sync the moment
    it is appointed, before replication is established; the primary's
    catch-up wait must not wedge — it appoints a replacement and the
    cluster becomes writable."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            # kill the sync as soon as the bootstrap names it, without
            # waiting for catch-up/writability
            st = await cluster.wait_for(
                lambda s: s.get("sync") is not None, 60, "bootstrap")
            sync = cluster.peer_by_id(st["sync"]["id"])
            sync.kill()

            st = await cluster.wait_for(
                lambda s: s.get("sync") is not None
                and s["sync"]["id"] != sync.ident
                and s["generation"] >= 1,
                60, "replacement sync")
            primary = cluster.peer_by_id(st["primary"]["id"])
            await cluster.wait_writable(primary, "post-212", timeout=60)
        finally:
            await cluster.stop()
    run(go())


def test_sequenced_kill_storm(tmp_path):
    """MANATEE_207-style storm (integ.test.js:3158-3671): kill each
    peer in sequence with no waiting between kills, restart them all,
    and require convergence to a writable cluster."""
    async def go():
        cluster = ClusterHarness(tmp_path, n_peers=3)
        try:
            await cluster.start()
            primary, sync, asyncs = await converged(cluster)

            # storm: no waits between kills
            for p in (asyncs[0], primary, sync):
                p.kill()
            for p in (primary, sync, asyncs[0]):
                p.start()

            st = await cluster.wait_for(
                lambda s: s.get("sync") is not None, 60,
                "post-storm topology")
            new_primary = cluster.peer_by_id(st["primary"]["id"])
            await cluster.wait_writable(new_primary, "after-storm",
                                        timeout=60)
            # no data loss of synchronously-committed writes
            res = await new_primary.pg_query({"op": "select"})
            assert "setup-write" in res["rows"]
        finally:
            await cluster.stop()
    run(go())
