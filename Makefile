# Build/lint/test harness (reference: Makefile + tools/catest;
# `make test` runs the whole suite, `make lint` style checks,
# `make devcluster` generates a local 3-peer config tree).

PYTHON ?= python3

# a failed recipe must not leave a fresh-looking partial target behind
.DELETE_ON_ERROR:

.PHONY: all test test-unit test-integ test-integ-postgres lint \
    lint-fast bench flamegraph \
    devcluster native clean modelcheck modelcheck-jax chaos \
    chaos-postgres chaos-partition man \
    train-health eval-recorded

all: lint test

test:
	$(PYTHON) -m pytest tests/ -x -q

test-unit:
	$(PYTHON) -m pytest tests/ -x -q --ignore=tests/test_integration.py \
	    --ignore=tests/test_killstorms.py --ignore=tests/test_adm_live.py

test-integ:
	$(PYTHON) -m pytest tests/test_integration.py tests/test_killstorms.py \
	    tests/test_adm_live.py -x -q

# the same fault-injection tier, but every peer's database runs through
# the real PostgresEngine against the fakepg binaries (MANATEE_ENGINE
# re-routes the harness; tests/harness.py)
test-integ-postgres:
	MANATEE_ENGINE=postgres $(PYTHON) -m pytest \
	    tests/test_integration.py tests/test_killstorms.py \
	    tests/test_adm_live.py -x -q

lint:
	$(PYTHON) -m compileall -q manatee_tpu tools/mkdevcluster bench.py \
	    __graft_entry__.py
	$(PYTHON) tools/lint --suppression-baseline .mnt-lint-baseline.json

# pre-commit loop: only git-changed files, content-hash result cache —
# the tree-wide CFG construction cost drops to the files you touched
lint-fast:
	$(PYTHON) tools/lint --changed --cache

# exhaustive interleaving exploration of the cluster state machine
# (deeper than the bounded sweep `make test` runs)
modelcheck:
	$(PYTHON) -m manatee_tpu.state.modelcheck --config all --depth 6

# the same sweep two plies deeper on the JAX array engine
# (docs/modelcheck.md); exact agreement with the python oracle is
# enforced by tests/test_mc_array.py
modelcheck-jax:
	JAX_PLATFORMS=cpu $(PYTHON) -m manatee_tpu.state.modelcheck \
	    --config all --depth 8 --engine jax --progress

# unscripted randomized storm against real processes + the real CLI
# (MANATEE_CHAOS_SECONDS / MANATEE_CHAOS_SEED to vary)
chaos:
	MANATEE_CHAOS=1 $(PYTHON) -m pytest tests/test_chaos.py \
	    tests/test_slo_live.py -x -q -s

chaos-postgres:
	MANATEE_CHAOS=1 MANATEE_ENGINE=postgres \
	    $(PYTHON) -m pytest tests/test_chaos.py -x -q -s

# the same storm + live asymmetric network partitions armed through
# `manatee-adm fault` (docs/fault-injection.md), with the continuous
# split-brain probe
chaos-partition:
	MANATEE_CHAOS=1 MANATEE_CHAOS_PARTITION=1 \
	    $(PYTHON) -m pytest tests/test_chaos.py \
	    tests/test_slo_live.py -x -q -s

# reproduces the packaged weights: synthetic degradation batches plus
# healthy-stretch negatives from three recorded chaos runs (seeds 1-3;
# seeds 4-5 + the hang run stay held out — eval numbers in PARITY.md).
# NB: run with PYTHONPATH=$(CURDIR) JAX_PLATFORMS=cpu on dev images
# where the default PYTHONPATH pulls in an accelerator sitecustomize.
train-health:
	$(PYTHON) -m manatee_tpu.health.train \
	    --mix-recorded tests/data/recorded-chaos-r4/*.jsonl \
	    tests/data/recorded-chaos-s2/*.jsonl \
	    tests/data/recorded-chaos-s3/*.jsonl

# evaluate the packaged predictor weights on recorded telemetry dumps
# (telemetry.jsonl files an integration/chaos run leaves in its tmp
# dirs); TRACES=<files> overrides the default glob
eval-recorded:
	$(PYTHON) -m manatee_tpu.health.train --recorded \
	    $(or $(TRACES),$(wildcard /tmp/pytest-of-$(shell id -un)/pytest-*/test_*/peer*/telemetry.jsonl))

bench:
	$(PYTHON) bench.py

# folded stacks (GET /profile, `manatee-adm profile`) -> SVG
# (docs/observability.md has the worked capture-to-graph example)
flamegraph:
	@test -n "$(FOLDED)" || { echo "usage: make flamegraph \
FOLDED=stacks.folded [SVG=out.svg]" >&2; exit 2; }
	$(PYTHON) tools/flamegraph $(FOLDED) -o $(or $(SVG),flamegraph.svg)
	@echo wrote $(or $(SVG),flamegraph.svg)

# roff man pages generated from the markdown source (reference:
# Makefile:68-79)
man: man/man1/manatee-adm.1 man/man1/manatee-adm-trace.1 \
		man/man1/manatee-sitter.1 man/man1/manatee-prober.1 \
		man/man1/manatee-adm-slo.1 man/man1/manatee-adm-profile.1 \
		man/man1/manatee-adm-tasks.1 man/man1/manatee-adm-incident.1 \
		man/man1/manatee-router.1 man/man1/manatee-adm-reshard.1
man/man1/manatee-adm.1: docs/man/manatee-adm.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-adm.md > $@
man/man1/manatee-adm-trace.1: docs/man/manatee-adm-trace.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-adm-trace.md > $@
man/man1/manatee-sitter.1: docs/man/manatee-sitter.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-sitter.md > $@
man/man1/manatee-prober.1: docs/man/manatee-prober.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-prober.md > $@
man/man1/manatee-adm-slo.1: docs/man/manatee-adm-slo.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-adm-slo.md > $@
man/man1/manatee-adm-profile.1: docs/man/manatee-adm-profile.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-adm-profile.md > $@
man/man1/manatee-adm-tasks.1: docs/man/manatee-adm-tasks.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-adm-tasks.md > $@
man/man1/manatee-adm-incident.1: docs/man/manatee-adm-incident.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-adm-incident.md > $@
man/man1/manatee-router.1: docs/man/manatee-router.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-router.md > $@
man/man1/manatee-adm-reshard.1: docs/man/manatee-adm-reshard.md tools/md2man
	mkdir -p man/man1
	$(PYTHON) tools/md2man docs/man/manatee-adm-reshard.md > $@

devcluster:
	$(PYTHON) tools/mkdevcluster -n 3

native:
	$(MAKE) -C native

clean:
	rm -rf devconfs .pytest_cache
	find . -name __pycache__ -type d -exec rm -rf {} +
