"""manatee-snapshotter — periodic storage snapshots of the PG dataset.

Reference parity: snapshotter.js (:119-127) + lib/snapShotter.js
semantics (see manatee_tpu.snapshots).
"""

from __future__ import annotations

import logging

from manatee_tpu.daemons.common import daemon_main
from manatee_tpu.shard import build_storage
from manatee_tpu.snapshots import SnapShotter

log = logging.getLogger("manatee.snapshotter")

SCHEMA = {
    "type": "object",
    "required": ["dataset"],
    "properties": {
        "dataset": {"type": "string"},
        "pollInterval": {"type": "number"},
        "snapshotNumber": {"type": "integer"},
    },
}


async def start_snapshotter(cfg: dict):
    storage = build_storage(cfg)
    ping = cfg.get("sitterPingUrl")
    if not ping and cfg.get("ip") and cfg.get("postgresPort"):
        ping = "http://%s:%d/ping" % (cfg["ip"],
                                      int(cfg["postgresPort"]) + 1)
    snap = SnapShotter(
        storage,
        dataset=cfg["dataset"],
        poll_interval=float(cfg.get("pollInterval", 3600.0)),
        snapshot_number=int(cfg.get("snapshotNumber", 50)),
        sitter_ping_url=ping,
    )
    snap.start()

    async def stop():
        await snap.stop()

    return stop


def main(argv=None) -> None:
    daemon_main("manatee-snapshotter", "manatee snapshotter", SCHEMA,
                start_snapshotter, argv)


if __name__ == "__main__":
    main()
