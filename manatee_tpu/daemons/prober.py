"""manatee-prober — the black-box SLO measurement plane.

Everything else in this tree grades the control plane from the inside
(spans, events, failover_duration_seconds are all the control plane's
own account of itself).  The prober is the outside view: one process
fronts a whole fleet over the multiplexed coordination connection
(CoordMux, exactly like ``manatee-sitter --fleet``), watches each
shard's cluster state, and continuously does what a client would do —
synchronous writes against the primary, staleness-bounded reads
against every replica — producing per-shard **client-observed** SLIs:

- write availability and ack latency (``prober_writes_total``,
  ``prober_write_ack_seconds``);
- read staleness per peer, from its own read-your-write probes
  (``prober_read_staleness_seconds``) plus the peer-reported
  ``replication_lag_seconds`` gauge scraped from each sitter
  (health/telemetry.py's normalized lag, re-exported raw by the
  manager);
- the measured error window across a failover: first failed write →
  first succeeding write (``prober_error_window_seconds`` and a
  ``prober.error_window`` journal event) — the number the span-derived
  failover breakdown is judged against (bench.py slo_probe leg).

Good/bad events feed the SLO engine (obs/slo.py) whose burn-rate
alerts this daemon serves at ``GET /alerts``; snapshots of the whole
registry land in the on-disk history ring (obs/history.py) served at
``GET /history``.  Collection follows the amortization discipline
(RPCAcc/Poseidon, PAPERS.md): one write + one read per replica per
shard per interval, observations serialized once into instruments the
existing scrape plumbing already ships — O(1) per shard per tick.

Config (single shard, ``-f``)::

    {"shardPath": "/manatee/1",
     "coordCfg": {"connStr": "127.0.0.1:2281"},
     "statusPort": 14001, "probeInterval": 1.0,
     "stalenessBudget": 5.0, "historyDir": "/var/manatee/history",
     "slos": [{"name": "write_availability", "objective": 0.999}]}

``probeVia`` (optional) routes the probe traffic THROUGH a
manatee-router listener instead of straight at the peers — writes and
one read per tick (peer label ``router``) judge the router's routing
against the same SLOs; ``probeTimeout`` overrides the per-probe
timeout (give a routed prober headroom for the router's park across a
failover — a parked-then-replayed write should count as a slow
success, not an error).

Fleet mode (``--fleet`` or a ``shards`` list in ``-f``'s config)
mirrors the sitter: top-level keys are the shared base, each
``shards`` entry ({name, shardPath}) overrides per shard, one probe
loop per shard over ONE coordination connection and ONE engine per
database flavor.

Shard-map mode (``shardMapPath`` instead of ``shardPath``/``shards``)
follows the resharder's versioned shard map the way the router does:
per-shard probe loops are reconciled from the watched map record — a
shard that appears at a ``manatee-adm reshard`` flip gets its probe
loop WITHOUT a restart — and, when ``probeVia`` points at a map-mode
router, a keyed probe loop cycles synthetic writes across the
keyspace and read-your-writes each key back through the router.  That
keyed loop's ``prober_error_window_seconds`` is the reshard drill's
headline number: the write outage a routed client actually saw across
the cutover.

The probe seams carry the ``prober.write`` and ``prober.read``
failpoints (armable over this daemon's own ``/faults``): an ``error``
counts a bad SLI event without touching the cluster — the way the
chaos drill proves a fast-burn alert fires — and ``crash`` feeds the
crash-recovery sweep.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import time
from collections import deque

from manatee_tpu import faults
from manatee_tpu.coord.api import CoordError, NoNodeError
from manatee_tpu.coord.client import mux_handle
from manatee_tpu.daemons.common import (
    attach_obs_routes,
    daemon_main,
    start_daemon_introspection,
)
from manatee_tpu.obs import (
    get_journal,
    get_registry,
    merge_remote,
    observe_peer_clock,
    set_peer,
)
from manatee_tpu.obs.history import DEFAULT_INTERVAL as HISTORY_INTERVAL
from manatee_tpu.obs.history import HistoryRecorder, init_history
from manatee_tpu.obs.slo import init_slo_engine, parse_slo_configs
from manatee_tpu.pg.engine import PgError, parse_pg_url
from manatee_tpu.utils.aio import cancel_and_wait
from manatee_tpu.utils.validation import ConfigError

log = logging.getLogger("manatee.prober")

DEFAULT_PROBE_INTERVAL = 1.0
DEFAULT_STALENESS_BUDGET = 5.0
PROBE_TIMEOUT = 5.0
# peer-reported lag is scraped at most this often per peer (the probe
# loop itself never blocks on it)
LAG_SCRAPE_INTERVAL = 10.0
# wall-clock skew probes (clock_skew_seconds{peer}) at the same
# cadence: skew drifts far slower than replication lag
CLOCK_PROBE_INTERVAL = 10.0
# read-your-write matching window: acked probe writes we can still
# recognize in a replica's table
ACKED_RING = 1024

_REG = get_registry()
_WRITES = _REG.counter(
    "prober_writes_total",
    "synthetic write probes against each shard's primary",
    ("shard", "result"))
_WRITE_ACK = _REG.histogram(
    "prober_write_ack_seconds",
    "client-observed ack latency of synthetic writes",
    ("shard",))
_READS = _REG.counter(
    "prober_reads_total",
    "staleness-bounded read probes against each replica",
    ("shard", "peer", "result"))
_READ_STALENESS = _REG.gauge(
    "prober_read_staleness_seconds",
    "read-your-write staleness observed at each replica",
    ("shard", "peer"))
_PEER_LAG = _REG.gauge(
    "prober_peer_reported_lag_seconds",
    "replication lag each sitter reports for its own database "
    "(scraped from the peer's /metrics)",
    ("shard", "peer"))
_ERR_WINDOW = _REG.histogram(
    "prober_error_window_seconds",
    "client-observed write outage: first failed write to first "
    "succeeding write",
    ("shard",))
_LAST_ERR_WINDOW = _REG.gauge(
    "prober_last_error_window_seconds",
    "most recent closed error window per shard",
    ("shard",))


# the per-shard and keyed via-router probes are the SAME seams, so
# they share each failpoint through one call site (one seam, one name)
async def _write_fault() -> str | None:
    return await faults.point("prober.write")


async def _read_fault() -> str | None:
    return await faults.point("prober.read")

PROBER_SCHEMA = {
    "type": "object",
    "required": ["coordCfg"],
    # probe either ONE shard (shardPath) or a whole keyspace
    # (shardMapPath, the resharder's map record)
    "anyOf": [
        {"required": ["shardPath"]},
        {"required": ["shardMapPath"]},
    ],
    "properties": {
        "name": {"type": "string"},
        "shardPath": {"type": "string"},
        "shardMapPath": {"type": "string"},
        "statusPort": {"type": "integer"},
        "statusHost": {"type": "string"},
        "probeInterval": {"type": "number", "exclusiveMinimum": 0},
        "probeVia": {"type": ["string", "null"]},
        "probeTimeout": {"type": "number", "exclusiveMinimum": 0},
        "stalenessBudget": {"type": "number", "exclusiveMinimum": 0},
        "historyDir": {"type": ["string", "null"]},
        "historyInterval": {"type": "number", "exclusiveMinimum": 0},
        "slos": {"type": "array", "items": {"type": "object"}},
        "faults": {"type": "array", "items": {"type": "string"}},
        "faultsEnabled": {"type": "boolean"},
        "coordCfg": {
            "type": "object",
            "anyOf": [
                {"required": ["host", "port"]},
                {"required": ["connStr"]},
            ],
        },
    },
}

PROBER_FLEET_SCHEMA = {
    "type": "object",
    "required": ["shards", "coordCfg"],
    "properties": {
        "shards": {
            "type": "array",
            "minItems": 1,
            "items": {"type": "object", "required": ["shardPath"]},
        },
        "coordCfg": PROBER_SCHEMA["properties"]["coordCfg"],
        "statusPort": {"type": "integer"},
        "statusHost": {"type": "string"},
        "probeInterval": {"type": "number", "exclusiveMinimum": 0},
        "probeVia": {"type": ["string", "null"]},
        "probeTimeout": {"type": "number", "exclusiveMinimum": 0},
        "stalenessBudget": {"type": "number", "exclusiveMinimum": 0},
        "historyDir": {"type": ["string", "null"]},
        "historyInterval": {"type": "number", "exclusiveMinimum": 0},
        "slos": {"type": "array", "items": {"type": "object"}},
        "faults": {"type": "array", "items": {"type": "string"}},
        "faultsEnabled": {"type": "boolean"},
    },
}


def prober_shard_configs(cfg: dict) -> list[dict]:
    """The fleet merge, sitter-style: shared base + per-shard
    overrides; duplicate names/paths are config errors."""
    if not isinstance(cfg.get("shards"), list):
        one = dict(cfg)
        one["name"] = str(cfg.get("name")
                          or cfg["shardPath"].strip("/").replace("/", "-"))
        return [one]
    base = {k: v for k, v in cfg.items() if k != "shards"}
    merged, names, paths = [], set(), set()
    for i, entry in enumerate(cfg["shards"]):
        c = dict(base)
        c.update(entry)
        if not c.get("shardPath"):
            raise ConfigError("prober shard %d has no shardPath" % i)
        name = str(c.get("name")
                   or c["shardPath"].strip("/").replace("/", "-"))
        c["name"] = name
        if name in names:
            raise ConfigError("duplicate prober shard name %r" % name)
        if c["shardPath"] in paths:
            raise ConfigError("duplicate prober shardPath %r"
                              % c["shardPath"])
        names.add(name)
        paths.add(c["shardPath"])
        merged.append(c)
    return merged


class EngineCache:
    """One query engine per database flavor for the whole prober: the
    sim engine is stateless; the real engine keeps its pooled psql
    coprocess (PsqlSession) warm across probes — a probe must cost a
    query, not a process spawn."""

    def __init__(self):
        self._engines: dict[str, object] = {}

    def for_url(self, pg_url: str):
        scheme, _h, _p = parse_pg_url(pg_url)
        eng = self._engines.get(scheme)
        if eng is None:
            if scheme == "sim":
                from manatee_tpu.pg.engine import SimPgEngine
                eng = SimPgEngine()
            elif scheme == "tcp":
                import os
                from manatee_tpu.pg.postgres import PostgresEngine
                eng = PostgresEngine(
                    pg_bin_dir=os.environ.get("MANATEE_PG_BIN_DIR", ""),
                    use_sudo=False, session_pool=True)
            else:
                raise PgError("unsupported pgUrl scheme %r" % scheme)
            self._engines[scheme] = eng
        return eng

    async def query(self, pg_url: str, op: dict,
                    timeout: float) -> dict:
        return await self.for_url(pg_url).query_url(pg_url, op, timeout)

    async def aclose(self) -> None:
        for eng in self._engines.values():
            aclose = getattr(eng, "aclose", None)
            if aclose is not None:
                await aclose()
        self._engines.clear()


class ShardProber:
    """The probe loop for ONE shard: topology watch + synthetic writes
    + per-replica reads, each observation landing in registry
    instruments and the SLO engine."""

    def __init__(self, cfg: dict, engines: EngineCache, slo_engine, *,
                 http_get=None):
        self.name = cfg["name"]
        self.path = cfg["shardPath"]
        self.interval = float(cfg.get("probeInterval",
                                      DEFAULT_PROBE_INTERVAL))
        self.budget = float(cfg.get("stalenessBudget",
                                    DEFAULT_STALENESS_BUDGET))
        self.timeout = float(cfg["probeTimeout"]) \
            if cfg.get("probeTimeout") else \
            min(PROBE_TIMEOUT, max(0.5, self.interval * 5.0))
        # probeVia: route the probe traffic THROUGH manatee-router
        # instead of straight at the peers — the SLO plane then judges
        # the router's routing (a misrouting router pages itself).
        # Writes target the router; reads become ONE routed probe per
        # tick under the peer label "router"; lag/clock telemetry
        # scrapes keep going straight to the real peers.
        via = cfg.get("probeVia")
        self._via_rep = {"id": "router", "pgUrl": via} if via else None
        coord = cfg["coordCfg"]
        self._connstr = coord.get("connStr") or \
            "%s:%d" % (coord["host"], int(coord["port"]))
        self._session_timeout = float(coord.get("sessionTimeout", 60.0))
        grace = coord.get("disconnectGrace")
        self._disconnect_grace = None if grace is None else float(grace)
        self._engines = engines
        self._slo = slo_engine
        self._http_get = http_get or _http_get_text
        self._handle = None
        self._dirty = True
        self._primary: dict | None = None
        self._replicas: list[dict] = []
        self._wseq = 0
        # acked probe writes, oldest first: (seq, wall ts) — the
        # read-your-write matching set
        self._acked: deque[tuple[int, float]] = deque(maxlen=ACKED_RING)
        self._err_start: float | None = None   # monotonic, first failure
        self._last_lag_scrape: dict[str, float] = {}
        self._last_clock_probe: dict[str, float] = {}
        self._task: asyncio.Task | None = None

    # -- lifecycle --

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            # re-issuing cancel: one cancel can be swallowed by the
            # wait_for race under the probe queries (utils/aio.py)
            await cancel_and_wait(self._task)
            self._task = None
        if self._handle is not None:
            try:
                await self._handle.close()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            self._handle = None

    async def _run(self) -> None:
        while True:
            t0 = time.monotonic()
            try:
                await self._tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                # the prober must outlive everything it measures
                log.exception("probe tick failed on %s", self.name)
            elapsed = time.monotonic() - t0
            await asyncio.sleep(max(0.0, self.interval - elapsed))

    # -- topology --

    def _on_change(self, _ev) -> None:
        self._dirty = True

    async def _refresh_topology(self) -> None:
        if self._handle is None:
            self._handle = await mux_handle(
                self._connstr,
                session_timeout=self._session_timeout,
                disconnect_grace=self._disconnect_grace,
                name="prober:%s" % self.name)
            self._handle.on_session_event(self._on_change)
        try:
            data, _ver = await self._handle.get(
                self.path + "/state", watch=self._on_change)
        except NoNodeError:
            self._primary, self._replicas = None, []
            # the watch did not arm (no node): stay dirty so the next
            # tick re-reads until the shard writes its first state
            self._dirty = True
            return
        except CoordError:
            # severed/expired: drop the handle, rebuild next tick
            try:
                await self._handle.close()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            self._handle = None
            self._dirty = True
            raise
        self._dirty = False
        state = json.loads(data.decode())
        self._primary = state.get("primary") \
            if (state.get("primary") or {}).get("pgUrl") else None
        self._replicas = [
            p for p in [state.get("sync")] + list(state.get("async") or [])
            if p and p.get("pgUrl")]

    # -- probes --

    async def _tick(self) -> None:
        if self._dirty:
            try:
                await self._refresh_topology()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("topology refresh failed on %s: %s",
                            self.name, e)
        await self._probe_write()
        if self._via_rep is not None:
            await self._probe_read(self._via_rep)
            for rep in list(self._replicas):
                peer = rep.get("id") or rep["pgUrl"]
                await self._maybe_scrape_lag(rep, peer)
                await self._maybe_probe_clock(rep, peer)
        else:
            for rep in list(self._replicas):
                await self._probe_read(rep)
        if self._primary is not None:
            await self._maybe_probe_clock(
                self._primary,
                self._primary.get("id") or self._primary["pgUrl"])

    async def _probe_write(self) -> None:
        self._wseq += 1
        ts = time.time()
        value = {"probe": self.name, "seq": self._wseq,
                 "ts": round(ts, 6)}
        t0 = time.monotonic()
        err = None
        try:
            await _write_fault()
            if self._via_rep is not None:
                # routed: the router owns primary discovery (and
                # parks the write across a failover instead of
                # erroring — the stall this probe then measures)
                target = self._via_rep["pgUrl"]
            elif self._primary is None:
                raise PgError("no primary in cluster state")
            else:
                target = self._primary["pgUrl"]
            await self._engines.query(
                target, {"op": "insert", "value": value}, self.timeout)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            err = e
        now = time.monotonic()
        if err is None:
            _WRITES.inc(shard=self.name, result="ok")
            _WRITE_ACK.observe(now - t0, shard=self.name)
            self._slo.record("write_availability", good=True,
                             shard=self.name)
            self._acked.append((self._wseq, ts))
            if self._err_start is not None:
                # the outage a client saw: first failed write's issue
                # time to this ack
                window = now - self._err_start
                self._err_start = None
                _ERR_WINDOW.observe(window, shard=self.name)
                _LAST_ERR_WINDOW.set(window, shard=self.name)
                get_journal().record("prober.error_window",
                                     shard=self.name,
                                     seconds=round(window, 3))
        else:
            log.debug("write probe failed on %s: %s", self.name, err)
            _WRITES.inc(shard=self.name, result="error")
            self._slo.record("write_availability", good=False,
                             shard=self.name)
            if self._err_start is None:
                self._err_start = t0
            # a failed write is the moment to re-learn who the
            # primary is
            self._dirty = True

    async def _probe_read(self, rep: dict) -> None:
        peer = rep.get("id") or rep["pgUrl"]
        try:
            await _read_fault()
            res = await self._engines.query(
                rep["pgUrl"], {"op": "select"}, self.timeout)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.debug("read probe failed on %s/%s: %s",
                      self.name, peer, e)
            _READS.inc(shard=self.name, peer=peer, result="error")
            self._slo.record("read_staleness", good=False,
                             shard=self.name)
            return
        staleness = self._staleness(res.get("rows") or [])
        if staleness is None:
            # nothing acked yet: nothing to judge this replica by
            _READS.inc(shard=self.name, peer=peer, result="ok")
            return
        _READ_STALENESS.set(round(staleness, 6),
                            shard=self.name, peer=peer)
        good = staleness <= self.budget
        _READS.inc(shard=self.name, peer=peer,
                   result="ok" if good else "stale")
        self._slo.record("read_staleness", good=good, shard=self.name)
        if rep is self._via_rep:
            return      # the router serves no /metrics at pgUrl+1
        await self._maybe_scrape_lag(rep, peer)
        await self._maybe_probe_clock(rep, peer)

    def _staleness(self, rows: list) -> float | None:
        """Read-your-write staleness: age of the newest acked write
        the replica has NOT seen yet (0.0 = fully caught up), or None
        when nothing has been acked to judge by."""
        if not self._acked:
            return None
        newest = None
        for v in reversed(rows):
            if isinstance(v, dict) and v.get("probe") == self.name:
                newest = v
                break
        if newest is None:
            # the replica has none of our writes: behind by the full
            # acked window
            return max(0.0, time.time() - self._acked[0][1])
        seen_seq = int(newest.get("seq") or 0)
        for seq, ts in self._acked:
            if seq > seen_seq:
                # oldest acked write the replica is missing
                return max(0.0, time.time() - ts)
        return 0.0

    async def _maybe_scrape_lag(self, rep: dict, peer: str) -> None:
        """Fold in the peer's own account of its lag (the
        replication_lag_seconds gauge its sitter exports) — scraped at
        most once per LAG_SCRAPE_INTERVAL per peer, best-effort."""
        now = time.monotonic()
        last = self._last_lag_scrape.get(peer, 0.0)
        if now - last < LAG_SCRAPE_INTERVAL:
            return
        self._last_lag_scrape[peer] = now
        try:
            _s, host, pg_port = parse_pg_url(rep["pgUrl"])
            text = await self._http_get(
                "http://%s:%d/metrics" % (host, pg_port + 1))
        except asyncio.CancelledError:
            raise
        except Exception:
            return
        lag = _parse_lag_gauge(text)
        if lag is not None:
            _PEER_LAG.set(lag, shard=self.name, peer=peer)

    async def _maybe_probe_clock(self, rep: dict, peer: str) -> None:
        """NTP-style skew probe, best-effort: each peer's ``/events``
        payload carries its wall clock (``now``) and HLC stamp; the
        RTT midpoint gives the offset (``clock_skew_seconds{peer}``,
        rendered on this daemon's /metrics and the SKEW column of
        `manatee-adm top`), and folding the stamp keeps everything
        this prober journals causally after what it observed."""
        mono = time.monotonic()
        last = self._last_clock_probe.get(peer, 0.0)
        if mono - last < CLOCK_PROBE_INTERVAL:
            return
        self._last_clock_probe[peer] = mono
        try:
            _s, host, pg_port = parse_pg_url(rep["pgUrl"])
            t0 = time.time()
            text = await self._http_get(
                "http://%s:%d/events?limit=0" % (host, pg_port + 1))
            t1 = time.time()
            body = json.loads(text)
        except asyncio.CancelledError:
            raise
        except Exception:
            return
        if not isinstance(body, dict):
            return
        now = body.get("now")
        if isinstance(now, (int, float)):
            observe_peer_clock(peer, float(now), t0, t1)
        await merge_remote(body.get("hlc"))


class ShardMapProber:
    """Map mode: a probe plane that follows the shard map.

    Two jobs, both reconciled from the same watched map record the
    router compiles routes from (manatee_tpu/reshard/plan.py):

    - **follow-the-split**: one direct :class:`ShardProber` per shard
      the map names, started/stopped as ranges change hands — the
      shard a reshard flip creates starts getting measured the moment
      the map says it serves;
    - **the keyed via-router loop** (``probeVia``): synthetic writes
      whose values carry a ``key`` cycling across the keyspace, each
      read-your-write'd back through the router by the same key.  The
      router sniffs the key and routes per the map, so this loop
      measures what a keyed client sees through a cutover — its
      ``prober_error_window_seconds{shard=<map name>}`` is the
      reshard acceptance number.
    """

    def __init__(self, cfg: dict, engines: EngineCache, slo_engine, *,
                 http_get=None):
        self.name = str(cfg.get("name") or "map")
        self.map_path = cfg["shardMapPath"]
        self.interval = float(cfg.get("probeInterval",
                                      DEFAULT_PROBE_INTERVAL))
        self.via = cfg.get("probeVia")
        self.timeout = float(cfg["probeTimeout"]) \
            if cfg.get("probeTimeout") else \
            min(PROBE_TIMEOUT, max(0.5, self.interval * 5.0))
        coord = cfg["coordCfg"]
        self._connstr = coord.get("connStr") or \
            "%s:%d" % (coord["host"], int(coord["port"]))
        self._session_timeout = float(coord.get("sessionTimeout", 60.0))
        grace = coord.get("disconnectGrace")
        self._disconnect_grace = None if grace is None else float(grace)
        self._engines = engines
        self._slo = slo_engine
        self._http_get = http_get
        # per-shard child config base: identity and map/via keys out
        # (children probe their shard DIRECT; the via loop is ours)
        self._child_base = {
            k: v for k, v in cfg.items()
            if k not in ("shardMapPath", "shardPath", "name",
                         "probeVia", "statusPort", "statusHost",
                         "slos", "historyDir", "historyInterval",
                         "faults", "faultsEnabled")}
        self._children: dict[str, ShardProber] = {}
        self._handle = None
        self._dirty = True
        self._wake = asyncio.Event()
        self._wake.set()
        self._epoch = 0
        self._map_task: asyncio.Task | None = None
        self._via_task: asyncio.Task | None = None
        # keyed via-loop state: last acked (seq, wall ts) per key
        self._wseq = 0
        self._acked_by_key: dict[str, tuple[int, float]] = {}
        self._err_start: float | None = None

    # -- lifecycle --

    def start(self) -> None:
        if self._map_task is None:
            self._map_task = asyncio.create_task(self._map_loop())
        if self.via and self._via_task is None:
            self._via_task = asyncio.create_task(self._via_loop())

    async def stop(self) -> None:
        for task in (self._map_task, self._via_task):
            await cancel_and_wait(task)
        self._map_task = self._via_task = None
        for child in self._children.values():
            await child.stop()
        self._children.clear()
        if self._handle is not None:
            try:
                await self._handle.close()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            self._handle = None

    # -- the map watch (the router's pattern) --

    def _on_change(self, _ev) -> None:
        self._dirty = True
        self._wake.set()

    async def _map_loop(self) -> None:
        while True:
            with_timeout = asyncio.wait_for(self._wake.wait(), 1.0)
            try:
                await with_timeout
            except asyncio.TimeoutError:
                pass
            self._wake.clear()
            if not self._dirty:
                continue
            try:
                await self._refresh_map()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("shard-map refresh failed: %s", e)
                await asyncio.sleep(0.2)

    async def _refresh_map(self) -> None:
        if self._handle is None:
            self._handle = await mux_handle(
                self._connstr,
                session_timeout=self._session_timeout,
                disconnect_grace=self._disconnect_grace,
                name="prober:%s" % self.name)
            self._handle.on_session_event(self._on_change)
        try:
            data, _ver = await self._handle.get(
                self.map_path, watch=self._on_change)
        except NoNodeError:
            self._dirty = True      # keep polling for the map
            return
        except CoordError:
            try:
                await self._handle.close()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            self._handle = None
            self._dirty = True
            raise
        self._dirty = False
        await self.apply_map(json.loads(data.decode()))

    async def apply_map(self, m: dict) -> None:
        """Reconcile the per-shard probe loops against the shards the
        map names (the watch's landing point, and the test seam).  An
        invalid map keeps the current loops running."""
        from manatee_tpu.reshard.plan import validate_map
        try:
            validate_map(m)
        except Exception as e:
            log.warning("refusing invalid shard map: %s", e)
            return
        want = {r["shard"]: r["shardPath"] for r in m["ranges"]}
        for name in [n for n in self._children if n not in want]:
            old = self._children.pop(name)
            await old.stop()
        started = []
        for name, path in want.items():
            child = self._children.get(name)
            if child is not None and child.path != path:
                await child.stop()
                del self._children[name]
                child = None
            if child is None:
                ccfg = dict(self._child_base)
                ccfg["name"] = name
                ccfg["shardPath"] = path
                child = ShardProber(ccfg, self._engines, self._slo,
                                    http_get=self._http_get)
                child.start()
                self._children[name] = child
                started.append(name)
        old_epoch = self._epoch
        self._epoch = int(m.get("epoch", 0))
        if self._epoch != old_epoch or started:
            get_journal().record(
                "prober.map_change", epoch=self._epoch,
                shards=sorted(want), started=sorted(started))

    # -- the keyed via-router loop --

    @staticmethod
    def probe_key(seq: int) -> str:
        """The key cycle: 256 keys spread over [k00, kff] so a split
        at any interior point keeps traffic landing on BOTH sides of
        the cut (37 is coprime to 256 — every key is visited)."""
        return "k%02x" % ((seq * 37) % 256)

    async def _via_loop(self) -> None:
        while True:
            t0 = time.monotonic()
            try:
                await self._via_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("via probe tick failed on %s", self.name)
            elapsed = time.monotonic() - t0
            await asyncio.sleep(max(0.0, self.interval - elapsed))

    async def _via_tick(self) -> None:
        self._wseq += 1
        seq = self._wseq
        key = self.probe_key(seq)
        ts = time.time()
        value = {"probe": self.name, "seq": seq,
                 "ts": round(ts, 6), "key": key}
        t0 = time.monotonic()
        err = None
        try:
            await _write_fault()
            await self._engines.query(
                self.via, {"op": "insert", "value": value},
                self.timeout)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            err = e
        now = time.monotonic()
        if err is None:
            _WRITES.inc(shard=self.name, result="ok")
            _WRITE_ACK.observe(now - t0, shard=self.name)
            self._slo.record("write_availability", good=True,
                             shard=self.name)
            self._acked_by_key[key] = (seq, ts)
            if self._err_start is not None:
                window = now - self._err_start
                self._err_start = None
                _ERR_WINDOW.observe(window, shard=self.name)
                _LAST_ERR_WINDOW.set(window, shard=self.name)
                get_journal().record("prober.error_window",
                                     shard=self.name,
                                     seconds=round(window, 3))
        else:
            log.debug("keyed write probe failed on %s: %s",
                      self.name, err)
            _WRITES.inc(shard=self.name, result="error")
            self._slo.record("write_availability", good=False,
                             shard=self.name)
            if self._err_start is None:
                self._err_start = t0
        await self._via_read(key)

    async def _via_read(self, key: str) -> None:
        """Keyed read-your-write THROUGH the router: the key in the
        select line steers the router to whichever shard owns it now,
        where our last acked write for that key must be visible."""
        try:
            await _read_fault()
            res = await self._engines.query(
                self.via,
                {"op": "select", "key": key, "limit": ACKED_RING},
                self.timeout)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.debug("keyed read probe failed on %s/%s: %s",
                      self.name, key, e)
            _READS.inc(shard=self.name, peer="router", result="error")
            self._slo.record("read_staleness", good=False,
                             shard=self.name)
            return
        acked = self._acked_by_key.get(key)
        if acked is None:
            _READS.inc(shard=self.name, peer="router", result="ok")
            return
        seen = 0
        for v in reversed(res.get("rows") or []):
            if isinstance(v, dict) and v.get("probe") == self.name \
                    and v.get("key") == key:
                seen = int(v.get("seq") or 0)
                break
        good = seen >= acked[0]
        staleness = 0.0 if good else max(0.0, time.time() - acked[1])
        _READ_STALENESS.set(round(staleness, 6),
                            shard=self.name, peer="router")
        _READS.inc(shard=self.name, peer="router",
                   result="ok" if good else "stale")
        self._slo.record("read_staleness", good=good, shard=self.name)

    def describe_map(self) -> dict:
        return {
            "epoch": self._epoch,
            "path": self.map_path,
            "via": self.via,
            "shards": sorted(self._children),
            "error_window_open": self._err_start is not None,
        }


_LAG_RE = re.compile(
    r'^manatee_replication_lag_seconds\{[^}]*\}\s+([0-9.eE+-]+)\s*$',
    re.M)


def _parse_lag_gauge(text: str) -> float | None:
    m = _LAG_RE.search(text)
    return float(m.group(1)) if m else None


def _hist_quantile(hist, q: float, **labels) -> float | None:
    """Bucket-boundary quantile estimate (upper bound of the bucket the
    q-th observation landed in) — the /slis dashboard numbers."""
    snap = hist.snapshot(**labels)
    total = snap["count"]
    if not total:
        return None
    target = q * total
    cum = 0
    for i, ub in enumerate(hist.buckets):
        cum = snap["counts"][i]
        if cum >= target:
            return ub
    return hist.buckets[-1]


async def _http_get_text(url: str, timeout: float = 2.0) -> str:
    import aiohttp
    tmo = aiohttp.ClientTimeout(total=timeout)
    async with aiohttp.ClientSession(timeout=tmo) as http:
        async with http.get(url) as resp:
            return await resp.text()


# ---- the prober's own HTTP listener ----
#
# Not a StatusServer: that class's /ping and /state speak for a
# database this process does not run.  The listener reuses the same
# pure endpoint helpers, so /metrics, /events, /spans, /history,
# /alerts and /faults answer with exactly the contracts every other
# daemon serves.

class ProberServer:
    def __init__(self, probers: list[ShardProber], *,
                 host: str = "0.0.0.0", port: int = 0,
                 map_prober: ShardMapProber | None = None):
        from aiohttp import web
        self._web = web
        self.probers = probers
        self.map_prober = map_prober
        self.host = host
        self.port = port
        self._runner = None
        app = web.Application()
        app.router.add_get("/", self._routes)
        app.router.add_get("/slis", self._slis)
        # /metrics + the shared introspection table (daemons/common.py)
        self._obs_routes = attach_obs_routes(app, metrics=True)
        self._app = app

    async def start(self) -> None:
        web = self._web
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        log.info("prober listening on %s:%d (%d shards)",
                 self.host, self.port, len(self.probers))

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _routes(self, _req):
        return self._web.json_response(["/slis"] + self._obs_routes)

    async def _slis(self, _req):
        """Per-shard instantaneous SLIs — what `manatee-adm top`
        renders alongside the budget table."""
        out = []
        probers = list(self.probers)
        if self.map_prober is not None:
            # map mode: the reconciled children are the shard list
            probers += list(self.map_prober._children.values())
        for p in probers:
            out.append({
                "shard": p.name,
                "primary": (self._primary_id(p)),
                "replicas": [r.get("id") for r in p._replicas],
                "writes_ok": _WRITES.value(shard=p.name, result="ok"),
                "writes_error": _WRITES.value(shard=p.name,
                                              result="error"),
                "ack_p50_s": _hist_quantile(_WRITE_ACK, 0.5,
                                            shard=p.name),
                "ack_p99_s": _hist_quantile(_WRITE_ACK, 0.99,
                                            shard=p.name),
                "staleness": {
                    labels.get("peer"): v
                    for labels, v in _READ_STALENESS.samples()
                    if labels.get("shard") == p.name},
                "last_error_window_s": _LAST_ERR_WINDOW.value(
                    shard=p.name) or None,
                "error_window_open": p._err_start is not None,
            })
        body = {"now": round(time.time(), 3), "shards": out}
        if self.map_prober is not None:
            mp = self.map_prober
            body["map"] = dict(
                mp.describe_map(),
                writes_ok=_WRITES.value(shard=mp.name, result="ok"),
                writes_error=_WRITES.value(shard=mp.name,
                                           result="error"),
                last_error_window_s=_LAST_ERR_WINDOW.value(
                    shard=mp.name) or None)
        return self._web.json_response(body)

    @staticmethod
    def _primary_id(p: ShardProber):
        return p._primary.get("id") if p._primary else None


# ---- daemon wiring ----

async def start_prober(cfg: dict):
    map_mode = bool(cfg.get("shardMapPath")) \
        and not cfg.get("shards") and not cfg.get("shardPath")
    shard_cfgs = [] if map_mode else prober_shard_configs(cfg)
    host = cfg.get("statusHost", "0.0.0.0")
    port = int(cfg.get("statusPort", 0))
    set_peer("prober:%d" % port if port else "prober")
    # boot-time fault arming + runtime /faults opt-in, the same
    # contract every other daemon honors (docs/fault-injection.md):
    # the chaos drill arms prober.write over this surface
    faults.arm_specs(cfg.get("faults"), source="config")
    if cfg.get("faultsEnabled"):
        faults.enable_http()
    slo_engine = init_slo_engine(
        parse_slo_configs(cfg["slos"]) if cfg.get("slos") else None)
    recorder = None
    if cfg.get("historyDir"):
        history = init_history(cfg["historyDir"])
        recorder = HistoryRecorder(
            history, float(cfg.get("historyInterval",
                                   HISTORY_INTERVAL)))
        recorder.start()
    engines = EngineCache()
    probers = [ShardProber(c, engines, slo_engine)
               for c in shard_cfgs]
    map_prober = ShardMapProber(cfg, engines, slo_engine) \
        if map_mode else None
    intro = start_daemon_introspection(cfg)
    server = ProberServer(probers, host=host, port=port,
                          map_prober=map_prober)
    await server.start()
    for p in probers:
        p.start()
    if map_prober is not None:
        map_prober.start()
        log.info("prober following shard map %s%s",
                 cfg["shardMapPath"],
                 " via %s" % cfg["probeVia"]
                 if cfg.get("probeVia") else "")

    async def eval_loop():
        # journal alert transitions promptly even when nobody scrapes
        while True:
            await asyncio.sleep(1.0)
            slo_engine.evaluate()

    eval_task = asyncio.create_task(eval_loop())
    log.info("prober running %d shard loops on one coordination "
             "connection", len(probers))

    async def stop():
        eval_task.cancel()
        try:
            await eval_task
        except asyncio.CancelledError:
            pass
        for p in probers:
            await p.stop()
        if map_prober is not None:
            await map_prober.stop()
        if recorder is not None:
            await recorder.stop()
        await engines.aclose()
        await server.stop()
        await intro.stop()

    return stop


def main(argv=None) -> None:
    daemon_main("manatee-prober",
                "black-box SLO prober (synthetic writes/reads, "
                "burn-rate alerts)",
                PROBER_SCHEMA, start_prober, argv,
                fleet_schema=PROBER_FLEET_SCHEMA)


if __name__ == "__main__":
    main()
