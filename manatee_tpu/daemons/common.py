"""Shared daemon plumbing: -f/-v option parsing, config loading, signal
handling (parseOptions/readConfig parity, sitter.js:50-94)."""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from manatee_tpu.utils.logutil import setup_logging
from manatee_tpu.utils.validation import ConfigError, load_json_config


def parse_daemon_args(description: str, argv=None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-f", "--config", required=True,
                   help="JSON config file path")
    p.add_argument("-v", "--verbose", action="count", default=0)
    return p.parse_args(argv)


def daemon_main(name: str, description: str, schema: dict | None,
                run_coro_factory, argv=None) -> None:
    """Parse args, load config, set up logging, run until SIGINT/SIGTERM.
    *run_coro_factory(cfg)* returns (start_coro, stop_coro_factory)."""
    args = parse_daemon_args(description, argv)
    setup_logging(name, args.verbose)
    try:
        cfg = load_json_config(args.config, schema, name=name)
    except ConfigError as e:
        sys.stderr.write("%s: %s\n" % (name, e))
        sys.exit(2)

    async def run():
        stop_evt = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop_evt.set)
        stopper = await run_coro_factory(cfg)
        await stop_evt.wait()
        await stopper()

    asyncio.run(run())
