"""Shared daemon plumbing: -f/-v option parsing, config loading, signal
handling (parseOptions/readConfig parity, sitter.js:50-94)."""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from manatee_tpu.utils.logutil import setup_logging
from manatee_tpu.utils.validation import (
    ConfigError,
    load_json_config,
    validate_config,
)


def parse_daemon_args(description: str, argv=None, *,
                      fleet: bool = False) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-f", "--config", required=not fleet,
                   help="JSON config file path")
    if fleet:
        p.add_argument("--fleet", metavar="SHARDS_JSON", default=None,
                       help="fleet mode: JSON config with a `shards` "
                            "list — run every shard's state machine "
                            "in this one process over one multiplexed "
                            "coordination connection")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    if fleet:
        if args.config and args.fleet:
            p.error("-f/--config and --fleet are mutually exclusive")
        if not args.config and not args.fleet:
            p.error("one of -f/--config or --fleet is required")
    return args


def daemon_main(name: str, description: str, schema: dict | None,
                run_coro_factory, argv=None, *,
                fleet_schema: dict | None = None) -> None:
    """Parse args, load config, set up logging, run until SIGINT/SIGTERM.
    *run_coro_factory(cfg)* returns (start_coro, stop_coro_factory).

    *fleet_schema*: enables the ``--fleet`` flag (and the ``shards``
    config key) for this daemon.  A config carrying a ``shards`` list —
    whether it arrived via ``--fleet`` or plain ``-f`` — is validated
    against *fleet_schema* instead of *schema*; the daemon validates
    each merged per-shard config itself (sitter.start_fleet)."""
    fleet = fleet_schema is not None
    args = parse_daemon_args(description, argv, fleet=fleet)
    setup_logging(name, args.verbose)
    path = args.fleet if fleet and args.fleet else args.config
    try:
        # load WITHOUT a schema first: which schema applies depends on
        # whether the config is a fleet config (`shards` key)
        cfg = load_json_config(path, None, name=name)
        if not isinstance(cfg, dict):
            raise ConfigError("%s: config must be a JSON object, "
                              "not %s" % (path, type(cfg).__name__))
        is_fleet_cfg = fleet and isinstance(cfg.get("shards"), list)
        if fleet and args.fleet and not is_fleet_cfg:
            raise ConfigError(
                "--fleet config %s has no `shards` list" % path)
        use_schema = fleet_schema if is_fleet_cfg else schema
        if use_schema is not None:
            validate_config(cfg, use_schema, name=name)
    except ConfigError as e:
        sys.stderr.write("%s: %s\n" % (name, e))
        sys.exit(2)

    async def run():
        stop_evt = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop_evt.set)
        stopper = await run_coro_factory(cfg)
        await stop_evt.wait()
        await stopper()

    try:
        asyncio.run(run())
    except ConfigError as e:
        # config errors the daemon itself raises at startup (the fleet
        # path validates each merged per-shard config in start_fleet)
        # exit like any other config error, not as a crash
        sys.stderr.write("%s: %s\n" % (name, e))
        sys.exit(2)
