"""Shared daemon plumbing: -f/-v option parsing, config loading, signal
handling (parseOptions/readConfig parity, sitter.js:50-94)."""

from __future__ import annotations

import argparse
import asyncio
import signal
import sys

from manatee_tpu.utils.logutil import setup_logging
from manatee_tpu.utils.validation import (
    ConfigError,
    load_json_config,
    validate_config,
)


# The introspection surface every daemon listener serves, in one
# table.  Until PR 16 each of the four listeners (StatusServer, coordd
# metrics, backup REST, prober) hand-maintained its own route list and
# they had drifted: coordd and the backup server lacked /events and
# /alerts, the backup server had no /metrics at all, and none served
# the new /profile and /tasks.  attach_obs_routes is now the only way
# these endpoints get mounted, so the contract cannot drift again.
OBS_ROUTES = ("/events", "/spans", "/history", "/alerts", "/profile",
              "/tasks", "/faults")


def attach_obs_routes(app, *, metrics: bool = False) -> list[str]:
    """Mount the shared introspection endpoints on an aiohttp *app*:
    ``/events``, ``/spans``, ``/history``, ``/alerts``, ``/profile``,
    ``/tasks`` (all through the pure ``*_http_reply`` helpers against
    the process-wide obs singletons) plus the ``/faults`` surface.

    *metrics*: also mount the generic registry-only ``GET /metrics``
    exposition — for listeners without daemon-specific gauges (the
    backup server, the prober).  The status server and coordd keep
    their own /metrics handlers.

    Returns the mounted paths, for ``GET /`` route listings."""
    import time as _time

    from aiohttp import web

    from manatee_tpu import faults
    from manatee_tpu.obs import get_journal, get_span_store
    from manatee_tpu.obs.causal import hlc_now
    from manatee_tpu.obs.history import get_history, history_http_reply
    from manatee_tpu.obs.profile import (
        get_profiler,
        profile_http_reply,
        tasks_http_reply,
    )
    from manatee_tpu.obs.slo import alerts_http_reply, get_slo_engine
    from manatee_tpu.obs.spans import parse_page_query, spans_http_reply

    async def _events(req):
        journal = get_journal()
        try:
            since, limit = parse_page_query(req.query)
        except ValueError:
            return web.json_response(
                {"error": "since/limit must be integers"}, status=400,
                content_type="application/json")
        return web.json_response({
            "peer": journal.peer,
            "now": round(_time.time(), 3),
            "hlc": hlc_now(),
            "events": journal.events(since=since, limit=limit),
        }, content_type="application/json")

    async def _spans(req):
        body, status = spans_http_reply(get_span_store(), req.query)
        return web.json_response(body, status=status,
                                 content_type="application/json")

    async def _history(req):
        body, status = history_http_reply(get_history(), req.query)
        return web.json_response(body, status=status,
                                 content_type="application/json")

    async def _alerts(req):
        body, status = alerts_http_reply(get_slo_engine(), req.query)
        return web.json_response(body, status=status,
                                 content_type="application/json")

    async def _profile(req):
        body, status = profile_http_reply(get_profiler(), req.query)
        if isinstance(body, str):
            # folded-stack text, ready for `tools/flamegraph`
            return web.Response(text=body, status=status,
                                content_type="text/plain")
        return web.json_response(body, status=status,
                                 content_type="application/json")

    async def _tasks(req):
        body, status = tasks_http_reply(req.query)
        return web.json_response(body, status=status,
                                 content_type="application/json")

    async def _metrics(_req):
        from manatee_tpu.obs import get_registry
        from manatee_tpu.obs.process import refresh_process_metrics
        from manatee_tpu.utils.prom import MetricsBuilder
        refresh_process_metrics()
        b = MetricsBuilder("manatee")
        get_registry().render_into(b)
        return web.Response(text=b.render(),
                            content_type="text/plain")

    app.router.add_get("/events", _events)
    app.router.add_get("/spans", _spans)
    app.router.add_get("/history", _history)
    app.router.add_get("/alerts", _alerts)
    app.router.add_get("/profile", _profile)
    app.router.add_get("/tasks", _tasks)
    faults.attach_http(app)
    mounted = list(OBS_ROUTES)
    if metrics:
        app.router.add_get("/metrics", _metrics)
        mounted.insert(0, "/metrics")
    return mounted


def start_daemon_introspection(cfg: dict | None):
    """The always-on profiling plane (obs/profile.py), started from
    every daemon's wiring exactly like the history recorder — one per
    process no matter how many shards it runs."""
    from manatee_tpu.obs.profile import start_introspection
    return start_introspection(cfg)


def parse_daemon_args(description: str, argv=None, *,
                      fleet: bool = False) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("-f", "--config", required=not fleet,
                   help="JSON config file path")
    if fleet:
        p.add_argument("--fleet", metavar="SHARDS_JSON", default=None,
                       help="fleet mode: JSON config with a `shards` "
                            "list — run every shard's state machine "
                            "in this one process over one multiplexed "
                            "coordination connection")
    p.add_argument("-v", "--verbose", action="count", default=0)
    args = p.parse_args(argv)
    if fleet:
        if args.config and args.fleet:
            p.error("-f/--config and --fleet are mutually exclusive")
        if not args.config and not args.fleet:
            p.error("one of -f/--config or --fleet is required")
    return args


def daemon_main(name: str, description: str, schema: dict | None,
                run_coro_factory, argv=None, *,
                fleet_schema: dict | None = None) -> None:
    """Parse args, load config, set up logging, run until SIGINT/SIGTERM.
    *run_coro_factory(cfg)* returns (start_coro, stop_coro_factory).

    *fleet_schema*: enables the ``--fleet`` flag (and the ``shards``
    config key) for this daemon.  A config carrying a ``shards`` list —
    whether it arrived via ``--fleet`` or plain ``-f`` — is validated
    against *fleet_schema* instead of *schema*; the daemon validates
    each merged per-shard config itself (sitter.start_fleet)."""
    fleet = fleet_schema is not None
    args = parse_daemon_args(description, argv, fleet=fleet)
    setup_logging(name, args.verbose)
    path = args.fleet if fleet and args.fleet else args.config
    try:
        # load WITHOUT a schema first: which schema applies depends on
        # whether the config is a fleet config (`shards` key)
        cfg = load_json_config(path, None, name=name)
        if not isinstance(cfg, dict):
            raise ConfigError("%s: config must be a JSON object, "
                              "not %s" % (path, type(cfg).__name__))
        is_fleet_cfg = fleet and isinstance(cfg.get("shards"), list)
        if fleet and args.fleet and not is_fleet_cfg:
            raise ConfigError(
                "--fleet config %s has no `shards` list" % path)
        use_schema = fleet_schema if is_fleet_cfg else schema
        if use_schema is not None:
            validate_config(cfg, use_schema, name=name)
    except ConfigError as e:
        sys.stderr.write("%s: %s\n" % (name, e))
        sys.exit(2)

    async def run():
        stop_evt = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            loop.add_signal_handler(sig, stop_evt.set)
        stopper = await run_coro_factory(cfg)
        await stop_evt.wait()
        await stopper()

    try:
        asyncio.run(run())
    except ConfigError as e:
        # config errors the daemon itself raises at startup (the fleet
        # path validates each merged per-shard config in start_fleet)
        # exit like any other config error, not as a crash
        sys.stderr.write("%s: %s\n" % (name, e))
        sys.exit(2)
