"""manatee-router — the fleet's connection front door.

Everything below this daemon *manages* databases; nothing so far
*serves* them.  The router is the missing data-plane edge: one
pgbouncer-shaped async TCP proxy fronts a whole fleet of shards over
ONE multiplexed coordination session (CoordMux, exactly like
``manatee-sitter --fleet`` and ``manatee-prober``), watches each
shard's cluster state, and routes the simpg line protocol:

- **writes** (``insert``, and any verb it cannot classify) pin to the
  shard's primary;
- **reads** (``select``, ``health``) spread round-robin across the
  sync/async chain, **staleness-bounded**: a replica whose known
  replication lag exceeds ``stalenessBudget`` drops out of the read
  set.  Lag is fed the way the prober feeds it — the
  ``replication_lag_seconds`` gauge scraped from each sitter's
  /metrics — plus passive inference from the state watch itself (a
  peer deposed or removed from the chain is evicted the moment the
  watch fires, without waiting for a scrape);
- ``status`` goes to the primary (the authoritative replication
  view); ``replicate`` is refused — routers do not proxy replication
  streams.

The headline behavior is what happens during a failover: instead of
erroring, in-flight writes are **drained and parked** — held while
the topology watch converges on the new primary, then replayed
against it — so a client sees a sub-second stall where it used to see
connection errors.  The park is bounded by ``parkTimeout`` and
measured (``router_park_seconds``, a ``router.park`` journal event).
A replay after a connection died mid-ack can duplicate a write — the
same exposure any client retry loop has, and the sim engine's
insert-only table is idempotent about it.

Per-connection cost is the perf target, per the serialize-once /
amortize-everything discipline (RPCAcc, Poseidon — PAPERS.md):

- upstream connections are **pooled per (shard, peer)** and reused
  across requests (``router_upstream_dials_total`` stays flat while
  ``router_routed_total`` grows);
- the route table is computed **once per state watch / lag update**
  (``router_route_rebuilds_total``), never per connection or per
  request — the relay path reads one immutable table;
- the steady-state relay path does **no JSON parse and no per-request
  object construction**: the verb is sniffed with a single compiled
  regex over the raw line, the routing decision is a table lookup,
  and the bytes the client sent are the bytes the upstream receives.

The router fronts the simpg newline-JSON wire (``sim://`` pgUrls) —
the protocol every test cluster and the bench speak.  Fronting real
PostgreSQL would mean speaking the pg wire protocol at this seam; the
routing, parking and pooling layers are protocol-agnostic and would
carry over unchanged.

Config (single shard, ``-f``)::

    {"shardPath": "/manatee/1", "listenPort": 15432,
     "coordCfg": {"connStr": "127.0.0.1:2281"},
     "statusPort": 14002, "stalenessBudget": 5.0,
     "parkTimeout": 30.0}

Fleet mode (``--fleet`` or a ``shards`` list) mirrors the sitter and
prober: top-level keys are the shared base, each ``shards`` entry
({name, shardPath, listenPort}) overrides per shard, one listener per
shard over ONE coordination connection.

Shard-map mode (``shardMapPath`` instead of ``shardPath``/``shards``)
fronts a *keyspace* instead of a fixed shard list: the router watches
the versioned shard-map record the resharder maintains
(manatee_tpu/reshard/plan.py), sniffs the ``"key"`` field off each
request line the same zero-parse way it sniffs the verb, and routes to
whichever shard's range owns the key.  Child per-shard routers are
reconciled from the map on every watch fire — a range that changes
hands mid-flight (``manatee-adm reshard``) re-routes WITHOUT a restart,
and a range marked ``frozen`` parks writes at the map layer until the
flip lands, exactly the failover park but keyed to the cutover.  The
``/status`` ``map`` section (epoch + per-shard ``inflight_writes``)
is the drain barrier the resharder polls before shipping the final
delta.

The traffic seams carry the ``router.accept``, ``router.relay`` and
``router.park`` failpoints (armable over this daemon's own
``/faults``); the crash-recovery sweep kills the router mid-relay and
mid-park and proves clients see a closed socket, never a wedge.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import re
import time

from manatee_tpu import faults
from manatee_tpu.coord.api import CoordError, NoNodeError
from manatee_tpu.coord.client import mux_handle
from manatee_tpu.daemons.common import (
    attach_obs_routes,
    daemon_main,
    start_daemon_introspection,
)
from manatee_tpu.obs import get_journal, get_registry, set_peer, span
from manatee_tpu.pg.engine import parse_pg_url
from manatee_tpu.utils.aio import cancel_and_wait
from manatee_tpu.utils.validation import ConfigError

log = logging.getLogger("manatee.router")

DEFAULT_STALENESS_BUDGET = 5.0
DEFAULT_PARK_TIMEOUT = 30.0
DEFAULT_RELAY_TIMEOUT = 5.0
DEFAULT_LAG_INTERVAL = 2.0
DEFAULT_MAX_IDLE = 8
# parked writers re-check the table at least this often even when no
# route-change event fires (a new primary may become writable without
# a state transition we can observe)
PARK_POLL = 0.25
# a peer that failed a relay is out of the read set for this long;
# the next state watch or lag refresh re-admits it if healthy
DOWN_COOLDOWN = 5.0
UPSTREAM_DIAL_TIMEOUT = 5.0

_REG = get_registry()
_CONNS = _REG.gauge(
    "router_connections",
    "live client connections per fronted shard",
    ("shard",))
_ROUTED = _REG.counter(
    "router_routed_total",
    "requests relayed, by sniffed verb and the peer that served them",
    ("shard", "verb", "peer"))
_PARK_SECONDS = _REG.histogram(
    "router_park_seconds",
    "how long parked requests were held across a failover before "
    "replay (or park-budget exhaustion)",
    ("shard",))
_PARKED = _REG.gauge(
    "router_parked",
    "requests currently parked awaiting a writable primary",
    ("shard",))
_DIALS = _REG.counter(
    "router_upstream_dials_total",
    "new upstream connections dialed (pool misses); flat while "
    "router_routed_total grows means the pool is doing its job",
    ("shard", "peer"))
_POOLED = _REG.gauge(
    "router_pooled_idle",
    "idle pooled upstream connections per (shard, peer)",
    ("shard", "peer"))
_REBUILDS = _REG.counter(
    "router_route_rebuilds_total",
    "route-table recomputations (one per state watch or lag-set "
    "change, NEVER per request)",
    ("shard",))
_READ_PEERS = _REG.gauge(
    "router_read_peers",
    "replicas currently eligible for reads (within the staleness "
    "budget and not recently failed)",
    ("shard",))
_ROUTER_LAG = _REG.gauge(
    "router_replica_lag_seconds",
    "replication lag the router last learned for each replica "
    "(scraped from the peer's sitter, prober-style)",
    ("shard", "peer"))
_MAP_EPOCH = _REG.gauge(
    "router_map_epoch",
    "shard-map epoch this router last compiled routes from "
    "(map mode only; lags the coord record by one watch fire)")
_MAP_CHANGES = _REG.counter(
    "router_map_changes_total",
    "shard-map recompilations (one per watched map change, which is "
    "one per reshard step that edits the map — NEVER per request)")

# the verb sniff: one compiled regex over the raw request line — the
# engine's json.dumps puts the "op" key first, so the first match IS
# the op (no JSON parse on the relay path)
_OP_RE = re.compile(rb'"op"\s*:\s*"([A-Za-z_]+)"')
# map mode's routing key, sniffed the same zero-parse way: the first
# "key" field in the request line (inserts carry it in the value,
# keyed reads carry it top-level; a line without one routes to the
# map's first range)
_KEY_RE = re.compile(rb'"key"\s*:\s*"([^"\\]*)"')
_READ_VERBS = ("select", "health")
# simpg's reply when an insert lands on a standby (or a primary still
# in catchup): the signal that the state's primary is not yet
# writable and the request should park, not error
_READONLY_MARK = b"read-only"
_ERR_REPLICATE = (b'{"ok": false, "error": "router: replication '
                  b'streams are not proxied"}\n')
_ERR_PARK_BUDGET = (b'{"ok": false, "error": "router: no writable '
                    b'primary within park budget"}\n')

ROUTE_ERRORS = (OSError, asyncio.TimeoutError,
                asyncio.IncompleteReadError)


# the per-shard and map-level front doors are the SAME seams, so they
# share each failpoint through one call site (one seam, one name)
async def _accept_fault() -> str | None:
    return await faults.point("router.accept")


async def _park_fault() -> str | None:
    return await faults.point("router.park")

ROUTER_SCHEMA = {
    "type": "object",
    "required": ["listenPort", "coordCfg"],
    # one listener fronts either ONE shard (shardPath) or a whole
    # keyspace (shardMapPath, the resharder's map record)
    "anyOf": [
        {"required": ["shardPath"]},
        {"required": ["shardMapPath"]},
    ],
    "properties": {
        "name": {"type": "string"},
        "shardPath": {"type": "string"},
        "shardMapPath": {"type": "string"},
        "listenPort": {"type": "integer"},
        "listenHost": {"type": "string"},
        "statusPort": {"type": "integer"},
        "statusHost": {"type": "string"},
        "stalenessBudget": {"type": "number", "exclusiveMinimum": 0},
        "parkTimeout": {"type": "number", "exclusiveMinimum": 0},
        "relayTimeout": {"type": "number", "exclusiveMinimum": 0},
        "lagInterval": {"type": "number", "exclusiveMinimum": 0},
        "maxIdlePerPeer": {"type": "integer", "minimum": 0},
        "faults": {"type": "array", "items": {"type": "string"}},
        "faultsEnabled": {"type": "boolean"},
        "coordCfg": {
            "type": "object",
            "anyOf": [
                {"required": ["host", "port"]},
                {"required": ["connStr"]},
            ],
        },
    },
}

ROUTER_FLEET_SCHEMA = {
    "type": "object",
    "required": ["shards", "coordCfg"],
    "properties": {
        "shards": {
            "type": "array",
            "minItems": 1,
            "items": {"type": "object",
                      "required": ["shardPath", "listenPort"]},
        },
        "coordCfg": ROUTER_SCHEMA["properties"]["coordCfg"],
        "listenHost": {"type": "string"},
        "statusPort": {"type": "integer"},
        "statusHost": {"type": "string"},
        "stalenessBudget": {"type": "number", "exclusiveMinimum": 0},
        "parkTimeout": {"type": "number", "exclusiveMinimum": 0},
        "relayTimeout": {"type": "number", "exclusiveMinimum": 0},
        "lagInterval": {"type": "number", "exclusiveMinimum": 0},
        "maxIdlePerPeer": {"type": "integer", "minimum": 0},
        "faults": {"type": "array", "items": {"type": "string"}},
        "faultsEnabled": {"type": "boolean"},
    },
}


def router_shard_configs(cfg: dict) -> list[dict]:
    """The fleet merge, sitter/prober-style: shared base + per-shard
    overrides; duplicate names/paths/ports are config errors."""
    if not isinstance(cfg.get("shards"), list):
        one = dict(cfg)
        one["name"] = str(cfg.get("name")
                          or cfg["shardPath"].strip("/").replace("/", "-"))
        return [one]
    base = {k: v for k, v in cfg.items() if k != "shards"}
    merged, names, paths, ports = [], set(), set(), set()
    for i, entry in enumerate(cfg["shards"]):
        c = dict(base)
        c.update(entry)
        if not c.get("shardPath"):
            raise ConfigError("router shard %d has no shardPath" % i)
        if not c.get("listenPort"):
            raise ConfigError("router shard %d has no listenPort" % i)
        name = str(c.get("name")
                   or c["shardPath"].strip("/").replace("/", "-"))
        c["name"] = name
        if name in names:
            raise ConfigError("duplicate router shard name %r" % name)
        if c["shardPath"] in paths:
            raise ConfigError("duplicate router shardPath %r"
                              % c["shardPath"])
        if c["listenPort"] in ports:
            raise ConfigError("duplicate router listenPort %r"
                              % c["listenPort"])
        names.add(name)
        paths.add(c["shardPath"])
        ports.add(c["listenPort"])
        merged.append(c)
    return merged


class RouteTable:
    """One immutable routing decision: built once per state watch or
    lag-set change, consulted (never recomputed) per request."""

    __slots__ = ("gen", "primary", "primary_id", "readers", "_rr")

    def __init__(self, gen: int, primary: tuple | None,
                 primary_id: str | None,
                 readers: tuple[tuple[str, tuple], ...]):
        self.gen = gen
        self.primary = primary          # (host, port) or None
        self.primary_id = primary_id
        self.readers = readers          # ((peer_id, (host, port)), ...)
        self._rr = 0

    def read_pick(self) -> tuple[str, tuple] | None:
        """Next (peer_id, addr) round-robin, or None when the read
        set is empty (caller falls back to the primary)."""
        n = len(self.readers)
        if not n:
            return None
        i = self._rr
        self._rr = (i + 1) % n
        return self.readers[i % n]

    def signature(self) -> tuple:
        return (self.primary, self.primary_id, self.readers)


class UpstreamPool:
    """Pooled upstream (reader, writer) pairs per peer address.  A
    request costs a checkout, not a dial; relays that fail discard the
    connection so a stale pooled socket can never serve twice."""

    def __init__(self, shard: str, max_idle: int = DEFAULT_MAX_IDLE):
        self.shard = shard
        self.max_idle = max_idle
        self._idle: dict[tuple, list] = {}
        self._peer_of: dict[tuple, str] = {}

    async def acquire(self, addr: tuple, peer: str):
        self._peer_of[addr] = peer
        idle = self._idle.get(addr)
        while idle:
            reader, writer = idle.pop()
            _POOLED.set(len(idle), shard=self.shard, peer=peer)
            if reader.at_eof() or writer.is_closing():
                writer.close()
                continue
            return reader, writer
        _DIALS.inc(shard=self.shard, peer=peer)
        return await asyncio.wait_for(
            asyncio.open_connection(addr[0], addr[1]),
            UPSTREAM_DIAL_TIMEOUT)

    def release(self, addr: tuple, conn) -> None:
        idle = self._idle.setdefault(addr, [])
        if len(idle) < self.max_idle and not conn[0].at_eof():
            idle.append(conn)
        else:
            conn[1].close()
        _POOLED.set(len(idle), shard=self.shard,
                    peer=self._peer_of.get(addr, "?"))

    def discard(self, conn) -> None:
        with contextlib.suppress(Exception):
            conn[1].close()

    def invalidate(self, addr: tuple) -> None:
        """Close every idle connection to *addr* (the old primary's
        pool is garbage the moment a failover starts)."""
        for conn in self._idle.pop(addr, []):
            self.discard(conn)
        _POOLED.set(0, shard=self.shard,
                    peer=self._peer_of.get(addr, "?"))

    def close_all(self) -> None:
        for addr in list(self._idle):
            self.invalidate(addr)


class ShardRouter:
    """The front door for ONE shard: a TCP listener relaying the simpg
    line protocol against a route table maintained from the shard's
    cluster-state watch and the replicas' scraped lag."""

    def __init__(self, cfg: dict, *, http_get=None):
        self.name = cfg["name"]
        self.path = cfg["shardPath"]
        self.listen_host = cfg.get("listenHost", "0.0.0.0")
        self.listen_port = int(cfg["listenPort"])
        self.budget = float(cfg.get("stalenessBudget",
                                    DEFAULT_STALENESS_BUDGET))
        self.park_timeout = float(cfg.get("parkTimeout",
                                          DEFAULT_PARK_TIMEOUT))
        self.relay_timeout = float(cfg.get("relayTimeout",
                                           DEFAULT_RELAY_TIMEOUT))
        self.lag_interval = float(cfg.get("lagInterval",
                                          DEFAULT_LAG_INTERVAL))
        coord = cfg.get("coordCfg") or {}
        self._connstr = coord.get("connStr") or \
            ("%s:%d" % (coord["host"], int(coord["port"]))
             if coord else "")
        self._session_timeout = float(coord.get("sessionTimeout", 60.0))
        grace = coord.get("disconnectGrace")
        self._disconnect_grace = None if grace is None else float(grace)
        self._http_get = http_get or _http_get_text
        self._pool = UpstreamPool(
            self.name, int(cfg.get("maxIdlePerPeer", DEFAULT_MAX_IDLE)))
        self._handle = None
        self._dirty = True
        self._wake = asyncio.Event()
        self._wake.set()
        self._change = asyncio.Event()
        self._primary_addr: tuple | None = None
        self._primary_id: str | None = None
        self._replicas: list[dict] = []
        self._lag: dict[str, float] = {}
        self._down: dict[str, float] = {}
        self._gen = 0
        self._table = RouteTable(0, None, None, ())
        self._server = None
        self._topo_task: asyncio.Task | None = None
        self._lag_task: asyncio.Task | None = None

    # -- lifecycle --

    async def start(self, *, topology: bool = True,
                    listen: bool = True) -> None:
        """Bind the listener; with *topology* (the daemon path) also
        start the state watch and lag loops.  Tests drive the table
        directly via :meth:`apply_state` with ``topology=False``.
        Map mode runs children with ``listen=False`` — the map router
        owns the one socket and hands lines straight to
        :meth:`_route_one`."""
        if listen:
            self._server = await asyncio.start_server(
                self._serve_client, self.listen_host, self.listen_port)
            if self.listen_port == 0:
                self.listen_port = \
                    self._server.sockets[0].getsockname()[1]
        if topology:
            self._topo_task = asyncio.create_task(self._topo_loop())
            self._lag_task = asyncio.create_task(self._lag_loop())
        if listen:
            log.info("router %s listening on %s:%d", self.name,
                     self.listen_host, self.listen_port)

    async def stop(self) -> None:
        for task in (self._topo_task, self._lag_task):
            # re-issuing cancel: one cancel can be swallowed by the
            # wait_for race under the relay/scrape awaits (utils/aio)
            await cancel_and_wait(task)
        self._topo_task = self._lag_task = None
        if self._server is not None:
            self._server.close()
            with contextlib.suppress(Exception):
                await self._server.wait_closed()
            self._server = None
        if self._handle is not None:
            with contextlib.suppress(Exception):
                await self._handle.close()
            self._handle = None
        self._pool.close_all()

    # -- topology --

    def _on_change(self, _ev) -> None:
        self._dirty = True
        self._wake.set()

    async def _topo_loop(self) -> None:
        while True:
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._wake.wait(), 1.0)
            self._wake.clear()
            if not self._dirty:
                continue
            try:
                await self._refresh_topology()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("topology refresh failed on %s: %s",
                            self.name, e)
                await asyncio.sleep(0.2)

    async def _refresh_topology(self) -> None:
        if self._handle is None:
            self._handle = await mux_handle(
                self._connstr,
                session_timeout=self._session_timeout,
                disconnect_grace=self._disconnect_grace,
                name="router:%s" % self.name)
            self._handle.on_session_event(self._on_change)
        try:
            data, _ver = await self._handle.get(
                self.path + "/state", watch=self._on_change)
        except NoNodeError:
            self._dirty = True      # watch did not arm: keep polling
            self.apply_state({})
            return
        except CoordError:
            with contextlib.suppress(Exception):
                await self._handle.close()
            self._handle = None
            self._dirty = True
            raise
        self._dirty = False
        self.apply_state(json.loads(data.decode()))

    def apply_state(self, state: dict) -> None:
        """Fold one cluster state into the route table (the state
        watch's landing point, and the test seam).  Peers no longer in
        the chain are evicted here — passive lag inference: a deposed
        peer is stale by definition, no scrape needed."""
        prim = state.get("primary") or {}
        if prim.get("pgUrl"):
            _s, host, port = parse_pg_url(prim["pgUrl"])
            new_addr = (host, port)
            if (self._primary_addr is not None
                    and new_addr != self._primary_addr):
                # the old primary's pooled connections are garbage
                self._pool.invalidate(self._primary_addr)
            self._primary_addr = new_addr
            self._primary_id = prim.get("id") or prim["pgUrl"]
        else:
            self._primary_addr = self._primary_id = None
        reps = []
        for p in [state.get("sync")] + list(state.get("async") or []):
            if not (p and p.get("pgUrl")):
                continue
            _s, host, port = parse_pg_url(p["pgUrl"])
            reps.append({"id": p.get("id") or p["pgUrl"],
                         "addr": (host, port), "pgUrl": p["pgUrl"]})
        self._replicas = reps
        live = {r["id"] for r in reps}
        self._lag = {p: v for p, v in self._lag.items() if p in live}
        self._rebuild("state")

    def _rebuild(self, reason: str) -> None:
        """Serialize-once: the ONLY place a routing decision is
        computed.  Everything on the relay path reads the resulting
        immutable table."""
        now = time.monotonic()
        self._down = {p: t for p, t in self._down.items() if t > now}
        with span("router.route", shard=self.name, reason=reason,
                  primary=self._primary_id or ""):
            readers = []
            for rep in self._replicas:
                pid = rep["id"]
                if pid in self._down:
                    continue
                lag = self._lag.get(pid)
                if lag is not None and lag > self.budget:
                    continue
                readers.append((pid, rep["addr"]))
            self._gen += 1
            table = RouteTable(self._gen, self._primary_addr,
                               self._primary_id, tuple(readers))
        changed = table.signature() != self._table.signature()
        self._table = table
        _REBUILDS.inc(shard=self.name)
        _READ_PEERS.set(len(readers), shard=self.name)
        if changed:
            get_journal().record(
                "router.route_change", shard=self.name, reason=reason,
                gen=table.gen, primary=self._primary_id,
                readers=[p for p, _ in readers])
            old = self._change
            self._change = asyncio.Event()
            old.set()       # wake every parked request

    def _mark_down(self, peer: str) -> None:
        self._down[peer] = time.monotonic() + DOWN_COOLDOWN
        self._rebuild("peer-down")

    def _suspect_primary(self, addr: tuple | None) -> None:
        """A failed write relay is the moment to re-learn the primary
        (the prober's rule) — and to drop its pooled connections."""
        if addr is not None:
            self._pool.invalidate(addr)
        self._dirty = True
        self._wake.set()

    # -- lag feed (active scrape, prober-style) --

    async def _lag_loop(self) -> None:
        while True:
            await asyncio.sleep(self.lag_interval)
            try:
                await self._refresh_lag()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.debug("lag refresh failed on %s: %s", self.name, e)

    async def _refresh_lag(self) -> None:
        changed = False
        for rep in list(self._replicas):
            pid = rep["id"]
            try:
                host, port = rep["addr"]
                text = await self._http_get(
                    "http://%s:%d/metrics" % (host, port + 1))
            except asyncio.CancelledError:
                raise
            except Exception:
                continue
            lag = _parse_lag_gauge(text)
            if lag is None:
                continue
            old = self._lag.get(pid)
            self._lag[pid] = lag
            _ROUTER_LAG.set(lag, shard=self.name, peer=pid)
            was_ok = old is None or old <= self.budget
            now_ok = lag <= self.budget
            if was_ok != now_ok:
                changed = True
        if changed:
            self._rebuild("lag")

    # -- the relay path --

    async def _serve_client(self, reader, writer) -> None:
        _CONNS.inc(shard=self.name)
        try:
            if await _accept_fault() == "drop":
                return
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    reply = await self._route_one(line)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    reply = (json.dumps(
                        {"ok": False,
                         "error": "router: %s" % e})
                        .encode() + b"\n")
                if reply is None:
                    continue        # black-holed (drop): no reply
                writer.write(reply)
                await writer.drain()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.debug("client connection on %s closed: %s",
                      self.name, e)
        finally:
            _CONNS.dec(shard=self.name)
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    async def _route_one(self, line: bytes) -> bytes | None:
        m = _OP_RE.search(line)
        verb = m.group(1).decode() if m else "unknown"
        if await faults.point("router.relay") == "drop":
            return None
        if verb == "replicate":
            _ROUTED.inc(shard=self.name, verb=verb, peer="refused")
            return _ERR_REPLICATE
        if verb in _READ_VERBS:
            return await self._relay_read(line, verb)
        return await self._relay_write(line, verb)

    async def _relay_read(self, line: bytes, verb: str) -> bytes:
        table = self._table
        for _ in range(len(table.readers) + 1):
            picked = table.read_pick()
            if picked is None:
                break
            peer, addr = picked
            try:
                reply = await self._relay(addr, peer, line)
            except ROUTE_ERRORS:
                self._mark_down(peer)
                table = self._table
                continue
            _ROUTED.inc(shard=self.name, verb=verb, peer=peer)
            return reply
        # no eligible replica: the primary serves reads too
        return await self._relay_write(line, verb)

    async def _relay_write(self, line: bytes, verb: str) -> bytes:
        """Primary-pinned relay with park/replay: a request that finds
        no writable primary is HELD — drained out of the error path —
        until the topology watch lands a new one, then replayed."""
        t0 = None
        while True:
            table = self._table
            addr = table.primary
            if addr is not None:
                try:
                    reply = await self._relay(
                        addr, table.primary_id or "?", line)
                except ROUTE_ERRORS:
                    self._suspect_primary(addr)
                else:
                    if (verb == "insert"
                            and _READONLY_MARK in reply):
                        # state says primary, pg still in catchup:
                        # park and replay, don't bounce the error
                        self._dirty = True
                        self._wake.set()
                    else:
                        _ROUTED.inc(shard=self.name, verb=verb,
                                    peer=table.primary_id or "?")
                        if t0 is not None:
                            self._close_park(t0, verb, replayed=True)
                        return reply
            if t0 is None:
                await _park_fault()
                t0 = time.monotonic()
                _PARKED.inc(shard=self.name)
            if time.monotonic() - t0 >= self.park_timeout:
                self._close_park(t0, verb, replayed=False)
                return _ERR_PARK_BUDGET
            change = self._change
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(change.wait(), PARK_POLL)

    def _close_park(self, t0: float, verb: str,
                    *, replayed: bool) -> None:
        held = time.monotonic() - t0
        _PARKED.dec(shard=self.name)
        _PARK_SECONDS.observe(held, shard=self.name)
        get_journal().record("router.park", shard=self.name,
                             verb=verb, seconds=round(held, 3),
                             replayed=replayed)

    async def _relay(self, addr: tuple, peer: str,
                     line: bytes) -> bytes:
        conn = await self._pool.acquire(addr, peer)
        try:
            reply = None
            conn[1].write(line)
            await conn[1].drain()
            reply = await asyncio.wait_for(conn[0].readline(),
                                           self.relay_timeout)
            if not reply:
                reply = None
                raise ConnectionResetError("upstream closed")
        finally:
            # success returns the conn to the pool; any failure (error,
            # timeout, cancellation) discards it — a half-read stream
            # must never be reused
            if reply is None:
                self._pool.discard(conn)
            else:
                self._pool.release(addr, conn)
        return reply

    # -- status --

    def describe(self) -> dict:
        table = self._table
        return {
            "shard": self.name,
            "listen": "%s:%d" % (self.listen_host, self.listen_port),
            "gen": table.gen,
            "primary": table.primary_id,
            "readers": [
                {"peer": p, "lag": self._lag.get(p)}
                for p, _a in table.readers],
            "connections": _CONNS.value(shard=self.name),
            "parked": _PARKED.value(shard=self.name),
            "routed": sum(
                v for labels, v in _ROUTED.samples()
                if labels.get("shard") == self.name),
            "parks": _PARK_SECONDS.snapshot(shard=self.name)["count"],
        }


_LAG_RE = re.compile(
    r'^manatee_replication_lag_seconds\{[^}]*\}\s+([0-9.eE+-]+)\s*$',
    re.M)


def _parse_lag_gauge(text: str) -> float | None:
    m = _LAG_RE.search(text)
    return float(m.group(1)) if m else None


async def _http_get_text(url: str, timeout: float = 2.0) -> str:
    import aiohttp
    tmo = aiohttp.ClientTimeout(total=timeout)
    async with aiohttp.ClientSession(timeout=tmo) as http:
        async with http.get(url) as resp:
            return await resp.text()


# ---- shard-map mode (manatee-adm reshard's data-plane half) ----

class ShardMapRouter:
    """One listener fronting a keyspace: routes each request line to
    the shard whose map range owns the sniffed key, against the
    versioned shard-map record the resharder maintains.

    The map is compiled exactly like a shard's route table — once per
    watch fire, never per request (:meth:`apply_map` is the landing
    point and the test seam).  Child :class:`ShardRouter` instances
    (one per shard the map names, listener-less) do the actual
    relaying, so parking, pooling, staleness bounds and lag scrapes
    all carry over unchanged; children ride the same mux'd
    coordination session as the map watch.

    The reshard cutover contract lives here:

    - a range in state ``frozen`` parks WRITES at the map layer (the
      child never sees them) until a map change re-homes the range —
      the same drain-and-replay a failover gets, bounded by the same
      ``parkTimeout``;
    - ``inflight_writes`` counts writes between owner lookup and
      relay completion, bumped in the same event-loop tick as the
      lookup, so once the resharder sees the frozen epoch compiled
      AND the count at zero, no write can still be bound for the old
      owner (the drain barrier `_drain_routers` polls);
    - reads keep flowing to a frozen range — the source stays
      readable through the cutover window.
    """

    def __init__(self, cfg: dict, *, http_get=None):
        self.name = str(cfg.get("name") or "map")
        self.map_path = cfg["shardMapPath"]
        self.listen_host = cfg.get("listenHost", "0.0.0.0")
        self.listen_port = int(cfg["listenPort"])
        self.park_timeout = float(cfg.get("parkTimeout",
                                          DEFAULT_PARK_TIMEOUT))
        coord = cfg.get("coordCfg") or {}
        self._connstr = coord.get("connStr") or \
            ("%s:%d" % (coord["host"], int(coord["port"]))
             if coord else "")
        self._session_timeout = float(coord.get("sessionTimeout", 60.0))
        grace = coord.get("disconnectGrace")
        self._disconnect_grace = None if grace is None else float(grace)
        self._http_get = http_get
        # per-shard child config base: everything but the map/listen
        # identity (children are listener-less, port 0 placates the
        # schema-shaped ctor)
        self._child_base = {
            k: v for k, v in cfg.items()
            if k not in ("shardMapPath", "shardPath", "name",
                         "listenPort", "statusPort", "statusHost",
                         "faults", "faultsEnabled")}
        self._child_base["listenPort"] = 0
        self._handle = None
        self._dirty = True
        self._wake = asyncio.Event()
        self._wake.set()
        self._map_change = asyncio.Event()
        self._epoch = 0
        self._ranges: tuple[dict, ...] = ()
        self._children: dict[str, ShardRouter] = {}
        self._inflight: dict[str, int] = {}
        self._server = None
        self._map_task: asyncio.Task | None = None

    # -- lifecycle --

    async def start(self, *, topology: bool = True) -> None:
        self._server = await asyncio.start_server(
            self._serve_client, self.listen_host, self.listen_port)
        if self.listen_port == 0:
            self.listen_port = \
                self._server.sockets[0].getsockname()[1]
        if topology:
            self._map_task = asyncio.create_task(self._map_loop())
        log.info("map router listening on %s:%d (map %s)",
                 self.listen_host, self.listen_port, self.map_path)

    async def stop(self) -> None:
        if self._map_task is not None:
            await cancel_and_wait(self._map_task)
            self._map_task = None
        if self._server is not None:
            self._server.close()
            with contextlib.suppress(Exception):
                await self._server.wait_closed()
            self._server = None
        for child in self._children.values():
            await child.stop()
        self._children.clear()
        if self._handle is not None:
            with contextlib.suppress(Exception):
                await self._handle.close()
            self._handle = None

    # -- the map watch --

    def _on_change(self, _ev) -> None:
        self._dirty = True
        self._wake.set()

    async def _map_loop(self) -> None:
        while True:
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(self._wake.wait(), 1.0)
            self._wake.clear()
            if not self._dirty:
                continue
            try:
                await self._refresh_map()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("shard-map refresh failed: %s", e)
                await asyncio.sleep(0.2)

    async def _refresh_map(self) -> None:
        if self._handle is None:
            self._handle = await mux_handle(
                self._connstr,
                session_timeout=self._session_timeout,
                disconnect_grace=self._disconnect_grace,
                name="router:%s" % self.name)
            self._handle.on_session_event(self._on_change)
        try:
            data, _ver = await self._handle.get(
                self.map_path, watch=self._on_change)
        except NoNodeError:
            # map not initialized yet (or torn down): keep the last
            # compiled routes and keep polling for it to appear
            self._dirty = True
            return
        except CoordError:
            with contextlib.suppress(Exception):
                await self._handle.close()
            self._handle = None
            self._dirty = True
            raise
        self._dirty = False
        await self.apply_map(json.loads(data.decode()))

    async def apply_map(self, m: dict) -> None:
        """Fold one shard map into the route state (the watch's
        landing point, and the test seam): validate, reconcile the
        child-router set against the shards the map names, publish the
        new ranges, wake every parked writer.  An invalid map keeps
        the last good routes — a half-written record must degrade to
        staleness, never to misrouting."""
        from manatee_tpu.reshard.plan import validate_map
        try:
            validate_map(m)
        except Exception as e:
            log.warning("refusing invalid shard map: %s", e)
            return
        want = {r["shard"]: r["shardPath"] for r in m["ranges"]}
        for name in [n for n in self._children if n not in want]:
            old = self._children.pop(name)
            self._inflight.pop(name, None)
            await old.stop()
        for name, path in want.items():
            child = self._children.get(name)
            if child is not None and child.path != path:
                await child.stop()
                del self._children[name]
                child = None
            if child is None:
                ccfg = dict(self._child_base)
                ccfg["name"] = name
                ccfg["shardPath"] = path
                child = ShardRouter(ccfg, http_get=self._http_get)
                await child.start(topology=True, listen=False)
                self._children[name] = child
        old_epoch = self._epoch
        self._ranges = tuple(dict(r) for r in m["ranges"])
        self._epoch = int(m.get("epoch", 0))
        _MAP_EPOCH.set(self._epoch)
        if self._epoch != old_epoch:
            _MAP_CHANGES.inc()
            get_journal().record(
                "router.map_change", epoch=self._epoch,
                shards=sorted(want),
                frozen=sorted(r["shard"] for r in self._ranges
                              if r["state"] != "serving"))
            old = self._map_change
            self._map_change = asyncio.Event()
            old.set()       # wake writes parked on a frozen range

    def _owner(self, key: str | None) -> dict | None:
        """The per-request routing decision: a scan of the compiled
        ranges (maps are a handful of entries; no tree needed).  A
        keyless line belongs to the first range — keyless traffic is
        health checks and tail reads, and ONE consistent answer
        matters more than which one."""
        ranges = self._ranges
        if not ranges:
            return None
        if key is None:
            return ranges[0]
        for r in ranges:
            if (not r["lo"] or r["lo"] <= key) and \
                    (r.get("hi") is None or key < r["hi"]):
                return r
        return ranges[0]

    # -- the relay path --

    async def _serve_client(self, reader, writer) -> None:
        _CONNS.inc(shard=self.name)
        try:
            if await _accept_fault() == "drop":
                return
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    reply = await self._route_one(line)
                except asyncio.CancelledError:
                    raise
                except Exception as e:
                    reply = (json.dumps(
                        {"ok": False,
                         "error": "router: %s" % e})
                        .encode() + b"\n")
                if reply is None:
                    continue        # black-holed (drop): no reply
                writer.write(reply)
                await writer.drain()
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.debug("client connection on %s closed: %s",
                      self.name, e)
        finally:
            _CONNS.dec(shard=self.name)
            writer.close()
            with contextlib.suppress(Exception):
                await writer.wait_closed()

    async def _route_one(self, line: bytes) -> bytes | None:
        m = _OP_RE.search(line)
        verb = m.group(1).decode() if m else "unknown"
        k = _KEY_RE.search(line)
        key = k.group(1).decode() if k else None
        is_write = verb not in _READ_VERBS and verb != "replicate"
        t0 = None
        label = self.name
        while True:
            # owner lookup and the inflight bump happen in ONE event-
            # loop tick (no await between them): a status poll showing
            # {frozen epoch compiled, inflight 0} therefore proves no
            # write that saw the old serving state is still pending
            rng = self._owner(key)
            if rng is not None:
                label = rng["shard"]
                child = self._children.get(label)
                if child is not None and (
                        not is_write or rng["state"] == "serving"):
                    self._inflight[label] = \
                        self._inflight.get(label, 0) + 1
                    try:
                        reply = await child._route_one(line)
                    finally:
                        self._inflight[label] -= 1
                    if t0 is not None:
                        self._close_park(t0, label, verb)
                    return reply
            # a write bound for a frozen range (or any line with no
            # routable owner yet): park for the map change, exactly
            # the failover hold
            if t0 is None:
                await _park_fault()
                t0 = time.monotonic()
                _PARKED.inc(shard=label)
            if time.monotonic() - t0 >= self.park_timeout:
                held = time.monotonic() - t0
                _PARKED.dec(shard=label)
                _PARK_SECONDS.observe(held, shard=label)
                get_journal().record(
                    "router.park", shard=label, verb=verb,
                    seconds=round(held, 3), replayed=False)
                return _ERR_PARK_BUDGET
            change = self._map_change
            with contextlib.suppress(asyncio.TimeoutError):
                await asyncio.wait_for(change.wait(), PARK_POLL)

    def _close_park(self, t0: float, label: str, verb: str) -> None:
        held = time.monotonic() - t0
        _PARKED.dec(shard=label)
        _PARK_SECONDS.observe(held, shard=label)
        get_journal().record("router.park", shard=label, verb=verb,
                             seconds=round(held, 3), replayed=True)

    # -- status --

    def describe_map(self) -> dict:
        """The ``map`` section of /status — the resharder's drain
        barrier reads exactly this shape."""
        return {
            "epoch": self._epoch,
            "path": self.map_path,
            "listen": "%s:%d" % (self.listen_host, self.listen_port),
            "ranges": [
                {"lo": r["lo"], "hi": r.get("hi"),
                 "shard": r["shard"], "state": r["state"]}
                for r in self._ranges],
            "shards": {
                name: dict(child.describe(),
                           inflight_writes=self._inflight.get(name, 0))
                for name, child in self._children.items()},
        }


# ---- the router's own HTTP listener ----

class RouterServer:
    """The control listener (NOT the data path): /status renders every
    shard's live route table; the shared obs routes make the router
    scrapeable/drillable exactly like every other daemon."""

    def __init__(self, routers: list[ShardRouter], *,
                 host: str = "0.0.0.0", port: int = 0,
                 map_router: ShardMapRouter | None = None):
        from aiohttp import web
        self._web = web
        self.routers = routers
        self.map_router = map_router
        self.host = host
        self.port = port
        self._runner = None
        app = web.Application()
        app.router.add_get("/", self._routes)
        app.router.add_get("/status", self._status)
        self._obs_routes = attach_obs_routes(app, metrics=True)
        self._app = app

    async def start(self) -> None:
        web = self._web
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        log.info("router control listening on %s:%d (%d shards)",
                 self.host, self.port, len(self.routers))

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _routes(self, _req):
        return self._web.json_response(["/status"] + self._obs_routes)

    async def _status(self, _req):
        body = {"now": round(time.time(), 3),
                "shards": [r.describe() for r in self.routers]}
        if self.map_router is not None:
            # the map section IS the resharder's drain barrier; the
            # flat shards list keeps map-mode /status shaped like
            # every other router's for the generic tooling
            body["map"] = self.map_router.describe_map()
            body["shards"] = [
                c.describe()
                for c in self.map_router._children.values()]
        return self._web.json_response(body)


# ---- daemon wiring ----

async def start_router(cfg: dict):
    host = cfg.get("statusHost", "0.0.0.0")
    port = int(cfg.get("statusPort", 0))
    set_peer("router:%d" % port if port else "router")
    faults.arm_specs(cfg.get("faults"), source="config")
    if cfg.get("faultsEnabled"):
        faults.enable_http()
    intro = start_daemon_introspection(cfg)
    if cfg.get("shardMapPath"):
        # map mode: one listener over the whole keyspace; per-shard
        # children are reconciled from the watched map record
        map_router = ShardMapRouter(cfg)
        server = RouterServer([], host=host, port=port,
                              map_router=map_router)
        await server.start()
        await map_router.start()
        log.info("router fronting shard map %s", cfg["shardMapPath"])

        async def stop():
            await map_router.stop()
            await server.stop()
            await intro.stop()

        return stop
    shard_cfgs = router_shard_configs(cfg)
    routers = [ShardRouter(c) for c in shard_cfgs]
    server = RouterServer(routers, host=host, port=port)
    await server.start()
    for r in routers:
        await r.start()
    log.info("router fronting %d shards on one coordination "
             "connection", len(routers))

    async def stop():
        for r in routers:
            await r.stop()
        await server.stop()
        await intro.stop()

    return stop


def main(argv=None) -> None:
    daemon_main("manatee-router",
                "topology-aware connection front door (primary-pinned "
                "writes, staleness-bounded reads, park-don't-error "
                "failovers)",
                ROUTER_SCHEMA, start_router, argv,
                fleet_schema=ROUTER_FLEET_SCHEMA)


if __name__ == "__main__":
    main()
