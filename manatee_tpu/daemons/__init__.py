"""Daemon entry points (reference: sitter.js, backupserver.js,
snapshotter.js — one OS process each, supervisor-managed)."""
