"""manatee-backupserver — hosts the snapshot-send REST service.

Reference parity: backupserver.js — the REST server and the sender share
one queue (:120-123).
"""

from __future__ import annotations

import logging

from manatee_tpu.backup import BackupQueue, BackupRestServer, BackupSender
from manatee_tpu.daemons.common import (
    daemon_main,
    start_daemon_introspection,
)
from manatee_tpu.obs import set_peer
from manatee_tpu.shard import build_ident, build_storage

log = logging.getLogger("manatee.backupserver")

SCHEMA = {
    "type": "object",
    # postgresPort is part of the peer's identity (ip:pgPort:backupPort
    # — build_ident), which this daemon stamps on its spans; configgen
    # has always copied it into backupserver.json from the sitter's
    "required": ["ip", "postgresPort", "backupPort", "dataset"],
    "properties": {
        "ip": {"type": "string"},
        "postgresPort": {"type": "integer"},
        "backupPort": {"type": "integer"},
        "dataset": {"type": "string"},
    },
}


async def start_backupserver(cfg: dict):
    # the sitter's EXACT id (ip:pgPort:backupPort via the same
    # build_ident), so this process's backup.send spans merge under
    # the peer's identity in the `manatee-adm trace` fan-out
    set_peer(build_ident(cfg)["id"])
    # boot-time fault arming for THIS process (the sender's stream
    # faults live here, not in the sitter); runtime arming needs the
    # same explicit opt-in as the sitter
    from manatee_tpu import faults
    faults.arm_specs(cfg.get("faults"), source="config")
    if cfg.get("faultsEnabled"):
        faults.enable_http()
    storage = build_storage(cfg)
    queue = BackupQueue()
    # storage + dataset let the POST handler negotiate a common delta
    # base against our own snapshot list (incremental rebuild)
    server = BackupRestServer(queue,
                              host=cfg.get("listenHost", "0.0.0.0"),
                              port=int(cfg["backupPort"]),
                              storage=storage,
                              dataset=cfg["dataset"])
    sender = BackupSender(queue, storage, cfg["dataset"])
    intro = start_daemon_introspection(cfg)
    await server.start()
    sender.start()

    async def stop():
        await sender.stop()
        await server.stop()
        await intro.stop()

    return stop


def main(argv=None) -> None:
    daemon_main("manatee-backupserver", "manatee backup server",
                SCHEMA, start_backupserver, argv)


if __name__ == "__main__":
    main()
