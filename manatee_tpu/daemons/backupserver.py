"""manatee-backupserver — hosts the snapshot-send REST service.

Reference parity: backupserver.js — the REST server and the sender share
one queue (:120-123).
"""

from __future__ import annotations

import logging

from manatee_tpu.backup import BackupQueue, BackupRestServer, BackupSender
from manatee_tpu.daemons.common import daemon_main
from manatee_tpu.shard import build_storage

log = logging.getLogger("manatee.backupserver")

SCHEMA = {
    "type": "object",
    "required": ["ip", "backupPort", "dataset"],
    "properties": {
        "ip": {"type": "string"},
        "backupPort": {"type": "integer"},
        "dataset": {"type": "string"},
    },
}


async def start_backupserver(cfg: dict):
    storage = build_storage(cfg)
    queue = BackupQueue()
    server = BackupRestServer(queue,
                              host=cfg.get("listenHost", "0.0.0.0"),
                              port=int(cfg["backupPort"]))
    sender = BackupSender(queue, storage, cfg["dataset"])
    await server.start()
    sender.start()

    async def stop():
        await sender.stop()
        await server.stop()

    return stop


def main(argv=None) -> None:
    daemon_main("manatee-backupserver", "manatee backup server",
                SCHEMA, start_backupserver, argv)


if __name__ == "__main__":
    main()
