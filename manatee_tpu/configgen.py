"""Canonical per-peer config generation.

Reference parity: tools/mksitterconfig (:25-81) holds the reference's
canonical sitter-config template, and tools/mkdevsitters calls it per
peer.  Here the template lives in the package so the production CLI
(tools/mksitterconfig), the dev-cluster generator (tools/mkdevcluster),
and the tests all build configs from one source of truth.

Production defaults mirror etc/sitter.json / etc/backupserver.json /
etc/snapshotter.json: health 1 s / 5 s, ops/replication timeouts 60 s,
session timeout 60 s, disconnectGrace 10 s, hourly snapshots keeping
50.
"""

from __future__ import annotations

# production operational constants (etc/sitter.json)
PROD_DEFAULTS = {
    "opsTimeout": 60,
    "healthChkInterval": 1,
    "healthChkTimeout": 5,
    "replicationTimeout": 60,
    # bound on the restart-free pg_promote() wait before takeover
    # falls back to the restart path (VERDICT r4 weak #5)
    "promoteWait": 5,
    "sessionTimeout": 60,
    "disconnectGrace": 10,
    "pollInterval": 3600,
    "snapshotNumber": 50,
}


def _common(*, name: str, ip: str, pg_port: int, backup_port: int,
            dataset: str | None, data_dir: str,
            storage_backend: str, storage_root: str | None,
            pg_engine: str) -> dict:
    cfg = {
        "name": name,
        "zoneId": name,
        "ip": ip,
        "postgresPort": pg_port,
        "backupPort": backup_port,
        "dataDir": data_dir,
        "storageBackend": storage_backend,
        "pgEngine": pg_engine,
    }
    if dataset is not None:
        # backupserver/snapshotter schemas require a string dataset;
        # omit the key entirely rather than emit null
        cfg["dataset"] = dataset
    if storage_root is not None:
        cfg["storageRoot"] = storage_root
    return cfg


def build_sitter_config(*, name: str, ip: str, shard: str,
                        coord_connstr: str,
                        pg_port: int = 5432, backup_port: int = 12345,
                        zfs_port: int | None = None,
                        dataset: str | None = None,
                        data_dir: str = "/manatee/pg/data",
                        storage_backend: str = "zfs",
                        storage_root: str | None = None,
                        pg_engine: str = "postgres",
                        pg_bin_dir: str | None = None,
                        pg_version: str | None = None,
                        pg_conf_template: str | None = None,
                        pg_hba_file: str | None = None,
                        singleton: bool = False,
                        session_timeout: float | None = None,
                        disconnect_grace: float | None = None) -> dict:
    """The canonical sitter.json.  *coord_connstr* is ``host:port`` or
    a comma-separated ensemble list; single addresses are emitted as
    {host, port} (both shapes are accepted by the schema)."""
    cfg = _common(name=name, ip=ip, pg_port=pg_port,
                  backup_port=backup_port, dataset=dataset,
                  data_dir=data_dir, storage_backend=storage_backend,
                  storage_root=storage_root, pg_engine=pg_engine)
    if pg_bin_dir is not None:
        cfg["pgBinDir"] = pg_bin_dir
    if pg_version is not None:
        cfg["pgVersion"] = pg_version
    if pg_conf_template is not None:
        cfg["pgConfTemplate"] = pg_conf_template
    if pg_hba_file is not None:
        cfg["pgHbaFile"] = pg_hba_file

    coord: dict = {
        "sessionTimeout": (PROD_DEFAULTS["sessionTimeout"]
                           if session_timeout is None else session_timeout),
        "disconnectGrace": (PROD_DEFAULTS["disconnectGrace"]
                            if disconnect_grace is None
                            else disconnect_grace),
    }
    # validate with the SAME parser the daemons run (bare hosts get the
    # default port, empty members are skipped) so the generator never
    # rejects a string the runtime accepts, or vice versa
    from manatee_tpu.coord.client import parse_connstr
    try:
        members = parse_connstr(coord_connstr)
    except ValueError as exc:
        raise ValueError(
            "coordination address must be host[:port] or an "
            "h1:p1,h2:p2,... connection string (%s)" % exc) from None
    if any(not host for host, _ in members):
        raise ValueError(
            "coordination address has an empty host: %r" % coord_connstr)
    if "," in coord_connstr:
        coord["connStr"] = coord_connstr
    else:
        coord["host"], coord["port"] = members[0]

    cfg.update({
        "shardPath": "/manatee/%s" % shard,
        "zfsHost": ip,
        # status server is pgPort+1; the stream listener sits above it
        "zfsPort": zfs_port if zfs_port is not None else pg_port + 2,
        "coordCfg": coord,
        "opsTimeout": PROD_DEFAULTS["opsTimeout"],
        "healthChkInterval": PROD_DEFAULTS["healthChkInterval"],
        "healthChkTimeout": PROD_DEFAULTS["healthChkTimeout"],
        "replicationTimeout": PROD_DEFAULTS["replicationTimeout"],
        "promoteWait": PROD_DEFAULTS["promoteWait"],
        "oneNodeWriteMode": bool(singleton),
    })
    return cfg


def build_backupserver_config(sitter_cfg: dict) -> dict:
    """backupserver.json shares the peer's identity/storage block (the
    reference keeps backupPort identical across both files)."""
    keys = ("name", "zoneId", "ip", "postgresPort", "backupPort",
            "dataset", "dataDir", "storageBackend", "storageRoot",
            "pgEngine")
    return {k: sitter_cfg[k] for k in keys if k in sitter_cfg}


def build_snapshotter_config(sitter_cfg: dict, *,
                             poll_interval: float | None = None,
                             snapshot_number: int | None = None) -> dict:
    cfg = build_backupserver_config(sitter_cfg)
    cfg["pollInterval"] = (PROD_DEFAULTS["pollInterval"]
                           if poll_interval is None else poll_interval)
    cfg["snapshotNumber"] = (PROD_DEFAULTS["snapshotNumber"]
                             if snapshot_number is None
                             else snapshot_number)
    return cfg
