"""Backup job queue shared by the REST server and the sender.

Reference parity: lib/backupQueue.js — an EventEmitter FIFO; ``push``
notifies the sender (:56-67), jobs are looked up by uuid for status polls
(:96-110).  Job shape matches lib/backupServer.js:140-151: {uuid, host,
port, dataset, done: False | True | 'failed', size, completed}.
"""

from __future__ import annotations

import asyncio
import uuid as uuidlib
from dataclasses import dataclass, field
from typing import Callable


@dataclass
class BackupJob:
    host: str
    port: int
    dataset: str
    uuid: str = field(default_factory=lambda: str(uuidlib.uuid4()))
    done: bool | str = False          # False | True | 'failed'
    error: str | None = None
    size: int | None = None
    completed: int = 0
    # observability identity carried from the requester's POST: the
    # sender's backup.send span binds both, so the stream shows up in
    # the requester's restore tree despite living in another process
    trace: str | None = None
    span: str | None = None
    # wire codecs the REQUESTER can decode (storage.stream), best
    # first; the sender negotiates the actual stream codec from this.
    # Empty/None (an old peer's POST) means raw.
    compress: tuple = ()
    # stream-protocol generation the requester declared: >= 1 means it
    # probes for the wire header, so the sender may stamp the job uuid
    # (and a codec) on the stream; >= 2 means it also understands
    # delta streams.  0 = old peer = raw unstamped wire.
    stream_proto: int = 0
    # the negotiated common-base snapshot (POST-time intersection of
    # the requester's offer with our own snapshot list), or None for a
    # full stream.  The sender ships `zfs send -i base` / the dirstore
    # manifest delta when set.
    base: str | None = None

    def to_dict(self) -> dict:
        return {
            "uuid": self.uuid,
            "host": self.host,
            "port": self.port,
            "dataset": self.dataset,
            "done": self.done,
            "error": self.error,
            "size": self.size,
            "completed": self.completed,
            "trace": self.trace,
            "basis": "incremental" if self.base else "full",
            "base": self.base,
        }


class BackupQueue:
    def __init__(self):
        self._jobs: dict[str, BackupJob] = {}
        self._fifo: asyncio.Queue[BackupJob] = asyncio.Queue()
        self._push_cbs: list[Callable[[BackupJob], None]] = []

    def on_push(self, cb: Callable[[BackupJob], None]) -> None:
        self._push_cbs.append(cb)

    def push(self, job: BackupJob) -> None:
        self._jobs[job.uuid] = job
        self._fifo.put_nowait(job)
        for cb in list(self._push_cbs):
            cb(job)

    async def take(self) -> BackupJob:
        return await self._fifo.get()

    def get(self, uuid: str) -> BackupJob | None:
        return self._jobs.get(uuid)
