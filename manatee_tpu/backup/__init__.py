"""Backup/bootstrap plane (reference: lib/backupServer.js,
lib/backupQueue.js, lib/backupSender.js, lib/zfsClient.js restore path).

The bulk-data path of SURVEY.md §3.3: a joining/rebuilding peer opens a
TCP listener, POSTs a backup job to its upstream's backup server, and the
sender streams the latest storage snapshot into that socket while the
receiver pipes it into ``storage.recv``; job progress is observable over
the REST API and consumed by the manatee-adm rebuild progress bar.
"""

from manatee_tpu.backup.queue import BackupJob, BackupQueue
from manatee_tpu.backup.server import BackupRestServer
from manatee_tpu.backup.sender import BackupSender
from manatee_tpu.backup.client import RestoreClient, RestoreError

__all__ = [
    "BackupJob",
    "BackupQueue",
    "BackupRestServer",
    "BackupSender",
    "RestoreClient",
    "RestoreError",
]
