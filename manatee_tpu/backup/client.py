"""RestoreClient — bootstrap/rebuild a peer's dataset from an upstream's
backup server.

Reference parity: lib/zfsClient.js restore path —

- ``restore()``: isolate the old dataset → receive the stream → set
  mount properties → mount → take the initial post-restore snapshot
  (:115-207, :177-183);
- ``_receive()``: open a TCP listener, POST /backup {host, port,
  dataset} to the upstream's backup server, pipe the accepted socket
  into the storage receive, and poll GET <jobPath> until done/'failed'
  (:638-754, :765-886);
- ``isolateDataset({prefix})``: rename to
  ``<parent>/isolated/<prefix>-<ISO time>`` (:514-624).

The current restore job (with byte progress) is exposed for the status
server's GET /restore (lib/statusServer.js:111-121) and the manatee-adm
rebuild progress bar (lib/adm.js:1632-1658).
"""

from __future__ import annotations

import asyncio
import datetime
import errno
import logging

import aiohttp

from manatee_tpu import faults
from manatee_tpu.obs import (
    current_span_id,
    current_trace,
    get_journal,
    hlc_now,
    merge_remote,
    span,
)
from manatee_tpu.storage import stream as wirestream
from manatee_tpu.storage.base import StorageBackend, StreamIdMismatch
from manatee_tpu.utils.aio import cancel_requests

log = logging.getLogger("manatee.backup.client")


class RestoreError(Exception):
    pass


class DeltaRefused(RestoreError):
    """The sender's reply made the negotiated delta unusable (e.g. a
    base we never offered): the attempt must not consume that stream,
    but a FULL retry is still worth making — unlike a connectivity
    failure, which would fail identically on the retry."""


def _iso_now() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y%m%dT%H%M%S.%f")


class RestoreClient:
    def __init__(self, storage: StorageBackend, *, dataset: str,
                 mountpoint: str, listen_host: str = "127.0.0.1",
                 listen_port: int = 0, poll_interval: float = 1.0,
                 http_connect_timeout: float = 10.0,
                 http_read_timeout: float = 30.0):
        """*listen_host/port*: where the sender connects back (the
        zfsHost/zfsPort of etc/sitter.json).

        *http_connect_timeout*/*http_read_timeout*: per-socket budgets
        for the POST /backup and job-poll requests.  Deliberately NOT a
        ``total`` budget: a restore session legitimately spans hours,
        and a whole-request wall-clock cap (the old
        ``ClientTimeout(total=30)``) killed any transfer whose polling
        session outlived it — only silence (no connect, no bytes) is
        evidence of a dead upstream."""
        self.storage = storage
        self.dataset = dataset
        self.mountpoint = mountpoint
        self.listen_host = listen_host
        self.listen_port = listen_port
        self.poll_interval = poll_interval
        self.http_timeout = aiohttp.ClientTimeout(
            total=None, sock_connect=float(http_connect_timeout),
            sock_read=float(http_read_timeout))
        self.current_job: dict | None = None   # for GET /restore
        # monotonically numbers restore attempts so observers (the
        # rebuild CLI's RESTORE_RETRIES accounting, lib/adm.js:71) can
        # distinguish a NEW failed attempt from the same failed job
        self.attempts = 0
        # where the previous dataset went, when this restore isolated
        # one (set per attempt; full restores always isolate, delta
        # applies isolate only when the live dataset held the base)
        self.last_isolated: str | None = None

    async def isolate(self, prefix: str) -> str | None:
        """Move the current dataset out of the way; returns the isolated
        name (or None if the dataset didn't exist)."""
        if not await self.storage.exists(self.dataset):
            return None
        parent, _, _leaf = self.dataset.rpartition("/")
        iso_parent = (parent + "/isolated") if parent else "isolated"
        if not await self.storage.exists(iso_parent):
            await self.storage.create(iso_parent)
        target = "%s/%s-%s" % (iso_parent, prefix, _iso_now())
        await self.storage.rename(self.dataset, target)
        if await self.storage.is_mounted(target):
            await self.storage.unmount(target)
        log.info("isolated %s as %s", self.dataset, target)
        return target

    async def restore(self, backup_url: str, *,
                      isolate_prefix: str = "autorebuild",
                      incremental: bool = True,
                      fresh_snapshot: bool = False) -> None:
        """Restore from *backup_url* (the upstream PeerInfo's
        backupUrl).  With *incremental* (the default), local epoch-ms
        snapshots are offered as candidate delta bases in the POST;
        the sender picks the newest common one and ships only the
        delta.  No common base, an old peer on either side, or ANY
        failure along the incremental path degrades to the classic
        full stream — a bad base can cost a re-transfer, never a wrong
        dataset.

        *fresh_snapshot* asks the sender to snapshot its dataset at
        POST time before picking what to stream, so the transfer is
        current as of the request rather than the sender's last
        snapshotter tick — the reshard catch-up loop depends on this
        to converge on the write rate (an old server ignores the key
        and streams its latest existing snapshot)."""
        journal = get_journal()
        self.last_isolated = None
        bases, base_src = await self._delta_plan(incremental)
        journal.record("restore.receive.start", url=backup_url,
                       dataset=self.dataset, bases=len(bases))
        basis = "full"
        try:
            # one span for the whole snapshot transfer; its id rides
            # the POST so the sender's backup.send parents under it
            with span("restore.receive", url=backup_url,
                      dataset=self.dataset) as sp:
                if bases:
                    try:
                        basis = await self._receive(
                            backup_url, bases=bases, base_src=base_src,
                            isolate_prefix=isolate_prefix,
                            fresh_snapshot=fresh_snapshot)
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        # only failures SPECIFIC to the delta path are
                        # worth a full retry: the negotiation landed
                        # on incremental (or was itself unusable) and
                        # something after it went wrong.  A failure
                        # BEFORE that — a dead upstream, a refused
                        # POST — would fail the full retry identically
                        # and double the dead-upstream latency (and
                        # the rebuild CLI's failed-attempt budget).
                        delta_specific = (
                            isinstance(e, DeltaRefused)
                            or (self.current_job or {}).get("basis")
                            == "incremental")
                        if not delta_specific:
                            raise
                        # the partial (if any) was destroyed by
                        # recv_delta; whatever held the base is intact
                        # — retry the whole transfer full
                        log.warning("incremental restore failed (%s); "
                                    "retrying with a full stream", e)
                        journal.record("restore.delta.fallback",
                                       url=backup_url, error=str(e))
                        basis = await self._receive(
                            backup_url, isolate_prefix=isolate_prefix,
                            fresh_snapshot=fresh_snapshot)
                else:
                    basis = await self._receive(
                        backup_url, isolate_prefix=isolate_prefix,
                        fresh_snapshot=fresh_snapshot)
                sp.attrs["basis"] = basis
        except Exception as e:
            # the failed partial was cleaned by storage.recv; the
            # isolated dataset is left for operator recovery, as the
            # reference does
            journal.record("restore.receive.failed", url=backup_url,
                           error=str(e))
            raise
        journal.record(
            "restore.receive.done", url=backup_url, basis=basis,
            bytes=(self.current_job or {}).get("completed"))
        await self.storage.set_mountpoint(self.dataset, self.mountpoint)
        await self.storage.mount(self.dataset)
        await self.storage.snapshot(self.dataset)   # initial snapshot
        if self.last_isolated:
            log.info("restore complete; previous data preserved at %s",
                     self.last_isolated)

    async def destroy_isolated(self, isolated: str) -> None:
        await self.storage.destroy(isolated, recursive=True)

    async def _delta_plan(self, incremental: bool) \
            -> tuple[list[str], str | None]:
        """(candidate base names to offer, dataset holding them) — or
        ``([], None)`` when this restore must be full: incremental
        disabled, backend without delta support, nothing to offer, or
        half-applied debris from a crashed previous apply (doubt)."""
        if not incremental or not self.storage.supports_delta():
            return [], None
        try:
            if await self.storage.sweep_delta_debris(self.dataset):
                log.warning("swept a half-applied delta of %s; "
                            "forcing a FULL restore", self.dataset)
                get_journal().record("restore.delta.debris_swept",
                                     dataset=self.dataset)
                return [], None
            bases, src = await self.storage.delta_candidates(
                self.dataset, await self._newest_isolated())
            # newest first, capped: the server picks the newest common
            # one anyway, and the offer must stay a bounded request
            return sorted(bases, reverse=True)[:32], src
        except asyncio.CancelledError:
            raise
        except Exception as e:
            log.warning("delta eligibility probe failed (%s); "
                        "full restore", e)
            return [], None

    async def _newest_isolated(self) -> str | None:
        """The newest dataset `manatee-adm rebuild` isolated (prefix
        ``rebuild-``): its snapshots can still serve as delta bases —
        that is exactly what makes an operator rebuild incremental.
        ``fullrebuild-`` isolations (the --full escape hatch) are
        never offered, AND a fullrebuild newer than every rebuild
        suppresses the older ones too: the newest isolation is the
        operator's latest word, and that word was 'full'."""
        parent, _, _leaf = self.dataset.rpartition("/")
        iso_parent = (parent + "/isolated") if parent else "isolated"
        if not await self.storage.exists(iso_parent):
            return None
        best: tuple[str, str, bool] | None = None   # (ts, name, full?)
        for k in await self.storage.list_children(iso_parent):
            leaf = k.rsplit("/", 1)[-1]
            for pfx, is_full in (("fullrebuild-", True),
                                 ("rebuild-", False)):
                if leaf.startswith(pfx):
                    ts = leaf[len(pfx):]
                    if best is None or ts > best[0]:
                        best = (ts, k, is_full)
                    break
        if best is None or best[2]:
            return None
        return best[1]

    async def _receive(self, backup_url: str, *,
                       bases: list[str] | None = None,
                       base_src: str | None = None,
                       isolate_prefix: str = "autorebuild",
                       fresh_snapshot: bool = False) -> str:
        recv_done: asyncio.Future = asyncio.get_running_loop() \
            .create_future()
        self.attempts += 1
        import uuid
        job: dict = {"done": False, "size": None, "completed": 0,
                     "url": backup_url, "attempt": self.attempts,
                     "basis": "full",
                     # globally unique, unlike the counter: a sitter
                     # restart mid-rebuild resets attempts to 1, and
                     # the CLI's failed-attempt dedup must not mistake
                     # the new sitter's failures for already-counted
                     # ones (code-review r5)
                     "id": uuid.uuid4().hex}
        self.current_job = job

        def progress(done: int, total: int | None) -> None:
            job["completed"] = done
            if total is not None:
                job["size"] = total

        # the recv runs in a server-spawned handler task that NOTHING
        # cancels by default: an abort of _receive (the watchdog's
        # forced restore being cancelled by a topology change, a
        # sender-failed poll) must cancel it explicitly — on
        # Python >= 3.12 server.wait_closed() waits for handler tasks,
        # so leaving it running would block the teardown (and any lock
        # the caller holds) for the remainder of a multi-hour transfer
        handler_tasks: set[asyncio.Task] = set()
        # OUR job's uuid, learned from the POST response: the stream
        # id the sender stamps on the dial-back must match it, or the
        # connection is a STALE job's (a cancelled predecessor whose
        # sender dialed the port we rebound) and must be refused.  The
        # dial-back can legitimately beat the POST response, so
        # handlers wait for the id before consuming a byte.
        expected = {"jobid": None}
        job_known = asyncio.Event()
        # how the accepted stream will be applied, decided from the
        # POST response BEFORE job_known opens the gate: the classic
        # full receive, or a delta apply against the negotiated base
        mode: dict = {"basis": "full", "base": None, "base_src": None}

        async def _handle(reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
            try:
                # drop = the accepted stream is severed before a byte
                # is consumed: the sender sees a broken pipe, the poll
                # loop sees its job fail — a died link mid-restore
                if await faults.point("backup.recv.stream") == "drop":
                    raise RestoreError(
                        "receive stream severed (fault)")
                try:
                    await asyncio.wait_for(job_known.wait(), 30)
                except asyncio.TimeoutError:
                    raise RestoreError(
                        "dial-back arrived but no job was ever "
                        "registered (stale sender?)") from None
                if mode["basis"] == "incremental":
                    await self.storage.recv_delta(
                        self.dataset, reader, base=mode["base"],
                        base_src=mode["base_src"],
                        progress_cb=progress,
                        expect_stream_id=expected["jobid"])
                else:
                    await self.storage.recv(
                        self.dataset, reader, progress_cb=progress,
                        expect_stream_id=expected["jobid"])
                if not recv_done.done():
                    recv_done.set_result(None)
            except asyncio.CancelledError:
                if not recv_done.done():
                    recv_done.cancel()
                raise
            except StreamIdMismatch as e:
                # a STALE job's dial-back (a cancelled predecessor's
                # sender reaching the port we rebound): drop just this
                # connection and keep listening for our own stream —
                # the stale sender sees a broken pipe and fails its
                # job, ours is still on its way
                log.warning("refused stale restore stream: %s", e)
            except Exception as e:
                if not recv_done.done():
                    recv_done.set_exception(e)
            finally:
                writer.close()

        def handle(reader: asyncio.StreamReader,
                   writer: asyncio.StreamWriter) -> None:
            # PLAIN callback: the task is created and registered
            # synchronously at accept time, so the teardown's cancel
            # sweep can never miss a handler whose coroutine body has
            # not run its first line yet
            t = asyncio.create_task(_handle(reader, writer))
            handler_tasks.add(t)

            def _done(task, w=writer):
                handler_tasks.discard(task)
                # a task cancelled before its FIRST step never runs
                # _handle's finally: close the accepted socket here
                # (idempotent) or it leaks and the sender stays
                # blocked writing into it
                try:
                    w.close()
                except Exception:
                    pass

            t.add_done_callback(_done)

        async def _bind():
            try:
                return await asyncio.start_server(
                    handle, self.listen_host, self.listen_port)
            except OSError as e:
                if e.errno != errno.EADDRINUSE or not self.listen_port:
                    raise
                # the configured port can be squatted by ANY local
                # socket — including a long-lived outbound connection
                # whose ephemeral local port landed on it (observed
                # live: a coordination session on the zfsPort wedged
                # every restore attempt for a minute).  The dial-back
                # port is advertised in each POST /backup body, so
                # nothing requires the configured one: fall back to an
                # ephemeral listener instead of retry-looping forever.
                log.warning("restore listener port %d busy (%s); "
                            "falling back to an ephemeral port",
                            self.listen_port, e)
                return await asyncio.start_server(
                    handle, self.listen_host, 0)

        # CANCEL-SAFE BIND.  loop.create_server's last step (3.10) is
        # an `await sleep(0)` AFTER the socket is bound and listening:
        # a cancellation landing exactly there (a topology change
        # cancelling this restore in its first milliseconds — routine
        # now that the takeover path is fast) raises out of
        # start_server with the live Server object LOST, leaking the
        # listening socket into the loop forever.  The leaked listener
        # then shadows every later restore ('address already in use')
        # and its orphan accept-handlers recv into the dataset behind
        # the next attempt's back.  So: never cancel the bind itself —
        # shield it, and on OUR cancellation await its (fast, local)
        # completion and close whatever materialized.
        bind = asyncio.create_task(_bind())
        try:
            server = await asyncio.shield(bind)
        except asyncio.CancelledError:
            try:
                srv = await asyncio.wait_for(asyncio.shield(bind), 10)
                srv.close()
            except asyncio.CancelledError:
                raise
            except Exception:
                bind.cancel()
                # reap: even a cancelled bind may hold a live server
                try:
                    srv = await bind
                    srv.close()
                except asyncio.CancelledError:
                    pass
                except Exception:
                    pass
            raise
        port = server.sockets[0].getsockname()[1]
        try:
            async with aiohttp.ClientSession(
                    timeout=self.http_timeout) as http:
                if await faults.point("backup.post") == "drop":
                    # black-holed request: what the sock_connect budget
                    # would surface for an unreachable backup server
                    raise asyncio.TimeoutError(
                        "POST %s/backup black-holed (fault)"
                        % backup_url.rstrip("/"))
                post_body = {"host": self.listen_host, "port": port,
                             "dataset": self.dataset,
                             # observability identity: the sender's
                             # span parents under our receive span
                             "trace": current_trace(),
                             "span": current_span_id(),
                             # causal identity: the server folds this
                             # in, so sender-side records order after
                             # our request (old servers ignore it)
                             "hlc": hlc_now(),
                             # wire codecs we can decode, best first;
                             # an old server ignores the key and
                             # streams raw (storage.stream)
                             "compress": wirestream.available_codecs(),
                             # we probe for the wire header, check
                             # stream ids, and apply delta streams
                             "streamProto": 2}
                if fresh_snapshot:
                    # reshard catch-ups: stream the dataset as of NOW,
                    # not as of the sender's last snapshotter tick
                    post_body["freshSnapshot"] = True
                if bases:
                    # candidate common bases, newest first; an old
                    # server ignores the key and streams full
                    post_body["bases"] = list(bases)
                async with http.post(
                        backup_url.rstrip("/") + "/backup",
                        json=post_body) as resp:
                    if resp.status != 201:
                        raise RestoreError(
                            "backup request refused: %d %s"
                            % (resp.status, await resp.text()))
                    body = await resp.json()
                    # fold the server's reply stamp: our restore's
                    # subsequent records order after the enqueue
                    await merge_remote(body.get("hlc"))
                    job_path = body["jobPath"]
                    jobid = body.get("jobid")
                    expected["jobid"] = jobid \
                        if isinstance(jobid, str) else None
                    # decide how the stream will be applied, and make
                    # room for it, BEFORE the handler gate opens: a
                    # full stream lands in a fresh dataset (isolate
                    # whatever exists, as always); a delta applies
                    # against the base — whose content must survive
                    # the isolation when it lives in the dataset being
                    # replaced
                    basis = body.get("basis")
                    if bases and isinstance(basis, dict) \
                            and basis.get("mode") == "incremental":
                        b = basis.get("base")
                        if b not in bases:
                            raise DeltaRefused(
                                "sender negotiated base %r we never "
                                "offered" % (b,))
                        src = base_src
                        if not self.storage.delta_in_place \
                                and await self.storage.exists(
                                    self.dataset):
                            self.last_isolated = await self.isolate(
                                isolate_prefix)
                            if src == self.dataset:
                                src = self.last_isolated
                        mode.update(basis="incremental", base=b,
                                    base_src=src)
                    else:
                        # full (old server, or no common base); keep
                        # any earlier attempt's isolation on record —
                        # a full retry after a failed delta has
                        # nothing left to isolate, but the operator
                        # still wants to know where the data went
                        iso = await self.isolate(isolate_prefix)
                        if iso:
                            self.last_isolated = iso
                    job["basis"] = mode["basis"]
                    job_known.set()

                # poll the job while receiving (zfsClient:685-754)
                poll_error: str | None = None
                while not recv_done.done():
                    await asyncio.wait(
                        [recv_done], timeout=self.poll_interval)
                    if recv_done.done():
                        break
                    try:
                        async with http.get(
                                backup_url.rstrip("/")
                                + job_path) as jr:
                            if jr.status == 404:
                                # the server no longer knows our job:
                                # it restarted (e.g. crashed mid-send)
                                # and its queue died with it.  The
                                # dial-back will never come — without
                                # this check the poll loop spins
                                # FOREVER on the 404 body (the crash
                                # sweep's backup.send.connect scenario
                                # caught exactly that wedge)
                                poll_error = ("restore job vanished "
                                              "on the sender (server "
                                              "restarted?)")
                                break
                            remote = await jr.json()
                    except (aiohttp.ClientError,
                            asyncio.TimeoutError) as e:
                        log.warning("restore job poll failed: %s", e)
                        continue
                    job["remote"] = remote
                    if remote.get("size") is not None:
                        job["size"] = remote["size"]
                    if remote.get("done") == "failed":
                        poll_error = remote.get("error") or "sender failed"
                        break
                if poll_error:
                    raise RestoreError("restore failed on the sender: %s"
                                       % poll_error)
                await recv_done
            job["done"] = True
        except asyncio.CancelledError:
            cur = asyncio.current_task()
            if recv_done.cancelled() \
                    and hasattr(cur, "cancelling") \
                    and cancel_requests(cur) == 0:
                # The HANDLER task was cancelled by something that did
                # not cancel US (only our own finally-sweep does today,
                # but e.g. a 3.12 server teardown could): re-raising
                # would propagate a spurious CancelledError out of an
                # UNcancelled _receive and label the job 'cancelled',
                # masking the real abort — surface it as the restore
                # failure it is (ADVICE r5).  cancelling() (3.11+) is
                # what proves nobody cancelled us; on 3.10 the counter
                # does not exist and the two cases cannot be told
                # apart (awaiting a future and being cancelled cancels
                # the future too), so the old re-raise behavior stands
                # there rather than risk converting a genuine caller
                # cancellation into a RestoreError.
                job["done"] = "failed"
                job["error"] = "receive handler aborted"
                raise RestoreError(
                    "restore receive handler was cancelled while the "
                    "restore itself was not") from None
            job["done"] = "failed"
            job["error"] = "cancelled"
            if not recv_done.done():
                recv_done.cancel()
            raise
        except Exception as e:
            job["done"] = "failed"
            job["error"] = str(e)
            if not recv_done.done():
                recv_done.cancel()
            raise
        finally:
            server.close()
            # stop a still-running transfer before wait_closed: the
            # handler's own CancelledError path reaps its child and
            # cleans up the partial dataset (storage.recv)
            while handler_tasks:
                tasks = [t for t in handler_tasks if not t.done()]
                if not tasks:
                    break   # done-callbacks just haven't swept the set
                for t in tasks:
                    t.cancel()
                await asyncio.gather(*tasks, return_exceptions=True)
            await server.wait_closed()
        return mode["basis"]
