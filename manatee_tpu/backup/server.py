"""Backup REST server.

Reference parity: lib/backupServer.js — ``POST /backup`` with
{host, port, dataset} enqueues a job and returns 201 with the job path
(:132-155); ``GET /backup/:uuid`` reports status/progress (:108-130).

Beyond parity: the POST may carry the requester's ``trace``/``span``
ids, which ride the job into the sender so the snapshot stream's span
parents into the requester's restore tree; ``GET /spans`` serves this
process's span ring for the `manatee-adm trace` fan-out.

Incremental rebuild negotiation: the POST may also carry ``bases`` —
the epoch-ms snapshot names the requester holds locally and can apply
a delta onto.  When this server was built with a storage backend, it
intersects that offer with its OWN snapshot list, picks the newest
common name, and answers with ``basis`` so the requester knows — before
the stream arrives — whether to prepare a delta apply or the classic
full receive.  The negotiated base rides the job into the sender, which
names {base, target} in the stream header; any doubt at ANY stage (no
storage wired, malformed offer, negotiation error, base vanished by
send time) degrades to the full stream.
"""

from __future__ import annotations

import asyncio
import logging

from aiohttp import web

from manatee_tpu import faults
from manatee_tpu.backup.queue import BackupJob, BackupQueue
from manatee_tpu.obs import hlc_now, merge_remote
from manatee_tpu.daemons.common import attach_obs_routes
from manatee_tpu.storage.base import (
    StorageBackend,
    is_epoch_ms_snapshot,
)

log = logging.getLogger("manatee.backup.server")

# a requester only ever holds snapshot_number (default 50) epoch-ms
# snapshots; anything past this is a malformed offer, not a bigger one
MAX_BASE_OFFER = 64


async def negotiate_base(storage: StorageBackend, dataset: str,
                         offered) -> str | None:
    """The sender's half of common-snapshot negotiation: newest
    epoch-ms snapshot name present both locally and in the requester's
    offer, or None for full.  Only 13-digit epoch names are even
    considered — they are the only cross-peer-stable names (a received
    snapshot keeps its sender's name), and anything else off the wire
    is noise."""
    await faults.point("backup.negotiate_base")
    if not isinstance(offered, (list, tuple)):
        return None
    offers = {str(o) for o in offered[:MAX_BASE_OFFER]
              if isinstance(o, str) and is_epoch_ms_snapshot(o)}
    if not offers:
        return None
    mine = {s.name for s in await storage.list_snapshots(dataset)
            if is_epoch_ms_snapshot(s.name)}
    common = mine & offers
    return max(common, key=int) if common else None


class BackupRestServer:
    def __init__(self, queue: BackupQueue, *, host: str = "0.0.0.0",
                 port: int = 12345,
                 storage: StorageBackend | None = None,
                 dataset: str | None = None):
        """*storage*/*dataset* (the same pair the sender streams from)
        enable common-base negotiation; without them every job is a
        full stream, exactly as before."""
        self.queue = queue
        self.host = host
        self.port = port
        self.storage = storage
        self.dataset = dataset
        self._runner: web.AppRunner | None = None
        app = web.Application()
        app.router.add_post("/backup", self._post_backup)
        app.router.add_get("/backup/{uuid}", self._get_backup)
        # the full shared introspection surface: this process's spans
        # (the sender's backup.send lives here, not in the sitter), its
        # journal, profile, task census, fault surface, and the generic
        # registry /metrics exposition (daemons/common.py)
        attach_obs_routes(app, metrics=True)
        self._app = app

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        log.info("backup server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _post_backup(self, req: web.Request) -> web.Response:
        try:
            params = await req.json()
        except asyncio.CancelledError:
            raise
        except Exception:
            return web.json_response(
                {"error": "invalid json"}, status=400)
        if not all(params.get(k) for k in ("host", "port", "dataset")):
            return web.json_response(
                {"error": "host, dataset, and port parameters required"},
                status=409)
        trace = params.get("trace")
        span_id = params.get("span")
        # POST /backup is an HLC piggyback boundary like the coord RPC
        # frames: fold the requester's stamp so the job's sender-side
        # records order after the request at any wall-clock skew
        await merge_remote(params.get("hlc"))
        # the requester's codec offer (absent/malformed = old peer =
        # raw); only string names survive into the job
        offered = params.get("compress")
        if not isinstance(offered, list):
            offered = []
        proto = params.get("streamProto")
        proto = proto if isinstance(proto, int) else 0
        if params.get("freshSnapshot") and self.storage is not None \
                and self.dataset:
            # reshard catch-ups: snapshot NOW so the stream (and the
            # base negotiation below) reflect the dataset as of this
            # request, not the last snapshotter tick.  A failed
            # snapshot serves a staler basis, never a refused rebuild.
            try:
                await self.storage.snapshot(self.dataset)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.warning("freshSnapshot failed (%s); serving the "
                            "latest existing snapshot", e)
        base = None
        if self.storage is not None and self.dataset \
                and proto >= 2 and params.get("bases"):
            try:
                base = await negotiate_base(self.storage, self.dataset,
                                            params.get("bases"))
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # any doubt — a fault, an unlistable dataset — serves
                # the full stream rather than refusing the rebuild
                log.warning("base negotiation failed (%s); serving a "
                            "full stream", e)
                base = None
        job = BackupJob(host=str(params["host"]),
                        port=int(params["port"]),
                        dataset=str(params["dataset"]),
                        trace=trace if isinstance(trace, str) else None,
                        span=span_id if isinstance(span_id, str)
                        else None,
                        compress=tuple(str(c) for c in offered),
                        stream_proto=proto,
                        base=base)
        self.queue.push(job)
        log.info("enqueued backup job %s -> %s:%d (basis=%s)",
                 job.uuid, job.host, job.port,
                 "incremental from %s" % base if base else "full")
        return web.json_response(
            {"jobid": job.uuid, "jobPath": "/backup/%s" % job.uuid,
             "hlc": hlc_now(),
             # the requester prepares its receive path off this BEFORE
             # the stream arrives (old requesters ignore the key)
             "basis": ({"mode": "incremental", "base": base}
                       if base else {"mode": "full"})},
            status=201)

    async def _get_backup(self, req: web.Request) -> web.Response:
        job = self.queue.get(req.match_info["uuid"])
        if job is None:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response(job.to_dict())
