"""Backup REST server.

Reference parity: lib/backupServer.js — ``POST /backup`` with
{host, port, dataset} enqueues a job and returns 201 with the job path
(:132-155); ``GET /backup/:uuid`` reports status/progress (:108-130).
"""

from __future__ import annotations

import logging

from aiohttp import web

from manatee_tpu.backup.queue import BackupJob, BackupQueue

log = logging.getLogger("manatee.backup.server")


class BackupRestServer:
    def __init__(self, queue: BackupQueue, *, host: str = "0.0.0.0",
                 port: int = 12345):
        self.queue = queue
        self.host = host
        self.port = port
        self._runner: web.AppRunner | None = None
        app = web.Application()
        app.router.add_post("/backup", self._post_backup)
        app.router.add_get("/backup/{uuid}", self._get_backup)
        self._app = app

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        log.info("backup server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _post_backup(self, req: web.Request) -> web.Response:
        try:
            params = await req.json()
        except asyncio.CancelledError:
            raise
        except Exception:
            return web.json_response(
                {"error": "invalid json"}, status=400)
        if not all(params.get(k) for k in ("host", "port", "dataset")):
            return web.json_response(
                {"error": "host, dataset, and port parameters required"},
                status=409)
        job = BackupJob(host=str(params["host"]),
                        port=int(params["port"]),
                        dataset=str(params["dataset"]))
        self.queue.push(job)
        log.info("enqueued backup job %s -> %s:%d", job.uuid, job.host,
                 job.port)
        return web.json_response(
            {"jobid": job.uuid, "jobPath": "/backup/%s" % job.uuid},
            status=201)

    async def _get_backup(self, req: web.Request) -> web.Response:
        job = self.queue.get(req.match_info["uuid"])
        if job is None:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response(job.to_dict())
