"""Backup REST server.

Reference parity: lib/backupServer.js — ``POST /backup`` with
{host, port, dataset} enqueues a job and returns 201 with the job path
(:132-155); ``GET /backup/:uuid`` reports status/progress (:108-130).

Beyond parity: the POST may carry the requester's ``trace``/``span``
ids, which ride the job into the sender so the snapshot stream's span
parents into the requester's restore tree; ``GET /spans`` serves this
process's span ring for the `manatee-adm trace` fan-out.
"""

from __future__ import annotations

import asyncio
import logging

from aiohttp import web

from manatee_tpu import faults
from manatee_tpu.backup.queue import BackupJob, BackupQueue
from manatee_tpu.obs import get_span_store
from manatee_tpu.obs.spans import spans_http_reply

log = logging.getLogger("manatee.backup.server")


class BackupRestServer:
    def __init__(self, queue: BackupQueue, *, host: str = "0.0.0.0",
                 port: int = 12345):
        self.queue = queue
        self.host = host
        self.port = port
        self._runner: web.AppRunner | None = None
        app = web.Application()
        app.router.add_post("/backup", self._post_backup)
        app.router.add_get("/backup/{uuid}", self._get_backup)
        app.router.add_get("/spans", self._spans)
        # the backupserver daemon's own registry (the sender's stream
        # faults live in THIS process, not the sitter)
        faults.attach_http(app)
        self._app = app

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        log.info("backup server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _post_backup(self, req: web.Request) -> web.Response:
        try:
            params = await req.json()
        except asyncio.CancelledError:
            raise
        except Exception:
            return web.json_response(
                {"error": "invalid json"}, status=400)
        if not all(params.get(k) for k in ("host", "port", "dataset")):
            return web.json_response(
                {"error": "host, dataset, and port parameters required"},
                status=409)
        trace = params.get("trace")
        span_id = params.get("span")
        # the requester's codec offer (absent/malformed = old peer =
        # raw); only string names survive into the job
        offered = params.get("compress")
        if not isinstance(offered, list):
            offered = []
        proto = params.get("streamProto")
        job = BackupJob(host=str(params["host"]),
                        port=int(params["port"]),
                        dataset=str(params["dataset"]),
                        trace=trace if isinstance(trace, str) else None,
                        span=span_id if isinstance(span_id, str)
                        else None,
                        compress=tuple(str(c) for c in offered),
                        stream_proto=proto
                        if isinstance(proto, int) else 0)
        self.queue.push(job)
        log.info("enqueued backup job %s -> %s:%d", job.uuid, job.host,
                 job.port)
        return web.json_response(
            {"jobid": job.uuid, "jobPath": "/backup/%s" % job.uuid},
            status=201)

    async def _get_backup(self, req: web.Request) -> web.Response:
        job = self.queue.get(req.match_info["uuid"])
        if job is None:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response(job.to_dict())

    async def _spans(self, req: web.Request) -> web.Response:
        """This process's completed spans (the backup sender's
        ``backup.send`` lives here, not in the sitter) — same contract
        as the status server's ``GET /spans``."""
        body, status = spans_http_reply(get_span_store(), req.query)
        return web.json_response(body, status=status,
                                 content_type="application/json")
