"""Backup sender: streams the latest snapshot to a requesting peer.

Reference parity: lib/backupSender.js — on queue push, find the latest
13-digit-epoch-named snapshot of OUR dataset (:244-288), connect to the
requester's receive listener, and stream the snapshot with progress
published into the job object (:154-242; size/completed parsed from
``zfs send -v`` there, delivered by the storage backend's progress
callback here).
"""

from __future__ import annotations

import asyncio
import logging

from manatee_tpu import faults
from manatee_tpu.backup.queue import BackupJob, BackupQueue
from manatee_tpu.obs import bind_parent, bind_trace, span
from manatee_tpu.storage import stream as wirestream
from manatee_tpu.storage.base import StorageBackend, StorageError

log = logging.getLogger("manatee.backup.sender")

CONNECT_TIMEOUT = 30.0   # dial-back to the requester's receive listener


class BackupSender:
    def __init__(self, queue: BackupQueue, storage: StorageBackend,
                 dataset: str):
        self.queue = queue
        self.storage = storage
        self.dataset = dataset
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass       # the cancel we just requested
            except Exception:
                log.exception("backup sender loop died uncleanly")

    async def _loop(self) -> None:
        while True:
            job = await self.queue.take()
            try:
                await self._send(job)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                log.error("backup job %s failed: %s", job.uuid, e)
                job.done = "failed"
                job.error = str(e)

    async def _send(self, job: BackupJob) -> None:
        # the job carries the requester's trace/span ids (POST /backup):
        # this process's send span parents into the requester's restore
        # tree even though it lives in the backupserver daemon
        # stream codec: best mutual pick from the requester's offer
        # (raw when it offered nothing — an old peer — or nothing
        # overlaps our own codec set)
        codec = wirestream.negotiate(job.compress)
        # the POST-time negotiation picked the common base; the target
        # is OUR latest snapshot at send time.  If the base cannot be
        # served anymore (GC race, backend without delta support), the
        # send raises, the job fails, and the requester retries full —
        # a failed job is the degrade path, never a wrong stream.
        basis = "incremental" if job.base else "full"
        with bind_trace(job.trace), bind_parent(job.span), \
                span("backup.send", job=job.uuid, dataset=self.dataset,
                     codec=codec or "raw", basis=basis):
            snap = await self.storage.latest_backup_snapshot(self.dataset)
            if snap is None:
                raise StorageError("no snapshots of %s eligible for "
                                   "backup" % self.dataset)
            log.info("sending %s to %s:%d for job %s (basis=%s)",
                     snap.full, job.host, job.port, job.uuid, basis)
            # bounded connect: a requester that vanished between the
            # POST and our dial must fail the job, not wedge the send
            # loop
            if await faults.point("backup.send.connect") == "drop":
                # black-holed SYN: what the bounded dial would yield
                raise asyncio.TimeoutError(
                    "dial-back to %s:%d black-holed (fault)"
                    % (job.host, job.port))
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(job.host, job.port),
                CONNECT_TIMEOUT)

            def progress(done: int, total: int | None) -> None:
                job.completed = done
                if total is not None:
                    job.size = total

            try:
                # stall = a wedged send stream the receiver's poll loop
                # must notice; error fails the job like a died pipe
                await faults.point("backup.send.stream")
                # stamp the job uuid on the stream for receivers that
                # declared the protocol: their listener port can be a
                # REBOUND one (a cancelled predecessor's), and the
                # stamp is what lets them refuse our stream if we are
                # the stale job
                sid = job.uuid if job.stream_proto >= 1 else None
                await self.storage.send(self.dataset, snap.name, writer,
                                        progress_cb=progress,
                                        compress=codec, stream_id=sid,
                                        from_snapshot=job.base)
                writer.close()
                try:
                    await writer.wait_closed()
                except (ConnectionError, OSError):
                    pass
            except asyncio.CancelledError:
                writer.close()
                raise
            except Exception:
                # StorageError, or an injected stream fault: either way
                # the half-sent socket must not leak with the job
                writer.close()
                raise
            job.done = True
            log.info("completed backup job %s (%d bytes)", job.uuid,
                     job.completed)
