"""Storage/data-plane layer (reference: lib/zfsClient.js, lib/common.js zfs
wrappers, lib/snapShotter.js snapshot naming/GC semantics).

Pluggable backends behind :class:`manatee_tpu.storage.base.StorageBackend`:

- :class:`manatee_tpu.storage.zfsbackend.ZfsBackend` — production; shells
  out to zfs(8) exactly as the reference does.
- :class:`manatee_tpu.storage.dirstore.DirBackend` — development/testing;
  plain directories, full-copy snapshots, tar send streams.  Lets the
  entire control plane (including restores) run on machines without ZFS.
"""

from manatee_tpu.storage.base import (
    Snapshot,
    StorageBackend,
    StorageError,
    snapshot_name_now,
    is_epoch_ms_snapshot,
)
from manatee_tpu.storage.dirstore import DirBackend
from manatee_tpu.storage.zfsbackend import ZfsBackend

__all__ = [
    "Snapshot",
    "StorageBackend",
    "StorageError",
    "snapshot_name_now",
    "is_epoch_ms_snapshot",
    "DirBackend",
    "ZfsBackend",
]
