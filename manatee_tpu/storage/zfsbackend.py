"""zfs(8) storage backend — the production data plane.

Command mapping follows the reference's wrappers (lib/common.js:177-451)
and restore/mount flows (lib/zfsClient.js).  All zfs invocations run with
an empty environment and the traced exec wrapper, as the reference does
(lib/common.js:148-172).

send/recv parity (lib/backupSender.js:154-242, lib/zfsClient.js:765-886):
``zfs send -v -P`` writes machine-parsable progress to stderr — total size
from the "size" line, periodic per-second byte counts — which we surface
through the progress callback, and ``zfs recv -v -u`` receives unmounted.
"""

from __future__ import annotations

import asyncio
import re

from manatee_tpu.storage.base import (
    ProgressCb,
    Snapshot,
    StorageBackend,
    StorageError,
)
from manatee_tpu.utils import ExecError, run

# zfs send -P stderr: "size   123456" then lines "HH:MM:SS   123456   ds@snap"
_SIZE_RE = re.compile(r"^size\s+(\d+)", re.M)
_TICK_RE = re.compile(r"^\d\d:\d\d:\d\d\s+(\d+)\s+", re.M)


class ZfsBackend(StorageBackend):
    def __init__(self, zfs_cmd: str = "zfs"):
        self.zfs = zfs_cmd

    async def _zfs(self, *args: str, check: bool = True):
        try:
            return await run([self.zfs, *args], empty_env=True, check=check)
        except ExecError as e:
            raise StorageError(str(e)) from None

    # ---- dataset lifecycle ----

    async def exists(self, dataset: str) -> bool:
        res = await self._zfs("list", dataset, check=False)
        return res.returncode == 0

    async def create(self, dataset: str, *, mountpoint: str | None = None) -> None:
        args = ["create"]
        if mountpoint:
            args += ["-o", "mountpoint=%s" % mountpoint]
        await self._zfs(*args, dataset)

    async def destroy(self, dataset: str, *, recursive: bool = False) -> None:
        args = ["destroy"]
        if recursive:
            args.append("-r")
        await self._zfs(*args, dataset)

    async def rename(self, old: str, new: str) -> None:
        await self._zfs("rename", old, new)

    # ---- properties / mounting ----

    async def get_prop(self, dataset: str, prop: str) -> str | None:
        res = await self._zfs("get", "-H", "-o", "value", prop, dataset)
        val = res.stdout.strip()
        return None if val in ("-", "") else val

    async def set_prop(self, dataset: str, prop: str, value: str) -> None:
        await self._zfs("set", "%s=%s" % (prop, value), dataset)

    async def inherit_prop(self, dataset: str, prop: str) -> None:
        await self._zfs("inherit", prop, dataset)

    async def set_mountpoint(self, dataset: str, mountpoint: str) -> None:
        await self.set_prop(dataset, "mountpoint", mountpoint)

    async def get_mountpoint(self, dataset: str) -> str | None:
        return await self.get_prop(dataset, "mountpoint")

    async def mount(self, dataset: str) -> None:
        res = await self._zfs("mount", dataset, check=False)
        if res.returncode != 0 and "already mounted" not in res.stderr:
            raise StorageError("zfs mount %s failed: %s"
                               % (dataset, res.stderr.strip()))

    async def unmount(self, dataset: str) -> None:
        res = await self._zfs("unmount", dataset, check=False)
        if res.returncode != 0 and "not currently mounted" not in res.stderr:
            raise StorageError("zfs unmount %s failed: %s"
                               % (dataset, res.stderr.strip()))

    async def is_mounted(self, dataset: str) -> bool:
        # kernel-reported state, the moral equivalent of the reference's
        # /etc/mnttab verification (lib/zfsClient.js:393-427)
        return (await self.get_prop(dataset, "mounted")) == "yes"

    # ---- snapshots ----

    async def snapshot(self, dataset: str, name: str | None = None) -> Snapshot:
        from manatee_tpu.storage.base import snapshot_name_now
        name = name or snapshot_name_now()
        await self._zfs("snapshot", "%s@%s" % (dataset, name))
        snaps = await self.list_snapshots(dataset)
        for s in snaps:
            if s.name == name:
                return s
        raise StorageError("snapshot %s@%s vanished" % (dataset, name))

    async def list_snapshots(self, dataset: str) -> list[Snapshot]:
        res = await self._zfs(
            "list", "-H", "-p", "-t", "snapshot",
            "-o", "name,creation", "-s", "creation", "-d", "1", dataset)
        out: list[Snapshot] = []
        for line in res.stdout.splitlines():
            if not line.strip():
                continue
            full, creation = line.split("\t")
            ds, snapname = full.split("@", 1)
            out.append(Snapshot(ds, snapname, float(creation)))
        return out

    async def destroy_snapshot(self, dataset: str, name: str) -> None:
        await self._zfs("destroy", "%s@%s" % (dataset, name))

    # ---- bulk streams ----

    async def estimate_send_size(self, dataset: str, name: str) -> int | None:
        res = await self._zfs("send", "-n", "-v", "-P",
                              "%s@%s" % (dataset, name), check=False)
        m = _SIZE_RE.search(res.stderr) or _SIZE_RE.search(res.stdout)
        return int(m.group(1)) if m else None

    async def send(
        self,
        dataset: str,
        name: str,
        writer: asyncio.StreamWriter,
        progress_cb: ProgressCb | None = None,
    ) -> None:
        proc = await asyncio.create_subprocess_exec(
            self.zfs, "send", "-v", "-P", "%s@%s" % (dataset, name),
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            env={},
        )
        size: int | None = None
        err_chunks: list[bytes] = []

        async def watch_stderr():
            nonlocal size
            while True:
                line = await proc.stderr.readline()
                if not line:
                    return
                err_chunks.append(line)
                text = line.decode("utf-8", "replace")
                m = _SIZE_RE.match(text)
                if m:
                    size = int(m.group(1))
                    continue
                m = _TICK_RE.match(text)
                if m and progress_cb:
                    progress_cb(int(m.group(1)), size)

        async def pump_stdout():
            done = 0
            while True:
                chunk = await proc.stdout.read(1 << 16)
                if not chunk:
                    return
                done += len(chunk)
                writer.write(chunk)
                await writer.drain()
                if progress_cb:
                    progress_cb(done, size)

        t_err = asyncio.ensure_future(watch_stderr())
        t_out = asyncio.ensure_future(pump_stdout())
        try:
            await asyncio.gather(t_err, t_out)
        except Exception as e:
            for t in (t_err, t_out):
                t.cancel()
            await asyncio.gather(t_err, t_out, return_exceptions=True)
            from manatee_tpu.utils.executil import reap_killed
            await reap_killed(proc)
            raise StorageError("zfs send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e
        rc = await proc.wait()
        if rc != 0:
            raise StorageError("zfs send failed (rc=%d): %s"
                               % (rc, b"".join(err_chunks).decode("utf-8", "replace")))

    async def recv(
        self,
        dataset: str,
        reader: asyncio.StreamReader,
        progress_cb: ProgressCb | None = None,
    ) -> None:
        proc = await asyncio.create_subprocess_exec(
            self.zfs, "recv", "-v", "-u", dataset,
            stdin=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            env={},
        )
        done = 0
        stream_error: Exception | None = None
        while True:
            try:
                chunk = await reader.read(1 << 16)
            except Exception as e:
                stream_error = e
                break
            if not chunk:
                break
            done += len(chunk)
            try:
                proc.stdin.write(chunk)
                await proc.stdin.drain()
            except (BrokenPipeError, ConnectionResetError):
                break  # zfs recv died early; rc/stderr below explain
            if progress_cb:
                progress_cb(done, None)
        if stream_error is not None:
            from manatee_tpu.utils.executil import reap_killed
            await reap_killed(proc)
            raise StorageError("zfs recv into %s aborted: %s"
                               % (dataset, stream_error)) from stream_error
        try:
            proc.stdin.close()
        except OSError:
            pass
        err = await proc.stderr.read()
        rc = await proc.wait()
        if rc != 0:
            raise StorageError("zfs recv failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))
