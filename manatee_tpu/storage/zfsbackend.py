"""zfs(8) storage backend — the production data plane.

Command mapping follows the reference's wrappers (lib/common.js:177-451)
and restore/mount flows (lib/zfsClient.js).  All zfs invocations run with
an empty environment and the traced exec wrapper, as the reference does
(lib/common.js:148-172).

send/recv parity (lib/backupSender.js:154-242, lib/zfsClient.js:765-886):
``zfs send -v -P`` writes machine-parsable progress to stderr — total size
from the "size" line, periodic per-second byte counts — which we surface
through the progress callback, and ``zfs recv -v -u`` receives unmounted.
"""

from __future__ import annotations

import asyncio
import json
import re

from manatee_tpu import faults
from manatee_tpu.storage import stream as wirestream
from manatee_tpu.storage.base import (
    ProgressCb,
    Snapshot,
    StorageBackend,
    StorageError,
    is_epoch_ms_snapshot,
    pump_child_to_socket,
    pump_socket_to_child,
)
from manatee_tpu.utils import ExecError, run

# zfs send -P stderr: "size   123456" then lines "HH:MM:SS   123456   ds@snap"
_SIZE_RE = re.compile(r"^size\s+(\d+)", re.M)
_TICK_RE = re.compile(r"^\d\d:\d\d:\d\d\s+(\d+)\s+", re.M)


class _SendState:
    """Mutable holder shared between the stderr watcher and the data
    path: the stream size parsed from `zfs send -v -P`."""

    def __init__(self):
        self.size: int | None = None


async def _watch_send_stderr(proc, state: "_SendState",
                             err_chunks: list, progress_cb) -> None:
    """Parse `zfs send -v -P` stderr: the size line plus per-second byte
    ticks surfaced through *progress_cb* (lib/backupSender.js:114-136,
    195-212).  Shared by the python and native send paths."""
    while True:
        line = await proc.stderr.readline()
        if not line:
            return
        err_chunks.append(line)
        text = line.decode("utf-8", "replace")
        m = _SIZE_RE.match(text)
        if m:
            state.size = int(m.group(1))
            continue
        m = _TICK_RE.match(text)
        if m and progress_cb:
            progress_cb(int(m.group(1)), state.size)


class ZfsBackend(StorageBackend):
    def __init__(self, zfs_cmd: str = "zfs"):
        self.zfs = zfs_cmd

    async def _zfs(self, *args: str, check: bool = True):
        # one seam for the whole zfs(8) command family: error/delay/
        # stall any dataset operation without root or a zpool
        await faults.point("storage.zfs.exec")
        try:
            return await run([self.zfs, *args], empty_env=True, check=check)
        except ExecError as e:
            raise StorageError(str(e)) from None

    # ---- dataset lifecycle ----

    async def exists(self, dataset: str) -> bool:
        res = await self._zfs("list", dataset, check=False)
        return res.returncode == 0

    async def create(self, dataset: str, *, mountpoint: str | None = None) -> None:
        args = ["create"]
        if mountpoint:
            args += ["-o", "mountpoint=%s" % mountpoint]
        await self._zfs(*args, dataset)

    async def destroy(self, dataset: str, *, recursive: bool = False) -> None:
        args = ["destroy"]
        if recursive:
            args.append("-r")
        await self._zfs(*args, dataset)

    async def rename(self, old: str, new: str) -> None:
        await self._zfs("rename", old, new)

    # ---- properties / mounting ----

    async def get_prop(self, dataset: str, prop: str) -> str | None:
        res = await self._zfs("get", "-H", "-o", "value", prop, dataset)
        val = res.stdout.strip()
        return None if val in ("-", "") else val

    async def set_prop(self, dataset: str, prop: str, value: str) -> None:
        await self._zfs("set", "%s=%s" % (prop, value), dataset)

    async def inherit_prop(self, dataset: str, prop: str) -> None:
        await self._zfs("inherit", prop, dataset)

    async def set_mountpoint(self, dataset: str, mountpoint: str) -> None:
        await self.set_prop(dataset, "mountpoint", mountpoint)

    async def get_mountpoint(self, dataset: str) -> str | None:
        return await self.get_prop(dataset, "mountpoint")

    async def mount(self, dataset: str) -> None:
        res = await self._zfs("mount", dataset, check=False)
        if res.returncode != 0 and "already mounted" not in res.stderr:
            raise StorageError("zfs mount %s failed: %s"
                               % (dataset, res.stderr.strip()))

    async def unmount(self, dataset: str) -> None:
        res = await self._zfs("unmount", dataset, check=False)
        if res.returncode != 0 and "not currently mounted" not in res.stderr:
            raise StorageError("zfs unmount %s failed: %s"
                               % (dataset, res.stderr.strip()))

    async def is_mounted(self, dataset: str) -> bool:
        # kernel-reported state, the moral equivalent of the reference's
        # /etc/mnttab verification (lib/zfsClient.js:393-427)
        return (await self.get_prop(dataset, "mounted")) == "yes"

    # ---- snapshots ----

    async def snapshot(self, dataset: str, name: str | None = None) -> Snapshot:
        from manatee_tpu.storage.base import snapshot_name_now
        name = name or snapshot_name_now()
        await self._zfs("snapshot", "%s@%s" % (dataset, name))
        snaps = await self.list_snapshots(dataset)
        for s in snaps:
            if s.name == name:
                return s
        raise StorageError("snapshot %s@%s vanished" % (dataset, name))

    async def list_snapshots(self, dataset: str) -> list[Snapshot]:
        res = await self._zfs(
            "list", "-H", "-p", "-t", "snapshot",
            "-o", "name,creation", "-s", "creation", "-d", "1", dataset)
        out: list[Snapshot] = []
        for line in res.stdout.splitlines():
            if not line.strip():
                continue
            full, creation = line.split("\t")
            ds, snapname = full.split("@", 1)
            out.append(Snapshot(ds, snapname, float(creation)))
        return out

    async def destroy_snapshot(self, dataset: str, name: str) -> None:
        """Idempotent under absence (StorageBackend contract): the
        snapshotter's GC and a sitter's restore run in SEPARATE
        processes, so a rebuild can isolate/rename the whole dataset —
        or another pass can destroy this snapshot — between the GC's
        list and this destroy.  Absence means the deletion's goal is
        achieved; raising instead fed the stuck-snapshot alarm
        spuriously (the extended-storm race DirBackend hit; the zfs(8)
        backend has the same window in production)."""
        res = await self._zfs("destroy", "%s@%s" % (dataset, name),
                              check=False)
        if res.returncode == 0:
            return
        err = (res.stderr or "") + (res.stdout or "")
        # illumos/OpenZFS wordings for the two absence shapes: missing
        # snapshot ("could not find any snapshots to destroy" or
        # "snapshot does not exist") vs missing/renamed dataset
        # ("dataset does not exist")
        if "does not exist" in err \
                or "could not find any snapshots" in err:
            return
        raise StorageError("cannot destroy snapshot %s@%s: %s"
                           % (dataset, name, err.strip()))

    # ---- bulk streams ----

    async def estimate_send_size(self, dataset: str, name: str) -> int | None:
        res = await self._zfs("send", "-n", "-v", "-P",
                              "%s@%s" % (dataset, name), check=False)
        m = _SIZE_RE.search(res.stderr) or _SIZE_RE.search(res.stdout)
        return int(m.group(1)) if m else None

    async def send(
        self,
        dataset: str,
        name: str,
        writer: asyncio.StreamWriter,
        progress_cb: ProgressCb | None = None,
        compress: str | None = None,
        stream_id: str | None = None,
        from_snapshot: str | None = None,
    ) -> None:
        from manatee_tpu import native

        if from_snapshot:
            await faults.point("storage.delta.send")
        basis = "incremental" if from_snapshot else "full"
        # zfs streams historically go raw with no header, so the codec
        # and stream id ride a magic-prefixed wire header — written
        # ONLY when the receiver's POST proved it knows how to probe
        # for the magic (it offered codecs / declared the stream
        # protocol; the sender gates stream_id/compress/delta on
        # that).  Old peers in either direction stay on the raw wire.
        if compress or stream_id or from_snapshot:
            hdr = {"snapshot": name}
            if compress:
                hdr["compression"] = compress
            if stream_id:
                hdr["stream"] = stream_id
            if from_snapshot:
                # the receiver verifies this names the NEGOTIATED base
                # before letting `zfs recv -F` near the dataset
                hdr["base"] = from_snapshot
            frame = wirestream.WIRE_MAGIC + json.dumps(hdr).encode() \
                + b"\n"
            try:
                writer.write(frame)
                await writer.drain()
            except Exception as e:
                raise StorageError("zfs send of %s@%s aborted: %s"
                                   % (dataset, name, e)) from e
        if from_snapshot == name:
            # the receiver already holds the send target (`zfs send
            # -i X ds@X` is an error): the header ALONE is the whole
            # stream — base == snapshot tells the receiver to roll
            # back to the common snapshot and stop.  ~100 bytes where
            # the fallback would re-ship the entire dataset.
            return
        send_args = ["send", "-v", "-P"]
        if from_snapshot:
            send_args += ["-i", from_snapshot]
        send_args.append("%s@%s" % (dataset, name))
        if not compress and native.enabled() \
                and writer.get_extra_info("socket") is not None:
            # an UNCOMPRESSED body still rides the kernel splice pump
            # even when a stream-id header was stamped (the pump's
            # flush_transport pushes the header out first, exactly
            # like DirBackend's header + native path)
            await self._send_native(dataset, name, writer, progress_cb,
                                    send_args)
            return
        proc = await asyncio.create_subprocess_exec(
            self.zfs, *send_args,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            env={},
        )
        state = _SendState()
        err_chunks: list[bytes] = []

        async def pump_stdout():
            with wirestream.recorded_stage("send", dataset,
                                           compress,
                                           basis=basis) as st:
                st.raw, st.wire = await wirestream.pipeline_copy(
                    proc.stdout.read, writer, codec=compress,
                    progress=(lambda d: progress_cb(d, state.size))
                    if progress_cb else None)

        t_err = asyncio.create_task(
            _watch_send_stderr(proc, state, err_chunks, progress_cb))
        t_out = asyncio.create_task(pump_stdout())
        async def abort() -> None:
            # shielded + strongly-referenced: a SECOND cancel during
            # the abort must not skip the reap
            from manatee_tpu.utils.executil import kill_and_reap
            await kill_and_reap(proc, (t_err, t_out))

        try:
            await asyncio.gather(t_err, t_out)
        except asyncio.CancelledError:
            # caller cancelled (server shutdown, handler teardown):
            # zfs send must not run on as an orphan blocked on its
            # full stdout pipe
            await abort()
            raise
        except Exception as e:
            await abort()
            raise StorageError("zfs send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e
        rc = await proc.wait()
        if rc != 0:
            raise StorageError("zfs send failed (rc=%d): %s"
                               % (rc, b"".join(err_chunks).decode("utf-8", "replace")))

    async def _send_native(self, dataset: str, name: str,
                           writer: asyncio.StreamWriter,
                           progress_cb: ProgressCb | None,
                           send_args: list[str] | None = None) -> None:
        """MANATEE_NATIVE=1: `zfs send` stdout is spliced to the peer
        socket in the kernel — fd-lifetime/cancellation protocol shared
        with DirBackend in storage.base.pump_child_to_socket — while
        the -v/-P progress lines are still parsed from stderr on the
        loop."""
        from manatee_tpu.utils.executil import reap_killed

        state = _SendState()
        err_chunks: list[bytes] = []

        proc, t_err = await pump_child_to_socket(
            [self.zfs, *(send_args
                         or ["send", "-v", "-P",
                             "%s@%s" % (dataset, name)])],
            writer,
            stderr_task=lambda p: _watch_send_stderr(
                p, state, err_chunks, progress_cb),
            env={},
            label="native zfs send of %s@%s" % (dataset, name))
        try:
            await t_err
            rc = await proc.wait()
        except asyncio.CancelledError:
            # cancellation on the tail awaits: the child must still be
            # reaped
            from manatee_tpu.utils.executil import drain_and_reap
            await drain_and_reap(proc, t_err)
            raise
        except Exception as e:
            # a failing progress callback aborts the send, exactly as on
            # the non-native path
            await reap_killed(proc)
            raise StorageError("zfs send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e
        if rc != 0:
            raise StorageError(
                "zfs send failed (rc=%d): %s"
                % (rc, b"".join(err_chunks).decode("utf-8", "replace")))

    async def recv(
        self,
        dataset: str,
        reader: asyncio.StreamReader,
        progress_cb: ProgressCb | None = None,
        expect_stream_id: str | None = None,
    ) -> None:
        # wire-header probe: a negotiating sender prefixed the stream
        # with WIRE_MAGIC + codec/stream id; a raw stream's probed
        # bytes are replayed into the child untouched
        try:
            hdr, feed = await wirestream.probe_wire_header(reader)
        except ValueError as e:
            raise StorageError(str(e)) from None
        # a stale sender's dial-back (its job predates this attempt)
        # is refused before zfs recv touches anything
        wirestream.check_stream_id(hdr, expect_stream_id)
        codec = (hdr or {}).get("compression")
        feed = wirestream.make_feed(feed, codec)
        proc = await asyncio.create_subprocess_exec(
            self.zfs, "recv", "-v", "-u", dataset,
            stdin=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            env={},
        )
        # drain stderr CONCURRENTLY with the feed (same hazard as the
        # send paths: a verbose recv blocking on a full stderr pipe
        # stops reading stdin and wedges the drain() below)
        t_err = asyncio.create_task(proc.stderr.read())
        seen = {"raw": 0}

        def _prog(d: int) -> None:
            seen["raw"] = d
            if progress_cb:
                progress_cb(d, None)

        # a killed zfs recv discards the incomplete stream itself, so
        # unlike DirBackend there is no partial dataset to remove on
        # abort — the helper's reap is the whole cleanup
        with wirestream.recorded_stage("recv", dataset, codec) as st:
            err, rc = await pump_socket_to_child(
                proc, feed, t_err, on_progress=_prog,
                label="zfs recv into %s" % dataset)
            st.raw = seen["raw"]
            st.wire = feed.wire_bytes if codec else st.raw
        if rc != 0:
            raise StorageError("zfs recv failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))

    # ---- incremental rebuild (delta) ----
    #
    # zfs deltas apply IN PLACE: `zfs recv -F` natively rolls the
    # existing dataset back to the common base and verifies the
    # incremental stream's lineage by guid/checksum — a same-named but
    # divergent base fails the recv, the partial is discarded by zfs
    # itself, and the restore client retries full.

    delta_in_place = True

    def supports_delta(self) -> bool:
        return True

    async def list_children(self, dataset: str) -> list[str]:
        res = await self._zfs("list", "-H", "-o", "name", "-d", "1",
                              dataset, check=False)
        if res.returncode != 0:
            return []
        return sorted(n.strip() for n in res.stdout.splitlines()
                      if n.strip() and n.strip() != dataset)

    async def delta_candidates(
            self, dataset: str,
            fallback: str | None = None) -> tuple[list[str], str | None]:
        # in-place apply needs the base ON the live dataset; a
        # pre-isolated predecessor (*fallback*) cannot serve as a zfs
        # incremental target, so it is deliberately ignored
        if not await self.exists(dataset):
            return [], None
        names = [s.name for s in await self.list_snapshots(dataset)
                 if is_epoch_ms_snapshot(s.name)]
        return (names, dataset) if names else ([], None)

    async def recv_delta(
        self,
        dataset: str,
        reader: asyncio.StreamReader,
        *,
        base: str,
        base_src: str | None = None,
        progress_cb: ProgressCb | None = None,
        expect_stream_id: str | None = None,
    ) -> None:
        try:
            hdr, feed = await wirestream.probe_wire_header(reader)
        except ValueError as e:
            raise StorageError(str(e)) from None
        wirestream.check_stream_id(hdr, expect_stream_id)
        if not hdr or hdr.get("base") != base:
            # a full/headerless stream, or a delta against some other
            # base: refuse before `zfs recv -F` touches the dataset
            raise StorageError(
                "delta stream names base %r, expected %r"
                % ((hdr or {}).get("base"), base))
        if not await self.exists(dataset):
            raise StorageError("delta recv target %s does not exist"
                               % dataset)
        if hdr.get("snapshot") == base:
            # base == target: the receiver already holds the sender's
            # newest snapshot; rolling back to it IS the whole apply
            # (discarding local changes/snapshots past it, exactly as
            # a streamed delta would)
            await self._zfs("rollback", "-r",
                            "%s@%s" % (dataset, base))
            with wirestream.recorded_stage("recv", dataset, None,
                                           basis="incremental"):
                pass
            return
        codec = hdr.get("compression")
        feed = wirestream.make_feed(feed, codec)
        # roll the dataset back to the negotiated base FIRST: `recv -F`
        # alone only discards data modifications since the MOST RECENT
        # snapshot, and this dataset holds snapshots newer than the
        # base (the post-restore initial snapshot, the snapshotter's
        # own) — a plain -i recv against those fails with 'most recent
        # snapshot does not match incremental source'.  rollback -r
        # destroys the intervening (local-only, superseded) snapshots
        # and makes the base the head; a failed rollback fails the
        # apply before recv touches anything, and the client retries
        # full.
        await self._zfs("rollback", "-r", "%s@%s" % (dataset, base))
        proc = await asyncio.create_subprocess_exec(
            self.zfs, "recv", "-F", "-v", "-u", dataset,
            stdin=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
            env={},
        )
        t_err = asyncio.create_task(proc.stderr.read())
        seen = {"raw": 0}

        def _prog(d: int) -> None:
            seen["raw"] = d
            if progress_cb:
                progress_cb(d, None)

        with wirestream.recorded_stage("recv", dataset, codec,
                                       basis="incremental") as st:
            err, rc = await pump_socket_to_child(
                proc, feed, t_err, on_progress=_prog,
                label="zfs delta recv into %s" % dataset)
            st.raw = seen["raw"]
            st.wire = feed.wire_bytes if codec else st.raw
        if rc != 0:
            raise StorageError("zfs delta recv failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))
