"""Storage backend interface.

The reference's storage contract is spread across lib/common.js:177-451
(zfs exec wrappers: set/inherit/get/snapshot/create/rename/mount/unmount/
destroy/exists) and lib/zfsClient.js (restore/isolate/mount-with-verify).
This module captures that contract as an abstract interface so the control
plane is identical over zfs(8) and over a plain-directory dev backend.

Snapshot naming follows the reference exactly: snapshots are named with a
13-digit epoch-milliseconds timestamp (lib/zfsClient.js:209-221); GC and
backup-sender selection only ever consider names matching ^\\d{13}$
(lib/snapShotter.js:251, lib/backupSender.js:268).
"""

from __future__ import annotations

import abc
import asyncio
import re
import time
from dataclasses import dataclass
from typing import Callable


class StorageError(Exception):
    pass


class StreamIdMismatch(StorageError):
    """The recv stream's header names a different job than the one
    this listener is serving: a STALE sender (a cancelled restore's
    job dialing the port its successor rebound).  Raised before any
    dataset mutation; receivers drop the connection and keep waiting
    for their own stream rather than failing the restore."""


@dataclass(frozen=True)
class Snapshot:
    dataset: str
    name: str
    creation: float  # unix seconds

    @property
    def full(self) -> str:
        return "%s@%s" % (self.dataset, self.name)


_EPOCH_MS_RE = re.compile(r"^\d{13}$")


def snapshot_name_now() -> str:
    """Epoch-ms snapshot name, e.g. '1753731200123' (lib/zfsClient.js:216)."""
    return str(int(time.time() * 1000))


def is_epoch_ms_snapshot(name: str) -> bool:
    return bool(_EPOCH_MS_RE.match(name))


# Progress callback: (bytes_done, bytes_total_estimate_or_None)
ProgressCb = Callable[[int, int | None], None]


class StorageBackend(abc.ABC):
    """Dataset lifecycle + snapshot + bulk-stream operations.

    Dataset names are hierarchical, '/'-separated, zfs-style.  A dataset
    has a *mountpoint* (where consumers like PostgreSQL see its data) and
    may be mounted or not; unmounted data is not visible at the
    mountpoint.
    """

    # -- dataset lifecycle --

    @abc.abstractmethod
    async def exists(self, dataset: str) -> bool: ...

    @abc.abstractmethod
    async def create(self, dataset: str, *, mountpoint: str | None = None) -> None: ...

    @abc.abstractmethod
    async def destroy(self, dataset: str, *, recursive: bool = False) -> None: ...

    @abc.abstractmethod
    async def rename(self, old: str, new: str) -> None:
        """zfs rename semantics: children and snapshots move with the
        dataset (used by isolateDataset, lib/zfsClient.js:514-624)."""

    # -- properties / mounting --

    @abc.abstractmethod
    async def get_prop(self, dataset: str, prop: str) -> str | None: ...

    @abc.abstractmethod
    async def set_prop(self, dataset: str, prop: str, value: str) -> None: ...

    @abc.abstractmethod
    async def inherit_prop(self, dataset: str, prop: str) -> None: ...

    @abc.abstractmethod
    async def set_mountpoint(self, dataset: str, mountpoint: str) -> None: ...

    @abc.abstractmethod
    async def get_mountpoint(self, dataset: str) -> str | None: ...

    @abc.abstractmethod
    async def mount(self, dataset: str) -> None: ...

    @abc.abstractmethod
    async def unmount(self, dataset: str) -> None: ...

    @abc.abstractmethod
    async def is_mounted(self, dataset: str) -> bool:
        """Must verify against ground truth (the reference re-checks
        /etc/mnttab rather than trusting its own bookkeeping,
        lib/zfsClient.js:251-437)."""

    # -- snapshots --

    @abc.abstractmethod
    async def snapshot(self, dataset: str, name: str | None = None) -> Snapshot: ...

    @abc.abstractmethod
    async def list_snapshots(self, dataset: str) -> list[Snapshot]:
        """Sorted by creation time ascending (zfs list -s creation,
        lib/snapShotter.js:241-248)."""

    @abc.abstractmethod
    async def destroy_snapshot(self, dataset: str, name: str) -> None:
        """MUST be idempotent under absence: the snapshot — or the
        whole dataset — vanishing between a caller's list and this
        call means the deletion's goal is achieved, not an error.  The
        snapshotter's GC runs in a separate process from the sitter's
        restore path, which isolates/renames datasets at will; a
        backend that raises on absence feeds the stuck-snapshot alarm
        spuriously during rebuilds."""

    # -- bulk streams (the zfs send/recv data path, §3.3 of SURVEY.md) --

    @abc.abstractmethod
    async def estimate_send_size(self, dataset: str, name: str) -> int | None: ...

    @abc.abstractmethod
    async def send(
        self,
        dataset: str,
        name: str,
        writer: asyncio.StreamWriter,
        progress_cb: ProgressCb | None = None,
        compress: str | None = None,
        stream_id: str | None = None,
        from_snapshot: str | None = None,
    ) -> None:
        """Stream snapshot *name* of *dataset* into *writer* (the
        sender side of lib/backupSender.js:154-242).  *compress* is a
        NEGOTIATED codec name (storage.stream) the receiver offered,
        or None for the raw wire format; the chosen codec is named in
        the per-stream header so the receiver keys off the wire.
        *stream_id* (the backup job uuid) rides the same header so the
        receiver can reject a STALE sender's dial-back — a cancelled
        restore's job connecting to the port its successor rebound.
        *from_snapshot* requests an INCREMENTAL stream: only the delta
        between that (negotiated common) base snapshot and *name* goes
        on the wire, and the header names both ends so the receiver
        can refuse a stream whose base it does not hold.  A backend
        that cannot produce the requested delta raises StorageError —
        the job fails and the restore client retries full."""

    @abc.abstractmethod
    async def recv(
        self,
        dataset: str,
        reader: asyncio.StreamReader,
        progress_cb: ProgressCb | None = None,
        expect_stream_id: str | None = None,
    ) -> None:
        """Receive a stream produced by :meth:`send` into *dataset*,
        unmounted (zfs recv -u, lib/zfsClient.js:793).  The received
        snapshot is preserved on the receiver.  A stream whose header
        names a stream id different from *expect_stream_id* is
        refused BEFORE any dataset mutation (a headerless/old-sender
        stream cannot be verified and is accepted)."""

    # -- incremental (delta) rebuild support --
    #
    # The negotiation protocol (backup/client.py POST /backup `bases`
    # offer, backup/server.py `negotiate_base`) is backend-agnostic;
    # these hooks are where each backend declares HOW a delta applies.
    # Every default degrades to the full-stream path, so a backend
    # that implements none of them keeps working exactly as before.

    #: True when a delta applies onto the EXISTING dataset in place
    #: (zfs recv -F rolls back to the common base natively); False
    #: when the receiver builds a fresh dataset from a base snapshot
    #: held in another dataset (dirstore clones the isolated
    #: predecessor's base snapshot, then applies the delta onto it).
    delta_in_place = False

    def supports_delta(self) -> bool:
        """Whether this backend can send/apply incremental streams."""
        return False

    async def list_children(self, dataset: str) -> list[str]:
        """Direct child datasets of *dataset* (zfs list -d 1), full
        names.  Used to find a previously-isolated dataset whose
        snapshots can still serve as delta bases."""
        return []

    async def delta_candidates(
            self, dataset: str,
            fallback: str | None = None) -> tuple[list[str], str | None]:
        """Epoch-ms snapshot names this peer can offer as delta bases,
        plus the dataset that holds their content (*dataset* itself
        when it exists, else *fallback* — a pre-isolated predecessor —
        for backends that can clone a base from a foreign dataset).
        ``([], None)`` means ineligible: the restore goes full."""
        return [], None

    async def sweep_delta_debris(self, dataset: str) -> bool:
        """Remove the remains of a delta apply that died mid-flight
        (crash between create and the verified install).  Returns True
        when debris WAS swept — the caller must treat the store as
        suspect and force a FULL restore for this attempt."""
        return False

    async def recv_delta(
        self,
        dataset: str,
        reader: asyncio.StreamReader,
        *,
        base: str,
        base_src: str | None = None,
        progress_cb: ProgressCb | None = None,
        expect_stream_id: str | None = None,
    ) -> None:
        """Apply an incremental stream produced by :meth:`send` with
        ``from_snapshot=base``.  The stream header MUST name exactly
        *base*; anything else — a full stream, a different base, an
        unverifiable header — raises StorageError before any dataset
        mutation, and the caller retries full.  Divergence discovered
        DURING apply (content that fails the stream's post-apply
        verification) destroys the partial and raises: a bad base can
        cost a re-transfer, never a wrong dataset."""
        raise StorageError("backend does not support incremental "
                           "receive")

    # -- convenience shared across backends --

    async def latest_backup_snapshot(self, dataset: str) -> Snapshot | None:
        """Newest snapshot eligible for backup/GC: 13-digit epoch-ms names
        only (lib/backupSender.js:244-288)."""
        snaps = [s for s in await self.list_snapshots(dataset)
                 if is_epoch_ms_snapshot(s.name)]
        return snaps[-1] if snaps else None


async def flush_transport(writer: asyncio.StreamWriter,
                          timeout: float = 30.0) -> None:
    """Wait until the transport's write buffer is EMPTY.  drain() only
    waits for the low-water mark, which is not enough when raw-fd I/O
    (the native pump) is about to bypass the transport: any buffered
    bytes would be interleaved after the raw writes."""
    deadline = asyncio.get_running_loop().time() + timeout
    while writer.transport.get_write_buffer_size() > 0:
        if asyncio.get_running_loop().time() > deadline:
            raise StorageError("transport buffer never drained")
        await asyncio.sleep(0.005)


async def pump_child_to_socket(
    argv: list[str],
    writer: asyncio.StreamWriter,
    *,
    on_progress: Callable[[int], None] | None = None,
    stderr_task: Callable | None = None,
    env: dict | None = None,
    label: str = "native send",
):
    """MANATEE_NATIVE=1 shared core: spawn *argv* with stdout on a fresh
    pipe and splice that pipe into *writer*'s socket with the native
    pump (native/streampump.cpp) — the kernel-piped transfer of the
    reference's `zfs send | socket` (lib/backupSender.js:172-180) —
    leaving the event loop free.  The transport socket stays
    non-blocking (asyncio refuses setblocking); the pump absorbs EAGAIN
    with poll(2).

    The fd-lifetime/cancellation protocol here is corruption-critical
    and exists in exactly ONE place (both backends share it): the read
    fd must stay open until the pump THREAD exits, or a reused fd
    number would receive spliced bytes (silent corruption); on
    cancellation the abort flag + child kill bound the thread's exit.

    The child's stderr is ALWAYS consumed concurrently with the pump —
    a child emitting more than the pipe buffer of stderr (tar's
    'file changed as we read it' flood, zfs send -v progress) would
    otherwise block on stderr, stall its stdout short of EOF, and hang
    the pump forever.  *stderr_task* customizes the consumer: a
    callable receiving the process and returning a coroutine (default:
    read stderr to EOF, resolving to the bytes).  The helper owns the
    consumer task's whole lifecycle, including the subtle abort
    ordering: on the failure paths it is cancelled and AWAITED before
    reap_killed reads the same StreamReader (a concurrent read would
    silently skip the drain and proc.wait() could block forever).

    Returns (child process, stderr-consumer task) after a successful
    pump, the child unwaited — rc/stderr semantics stay with the
    caller.  *on_progress* (optional) runs in the pump thread with the
    byte total.
    """
    import os
    import threading

    from manatee_tpu import native
    from manatee_tpu.utils.executil import drain_and_reap

    # drain() only waits for the low-water mark: the raw-fd pump must
    # not start while a JSON header is still buffered in the transport,
    # or child bytes would precede it on the wire
    await flush_transport(writer)
    sock = writer.get_extra_info("socket")
    rfd, wfd = os.pipe()
    try:
        kwargs: dict = {"stdout": wfd, "stderr": asyncio.subprocess.PIPE}
        if env is not None:
            kwargs["env"] = env
        proc = await asyncio.create_subprocess_exec(*argv, **kwargs)
    except Exception:
        os.close(rfd)
        os.close(wfd)
        raise
    os.close(wfd)   # pump sees EOF when the child exits
    consumer = stderr_task or (lambda p: p.stderr.read())
    err_task = asyncio.create_task(consumer(proc))

    cancelled = threading.Event()

    def pump_cb(total: int) -> bool:
        if on_progress:
            on_progress(total)
        return cancelled.is_set()

    loop = asyncio.get_running_loop()
    fut = loop.run_in_executor(None, native.pump, rfd, sock.fileno(),
                               pump_cb)
    # the finallys below keep the fd bookkeeping intact even when
    # drain_and_reap re-raises a FRESH cancellation delivered during
    # its own awaits
    try:
        await asyncio.shield(fut)
    except asyncio.CancelledError:
        cancelled.set()
        try:
            await drain_and_reap(proc, err_task)
        finally:
            finished = True
            try:
                await asyncio.wait_for(fut, 10)
            except asyncio.TimeoutError:
                finished = False
            except asyncio.CancelledError:
                # a FRESH cancel delivered at this await: the original
                # CancelledError is re-raised below either way; only
                # close the fd if the thread truly finished
                finished = fut.done()
            except BaseException:
                finished = fut.done()
            if finished:
                os.close(rfd)
            # else: the pump thread is wedged past the bound while
            # still holding rfd — deliberately LEAK the fd: closing
            # it under a live thread would let a reused fd number
            # receive spliced bytes (the silent corruption this
            # protocol exists to prevent)
        raise
    except OSError as e:
        # the pump itself failed: the thread has exited, rfd is safe
        try:
            await drain_and_reap(proc, err_task)
        finally:
            os.close(rfd)
        raise StorageError("%s aborted: %s" % (label, e)) from e
    except Exception:
        # e.g. a raising progress callback surfacing through the pump
        # thread (an expected abort mode): same cleanup, then let the
        # caller's exception propagate — without this branch the child
        # ran on as an orphan and rfd leaked per failed send
        try:
            await drain_and_reap(proc, err_task)
        finally:
            os.close(rfd)
        raise
    os.close(rfd)
    return proc, err_task


async def pump_socket_to_child(
    proc,
    reader: asyncio.StreamReader,
    err_task: "asyncio.Task",
    on_progress: Callable[[int], None] | None = None,
    label: str = "recv",
) -> tuple[bytes, int]:
    """The recv-side twin of :func:`pump_child_to_socket`, shared by
    both backends: feed *reader* into the child's stdin, with the
    child's stderr consumed concurrently by *err_task* (a child
    emitting more than a pipe buffer of warnings would otherwise block
    on stderr, stop reading stdin, and wedge the drain forever).

    Returns (stderr bytes, return code) once the stream reaches EOF
    and the child exits.  A died network stream raises StorageError; a
    cancellation anywhere — mid-feed or on the tail awaits — reaps the
    child first (drain_and_reap) and propagates.  Backend-specific
    aftermath (destroying a partial dataset, rc interpretation) stays
    with the caller.
    """
    from manatee_tpu.utils.executil import drain_and_reap

    done = 0
    stream_error: Exception | None = None
    try:
        while True:
            try:
                chunk = await reader.read(1 << 16)
            except asyncio.CancelledError:
                raise      # reaped + propagated by the outer handler
            except Exception as e:
                # the network stream died — a clean child exit would be
                # meaningless (truncated-but-aligned archives extract
                # "ok")
                stream_error = e
                break
            if not chunk:
                break
            done += len(chunk)
            try:
                proc.stdin.write(chunk)
                await proc.stdin.drain()
            except (BrokenPipeError, ConnectionResetError):
                break  # child died early; rc/stderr tell the story
            if on_progress:
                on_progress(done)
        if stream_error is None:
            try:
                proc.stdin.close()
            except OSError:
                pass
            err = await err_task
            rc = await proc.wait()
    except BaseException:
        # aborted anywhere — a cancel, or a raising progress callback:
        # the child must not run on as an orphan blocked on its stdin
        await drain_and_reap(proc, err_task)
        raise
    if stream_error is not None:
        # raised OUTSIDE the try above so the reap runs exactly once
        await drain_and_reap(proc, err_task)
        raise StorageError("%s aborted: %s" % (label, stream_error)) \
            from stream_error
    return err, rc
