"""Bulk-stream data plane: negotiated wire compression and a bounded
read-ahead pipeline for the snapshot send/recv path.

The restore stream is the biggest single payer on a restore-bound
failover (the PR 3 analyzer attributes 90%+ of one to
``pg.catchup``/``pg.restore``), and its costs are classic data-plane
costs: disk read latency serialized with socket write latency, and raw
bytes on the wire.  Two remedies here, both modeled on
compression-accelerated collectives (gZCCL) and RPC-overhead work
(RPCAcc) from the motivation papers:

- :func:`pipeline_copy` — the producer (tar/zfs-send stdout) reads
  ahead into a BOUNDED queue while the consumer compresses, writes,
  and drains, so disk and network latency overlap instead of adding.
  The bound is the backpressure contract: a slow receiver blocks
  ``drain()``, the queue fills to ``readahead`` chunks, and the
  producer stalls — sender memory never exceeds
  ``readahead × chunk_size`` plus the transport's own buffer.

- negotiated OPTIONAL compression — the restore client OFFERS the
  codecs it can decode in its ``POST /backup`` body, the sender picks
  the best mutual one (:func:`negotiate`) and names it in the stream
  header, and the receiver keys its decompressor off that header.
  Either side missing the feature degrades to raw: an old receiver
  offers nothing, an old sender names nothing.  zlib is always
  available (stdlib); zstd only when the ``zstandard`` module is
  importable — never a hard dependency.

Tuning knobs (docs/performance.md): ``MANATEE_STREAM_CHUNK_KB``
(chunk size, default 256), ``MANATEE_STREAM_READAHEAD`` (queue depth,
default 4), ``MANATEE_STREAM_COMPRESS`` (``zstd``/``zlib``/``off`` —
restricts what the restore client offers).
"""

from __future__ import annotations

import asyncio
import contextlib
import os
import time
import zlib
from typing import Awaitable, Callable

from manatee_tpu.obs import get_registry

CHUNK_SIZE = max(4096, int(os.environ.get(
    "MANATEE_STREAM_CHUNK_KB", "256")) * 1024)
READAHEAD = max(1, int(os.environ.get("MANATEE_STREAM_READAHEAD", "4")))

# preference order when several codecs are mutually supported
_PREFERENCE = ("zstd", "zlib")

# wire-header magic for streams whose NATIVE format has no header to
# extend (zfs send): written only when a codec was negotiated — and a
# codec is only negotiated when the receiver OFFERED one, which is
# exactly the evidence that the receiver knows how to probe for this
# prefix.  Old peers never see it in either direction.
WIRE_MAGIC = b"MNTSTRM1"

_REG = get_registry()
# the basis label ("full" | "incremental") is what lets the bench and
# the dashboards show the incremental-rebuild saving: the same rebuild
# traffic, split by whether the whole dataset or just a delta moved
STREAM_BYTES = _REG.counter(
    "stream_bytes_total", "raw snapshot bytes moved by bulk streams",
    ("direction", "basis"))
STREAM_WIRE_BYTES = _REG.counter(
    "stream_wire_bytes_total",
    "bulk-stream bytes on the wire (after compression)",
    ("direction", "basis"))
# stream-stage latency in the sub-second-to-minutes regime (a small
# dataset rebuild is tens of ms; a production one, minutes)
STREAM_DUR = _REG.histogram(
    "stream_stage_duration_seconds",
    "wall-clock of one bulk-stream stage", ("direction", "basis"),
    buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 15.0,
             60.0, 300.0, 1800.0))
STREAM_THROUGHPUT = _REG.histogram(
    "stream_throughput_mb_per_second",
    "raw-byte throughput of one bulk-stream stage",
    ("direction", "basis"),
    buckets=(1.0, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0,
             2500.0))


def record_stream(direction: str, raw: int, wire: int,
                  duration_s: float, basis: str = "full") -> None:
    """Fold one completed stream stage into the registry; returns
    nothing — callers stamp span attrs themselves."""
    STREAM_BYTES.inc(raw, direction=direction, basis=basis)
    STREAM_WIRE_BYTES.inc(wire, direction=direction, basis=basis)
    STREAM_DUR.observe(duration_s, direction=direction, basis=basis)
    if duration_s > 0:
        STREAM_THROUGHPUT.observe(raw / duration_s / 1e6,
                                  direction=direction, basis=basis)


def throughput_mb_s(raw: int, duration_s: float) -> float | None:
    return round(raw / duration_s / 1e6, 3) if duration_s > 0 else None


class _Stage:
    """Byte accounting a stream stage fills in; consumed by
    :func:`recorded_stage` on successful exit."""

    raw = 0
    wire = 0


@contextlib.contextmanager
def recorded_stage(direction: str, dataset: str, codec: str | None,
                   basis: str = "full"):
    """One bulk-stream stage's span + clock + registry fold, shared by
    every backend's send/recv (the glue existed four times before).
    The body sets ``st.raw``/``st.wire``; metrics and span attrs are
    recorded only when the stage completes.  *basis* labels whether
    the stage moved the whole dataset or a negotiated delta, so the
    span waterfall and the wire-byte counters show the saving."""
    from manatee_tpu.obs import span
    st = _Stage()
    with span("stream.%s" % direction, dataset=dataset,
              codec=codec or "raw", basis=basis) as sp:
        clock = StageClock()
        yield st
        dur = clock.elapsed()
        record_stream(direction, st.raw, st.wire, dur, basis=basis)
        sp.attrs.update(
            bytes_total=st.raw, wire_bytes=st.wire,
            throughput_mb_s=throughput_mb_s(st.raw, dur))


def make_feed(reader, codec: str | None):
    """The recv-side decoder for a stream's named *codec* (None =
    raw passthrough); an unknown codec surfaces as StorageError —
    shared by both backends so the error shape cannot drift."""
    if not codec:
        return reader
    from manatee_tpu.storage.base import StorageError
    try:
        return DecompressingReader(reader, codec)
    except ValueError as e:
        raise StorageError(str(e)) from None


def check_stream_id(hdr: dict | None, expected: str | None) -> None:
    """Refuse a stream whose header names a different job than the
    one this listener serves (a STALE sender's dial-back) — shared by
    both backends, raised before any dataset mutation.  Headerless /
    id-less streams (old senders) cannot be verified and pass."""
    from manatee_tpu.storage.base import StreamIdMismatch
    got = (hdr or {}).get("stream")
    if expected and got and got != expected:
        raise StreamIdMismatch(
            "recv stream id %r does not match expected %r "
            "(stale sender?)" % (got, expected))


# ---------------------------------------------------------------- codecs

def have_zstd() -> bool:
    try:
        import zstandard  # noqa: F401
    except ImportError:
        return False
    return True


def available_codecs() -> list[str]:
    """Codecs THIS process can decode, best first — what the restore
    client offers in its POST /backup body.  MANATEE_STREAM_COMPRESS
    restricts it: 'off' offers nothing (raw), a codec name offers just
    that one."""
    knob = os.environ.get("MANATEE_STREAM_COMPRESS", "").strip().lower()
    if knob in ("off", "0", "none", "raw"):
        return []
    out = [c for c in _PREFERENCE
           if c == "zlib" or (c == "zstd" and have_zstd())]
    if knob:
        out = [c for c in out if c == knob]
    return out


def negotiate(offered) -> str | None:
    """The sender's half: best codec BOTH ends support, or None for
    raw.  *offered* is whatever arrived in the POST body — absent or
    malformed (an old peer) reads as an empty offer."""
    if not isinstance(offered, (list, tuple)):
        return None
    offers = {str(o) for o in offered}
    for codec in available_codecs():
        if codec in offers:
            return codec
    return None


class _ZstdCompressor:
    def __init__(self):
        import zstandard
        self._c = zstandard.ZstdCompressor().compressobj()

    def compress(self, data: bytes) -> bytes:
        return self._c.compress(data)

    def flush(self) -> bytes:
        return self._c.flush()


class _ZstdDecompressor:
    def __init__(self):
        import zstandard
        self._d = zstandard.ZstdDecompressor().decompressobj()

    def decompress(self, data: bytes) -> bytes:
        return self._d.decompress(data)

    def flush(self) -> bytes:
        return b""


class _ZlibDecompressor:
    def __init__(self):
        self._d = zlib.decompressobj()

    def decompress(self, data: bytes) -> bytes:
        return self._d.decompress(data)

    def flush(self) -> bytes:
        return self._d.flush()


def make_compressor(codec: str | None):
    if codec is None:
        return None
    if codec == "zlib":
        return zlib.compressobj(6)
    if codec == "zstd" and have_zstd():
        return _ZstdCompressor()
    raise ValueError("unsupported stream codec: %r" % codec)


def make_decompressor(codec: str | None):
    if codec is None:
        return None
    if codec == "zlib":
        return _ZlibDecompressor()
    if codec == "zstd" and have_zstd():
        return _ZstdDecompressor()
    raise ValueError("unsupported stream codec: %r" % codec)


class PrefixedReader:
    """StreamReader facade that replays already-probed bytes before
    the live stream — the pushback half of the zfs wire-header probe
    (a raw stream's first bytes were consumed looking for
    :data:`WIRE_MAGIC` and must reach the child intact)."""

    def __init__(self, prefix: bytes, reader: asyncio.StreamReader):
        self._prefix = prefix
        self._reader = reader

    async def read(self, n: int = -1) -> bytes:
        if self._prefix:
            out, self._prefix = self._prefix, b""
            return out
        return await self._reader.read(n)


async def probe_wire_header(reader: asyncio.StreamReader):
    """Receiver half of the headerless-format negotiation: read just
    enough to decide whether the sender wrote a ``WIRE_MAGIC`` header
    line.  Returns ``(header_dict | None, feed)`` where *feed* serves
    the remaining stream (with any probed raw bytes replayed)."""
    import json as _json
    buf = b""
    while len(buf) < len(WIRE_MAGIC):
        chunk = await reader.read(len(WIRE_MAGIC) - len(buf))
        if not chunk:
            return None, PrefixedReader(buf, reader)
        buf += chunk
    if buf != WIRE_MAGIC:
        return None, PrefixedReader(buf, reader)
    line = await reader.readline()
    try:
        hdr = _json.loads(line)
        if not isinstance(hdr, dict):
            raise ValueError(hdr)
    except ValueError:
        raise ValueError("bad wire header after magic: %r" % line[:200]) \
            from None
    return hdr, reader


class DecompressingReader:
    """StreamReader facade that inflates a named codec; the recv-side
    twin of the compressor in :func:`pipeline_copy`.  ``read()``
    returns RAW (decompressed) bytes, so progress accounting and the
    header's size estimate stay in one unit on both ends."""

    def __init__(self, reader: asyncio.StreamReader, codec: str,
                 chunk_size: int | None = None):
        self._reader = reader
        self._d = make_decompressor(codec)
        self._chunk = chunk_size or CHUNK_SIZE
        self._eof = False
        self.wire_bytes = 0

    async def read(self, n: int = -1) -> bytes:
        while not self._eof:
            chunk = await self._reader.read(self._chunk)
            if not chunk:
                self._eof = True
                return self._d.flush()
            self.wire_bytes += len(chunk)
            out = self._d.decompress(chunk)
            if out:
                return out
            # a compressed frame can span chunks: keep reading
        return b""


# -------------------------------------------------------------- pipeline

async def pipeline_copy(
    read_fn: Callable[[int], Awaitable[bytes]],
    writer: asyncio.StreamWriter,
    *,
    codec: str | None = None,
    chunk_size: int | None = None,
    readahead: int | None = None,
    progress: Callable[[int], None] | None = None,
) -> tuple[int, int]:
    """Copy ``read_fn`` → *writer* with bounded read-ahead and optional
    compression; returns ``(raw_bytes, wire_bytes)``.

    The producer task keeps ``readahead`` chunks in flight so the next
    disk/child read overlaps the current socket write; every write is
    followed by ``drain()``, so a slow receiver stalls the producer
    through the full queue — the memory bound the backpressure test
    pins.  A failed read surfaces on the consumer side (never a hung
    queue); a failed write cancels the producer before propagating."""
    chunk_size = chunk_size or CHUNK_SIZE
    readahead = readahead or READAHEAD
    comp = make_compressor(codec)
    q: asyncio.Queue = asyncio.Queue(maxsize=readahead)

    async def produce() -> None:
        try:
            while True:
                chunk = await read_fn(chunk_size)
                if not chunk:
                    await q.put((None, None))
                    return
                await q.put((chunk, None))
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # surface the read error THROUGH the queue: raising here
            # alone would leave the consumer blocked on q.get forever
            await q.put((None, e))

    producer = asyncio.create_task(produce())
    raw = wire = 0
    try:
        while True:
            chunk, err = await q.get()
            if err is not None:
                raise err
            if chunk is None:
                break
            raw += len(chunk)
            data = comp.compress(chunk) if comp else chunk
            if data:
                wire += len(data)
                writer.write(data)
                await writer.drain()
            if progress:
                progress(raw)
        if comp is not None:
            tail = comp.flush()
            if tail:
                wire += len(tail)
                writer.write(tail)
                await writer.drain()
    finally:
        producer.cancel()
        with contextlib.suppress(asyncio.CancelledError, Exception):
            await producer
    return raw, wire


class StageClock:
    """Tiny monotonic stopwatch shared by the send/recv stages."""

    def __init__(self):
        self.t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self.t0
