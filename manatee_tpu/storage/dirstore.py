"""Directory-based storage backend for machines without ZFS.

Functional parity with the zfs backend at the interface level:
hierarchical datasets, mount/unmount visibility at a mountpoint,
point-in-time snapshots, rename-with-children (isolation), and tar-framed
send/recv bulk streams.  Snapshots are full copies — correct (unlike
hardlink farms) even when the consumer (PostgreSQL) rewrites files in
place; this backend optimizes for fidelity in tests, not disk usage.

On-disk layout under the backend root:

    datasets/<a>/<b>/...        nested dirs, one per dataset path component
        @data/                  the dataset's live content
        @snapshots/<name>/      snapshot content
        @meta.json              {mountpoint, mounted, props, snaps:{name:ctime}}

Mounting is emulated with a symlink: <mountpoint> -> .../@data, so
unmounted data really is invisible at the mountpoint, as with zfs.
"""

from __future__ import annotations

import asyncio
import json
import os
import shutil
import threading
import time
from pathlib import Path

from manatee_tpu import faults
from manatee_tpu.storage import stream as wirestream
from manatee_tpu.storage.base import (
    ProgressCb,
    Snapshot,
    StorageBackend,
    StorageError,
    pump_child_to_socket,
    pump_socket_to_child,
    snapshot_name_now,
)
from manatee_tpu.utils.executil import drain_and_reap

_RESERVED = {"@data", "@snapshots", "@meta.json"}
# the keys every @meta.json carries (create() writes exactly these).
# Together with _RESERVED this IS the on-disk contract `manatee-adm
# doctor` verifies (manatee_tpu/doctor.py imports both) — change them
# here and the verifier follows.
META_KEYS = ("mountpoint", "mounted", "props", "snaps")


class DirBackend(StorageBackend):
    def __init__(self, root: str | Path):
        self.root = Path(root)
        (self.root / "datasets").mkdir(parents=True, exist_ok=True)
        self._sweep_meta_tmp()

    # ---- internals ----

    def _sweep_meta_tmp(self, min_age_s: float = 60.0) -> None:
        """Startup cleanup of ``@meta.json.tmp-<pid>-<tid>`` files a
        crashed save never renamed into place — the same discipline
        coordd applies to its snapshot tmp orphans.  Only files older
        than *min_age_s* go: a sibling process (the snapshotter saving
        this dataset's meta right now) has an in-flight tmp that is
        milliseconds old, and unlinking it would fail that save."""
        now = time.time()
        base = self.root / "datasets"
        for dirpath, dirnames, filenames in os.walk(base):
            # never descend into dataset content
            dirnames[:] = [n for n in dirnames
                           if n not in ("@data", "@snapshots")]
            for name in filenames:
                if not name.startswith("@meta.json.tmp"):
                    continue
                p = Path(dirpath) / name
                try:
                    if now - p.stat().st_mtime >= min_age_s:
                        p.unlink()
                except OSError:
                    pass

    def _dspath(self, dataset: str) -> Path:
        if not dataset or dataset.startswith("/") or ".." in dataset.split("/"):
            raise StorageError("bad dataset name: %r" % dataset)
        for comp in dataset.split("/"):
            if comp in _RESERVED or not comp:
                raise StorageError("bad dataset name: %r" % dataset)
        return self.root / "datasets" / dataset

    def _meta_path(self, dataset: str) -> Path:
        return self._dspath(dataset) / "@meta.json"

    def _load_meta(self, dataset: str) -> dict:
        try:
            return json.loads(self._meta_path(dataset).read_text())
        except FileNotFoundError:
            raise StorageError("dataset does not exist: %s" % dataset) from None

    def _save_meta(self, dataset: str, meta: dict) -> None:
        # crash-safe install, same discipline as coordd's snapshot
        # path: tmp write, fsync the FILE (rename-before-data can
        # install an empty/truncated meta — the very damage
        # `manatee-adm doctor` classifies), atomic rename, fsync the
        # parent dir so the rename itself survives a power loss.
        # DELIBERATELY synchronous from the event loop: every caller
        # is a load-modify-save section whose atomicity the loop
        # guarantees only while there is no await between the load
        # and the installed save — pushing the fsyncs to a thread
        # would let a cancelled transition's orphaned save land AFTER
        # a successor's, reinstating stale meta.  Meta is tiny and
        # saves are rare (snapshots, mounts, transitions), so the
        # bounded fsync stall is the cheaper side of the trade.
        # The tmp name is per-writer-unique: the sitter AND the
        # snapshotter both save this dataset's meta, and a SHARED tmp
        # path lets one writer truncate the file another is about to
        # rename into place — installing torn meta (the storm suite
        # caught exactly that once the fsync widened the window)
        p = self._meta_path(dataset)
        tmp = p.with_name("%s.tmp-%d-%d"
                          % (p.name, os.getpid(),
                             threading.get_ident()))
        with open(tmp, "w") as f:
            f.write(json.dumps(meta, indent=2))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
        try:
            fd = os.open(p.parent, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def _exists_sync(self, dataset: str) -> bool:
        return self._meta_path(dataset).exists()

    def _mountlink(self, dataset: str) -> Path | None:
        meta = self._load_meta(dataset)
        return Path(meta["mountpoint"]) if meta.get("mountpoint") else None

    # ---- dataset lifecycle ----

    async def exists(self, dataset: str) -> bool:
        return self._exists_sync(dataset)

    async def create(self, dataset: str, *, mountpoint: str | None = None) -> None:
        if self._exists_sync(dataset):
            raise StorageError("dataset exists: %s" % dataset)
        if "/" in dataset and not self._exists_sync(dataset.rpartition("/")[0]):
            # zfs parity: the parent dataset must exist (a bare top-level
            # name plays the role of a pool root)
            raise StorageError("parent dataset does not exist: %s"
                               % dataset.rpartition("/")[0])
        p = self._dspath(dataset)
        if p.exists():
            # @meta.json is the existence marker (doctor's
            # dir-without-meta debris class): a create/recv cancelled
            # between the mkdirs and the meta save strands exactly
            # this shape, and destroy() cannot see it — without this
            # sweep every later create of the same dataset dies on
            # mkdir FileExistsError FOREVER (a restore-wedge the
            # overlapped takeover's tighter cancel timing exposed in
            # tier-1).  Only a CHILDLESS meta-less dir is debris; one
            # holding child datasets is load-bearing structure.
            children = [c.name for c in p.iterdir()
                        if c.name not in _RESERVED]
            if children:
                raise StorageError(
                    "dataset path %s exists without metadata and has "
                    "children %s" % (dataset, children))
            await asyncio.to_thread(shutil.rmtree, p)
        (p / "@data").mkdir(parents=True)
        (p / "@snapshots").mkdir()
        self._save_meta(dataset, {
            "mountpoint": mountpoint,
            "mounted": False,
            "props": {"canmount": "on"},
            "snaps": {},
        })

    async def destroy(self, dataset: str, *, recursive: bool = False) -> None:
        p = self._dspath(dataset)
        if not self._exists_sync(dataset):
            raise StorageError("dataset does not exist: %s" % dataset)
        children = [c.name for c in p.iterdir()
                    if c.is_dir() and c.name not in _RESERVED]
        if children and not recursive:
            raise StorageError("dataset %s has children %s (need recursive)"
                               % (dataset, children))
        for child in children:
            await self.destroy("%s/%s" % (dataset, child), recursive=True)
        if await self.is_mounted(dataset):
            await self.unmount(dataset)
        await asyncio.to_thread(shutil.rmtree, p)
        # prune now-empty parent plain dirs up to datasets/
        parent = p.parent
        base = self.root / "datasets"
        while parent != base and not any(parent.iterdir()) \
                and not (parent / "@meta.json").exists():
            parent.rmdir()
            parent = parent.parent

    async def rename(self, old: str, new: str) -> None:
        po, pn = self._dspath(old), self._dspath(new)
        if not self._exists_sync(old):
            raise StorageError("dataset does not exist: %s" % old)
        if pn.exists():
            raise StorageError("rename target exists: %s" % new)
        was_mounted = await self.is_mounted(old)
        pn.parent.mkdir(parents=True, exist_ok=True)
        await asyncio.to_thread(os.rename, po, pn)
        if was_mounted:
            # zfs keeps a renamed dataset mounted; re-point the symlink at
            # the moved @data so the mountpoint stays live
            mp = Path(self._load_meta(new)["mountpoint"])
            if mp.is_symlink():
                os.unlink(mp)
            os.symlink((pn / "@data").resolve(), mp)

    # ---- properties / mounting ----

    async def get_prop(self, dataset: str, prop: str) -> str | None:
        meta = self._load_meta(dataset)
        if prop == "mountpoint":
            return meta.get("mountpoint")
        if prop == "mounted":
            return "yes" if meta.get("mounted") else "no"
        return meta.get("props", {}).get(prop)

    async def set_prop(self, dataset: str, prop: str, value: str) -> None:
        meta = self._load_meta(dataset)
        if prop == "mountpoint":
            meta["mountpoint"] = value
        else:
            meta.setdefault("props", {})[prop] = value
        self._save_meta(dataset, meta)

    async def inherit_prop(self, dataset: str, prop: str) -> None:
        meta = self._load_meta(dataset)
        meta.get("props", {}).pop(prop, None)
        self._save_meta(dataset, meta)

    async def set_mountpoint(self, dataset: str, mountpoint: str) -> None:
        was_mounted = await self.is_mounted(dataset)
        if was_mounted:
            await self.unmount(dataset)
        await self.set_prop(dataset, "mountpoint", mountpoint)
        if was_mounted:
            await self.mount(dataset)

    async def get_mountpoint(self, dataset: str) -> str | None:
        return (await self.get_prop(dataset, "mountpoint"))

    async def mount(self, dataset: str) -> None:
        meta = self._load_meta(dataset)
        mp = meta.get("mountpoint")
        if not mp:
            raise StorageError("dataset %s has no mountpoint" % dataset)
        link = Path(mp)
        target = self._dspath(dataset) / "@data"
        if link.is_symlink():
            if os.path.realpath(link) == str(target.resolve()):
                meta["mounted"] = True
                self._save_meta(dataset, meta)
                return
            raise StorageError("mountpoint %s busy (-> %s)"
                               % (mp, os.path.realpath(link)))
        if link.exists():
            raise StorageError("mountpoint %s exists and is not a mount" % mp)
        link.parent.mkdir(parents=True, exist_ok=True)
        os.symlink(target.resolve(), link)
        meta["mounted"] = True
        self._save_meta(dataset, meta)

    async def unmount(self, dataset: str) -> None:
        meta = self._load_meta(dataset)
        mp = meta.get("mountpoint")
        if mp and Path(mp).is_symlink():
            # only unlink if the mountpoint is OUR mount — another dataset
            # may own that path now
            ours = str((self._dspath(dataset) / "@data").resolve())
            if os.path.realpath(mp) == ours:
                os.unlink(mp)
        meta["mounted"] = False
        self._save_meta(dataset, meta)

    async def is_mounted(self, dataset: str) -> bool:
        # ground truth = the symlink, not the meta flag (mnttab-verify
        # parity, lib/zfsClient.js:251-437)
        meta = self._load_meta(dataset)
        mp = meta.get("mountpoint")
        if not mp or not Path(mp).is_symlink():
            return False
        return os.path.realpath(mp) == str((self._dspath(dataset) / "@data").resolve())

    # ---- snapshots ----

    async def snapshot(self, dataset: str, name: str | None = None) -> Snapshot:
        # error:StorageError models a failed disk write at snapshot
        # time (callers like _snapshot_safe must tolerate it)
        await faults.point("storage.snapshot")
        name = name or snapshot_name_now()
        meta = self._load_meta(dataset)
        if name in meta["snaps"]:
            raise StorageError("snapshot exists: %s@%s" % (dataset, name))
        src = self._dspath(dataset) / "@data"
        dst = self._dspath(dataset) / "@snapshots" / name
        await asyncio.to_thread(shutil.copytree, src, dst, symlinks=True)
        now = time.time()
        meta["snaps"][name] = now
        self._save_meta(dataset, meta)
        return Snapshot(dataset, name, now)

    async def list_snapshots(self, dataset: str) -> list[Snapshot]:
        meta = self._load_meta(dataset)
        snaps = [Snapshot(dataset, n, t) for n, t in meta["snaps"].items()]
        snaps.sort(key=lambda s: (s.creation, s.name))
        return snaps

    async def destroy_snapshot(self, dataset: str, name: str) -> None:
        """Idempotent: the snapshotter's GC and a sitter's restore run
        in SEPARATE processes, so the dataset (or just this snapshot)
        can vanish between any two steps here — absence, however it
        came about, means the deletion's goal is achieved (the
        extended-storm race: a rebuild isolates/replaces the dataset
        mid-GC-pass, and raising here fed the stuck-snapshot alarm
        spuriously)."""
        try:
            meta = self._load_meta(dataset)
        except StorageError:
            return               # dataset replaced/renamed away
        if name not in meta["snaps"]:
            return               # another pass (or a restore) got it
        try:
            await asyncio.to_thread(
                shutil.rmtree,
                self._dspath(dataset) / "@snapshots" / name)
        except FileNotFoundError:
            pass
        except OSError as e:
            raise StorageError("cannot destroy snapshot %s@%s: %s"
                               % (dataset, name, e)) from None
        try:
            meta = self._load_meta(dataset)
        except StorageError:
            return
        meta["snaps"].pop(name, None)
        self._save_meta(dataset, meta)

    # ---- bulk streams ----
    #
    # Frame: one JSON header line {"snapshot": ..., "size": ...}\n followed
    # by a tar stream of the snapshot content (role of `zfs send`,
    # lib/backupSender.js:172-180).

    async def estimate_send_size(self, dataset: str, name: str) -> int | None:
        src = self._dspath(dataset) / "@snapshots" / name
        if not src.exists():
            raise StorageError("no such snapshot: %s@%s" % (dataset, name))

        def du(p: Path) -> int:
            total = 0
            for f in p.rglob("*"):
                if f.is_file() and not f.is_symlink():
                    total += f.stat().st_size
            return total

        return await asyncio.to_thread(du, src)

    async def send(
        self,
        dataset: str,
        name: str,
        writer: asyncio.StreamWriter,
        progress_cb: ProgressCb | None = None,
        compress: str | None = None,
        stream_id: str | None = None,
    ) -> None:
        src = self._dspath(dataset) / "@snapshots" / name
        if not src.exists():
            raise StorageError("no such snapshot: %s@%s" % (dataset, name))
        await faults.point("storage.send")
        size = await self.estimate_send_size(dataset, name)
        hdr = {"snapshot": name, "size": size}
        if compress:
            # named in the per-stream header so the receiver keys its
            # decompressor off the wire, not off config agreement
            hdr["compression"] = compress
        if stream_id:
            hdr["stream"] = stream_id
        header = json.dumps(hdr) + "\n"
        try:
            writer.write(header.encode())
            await writer.drain()
        except Exception as e:
            raise StorageError("send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e
        from manatee_tpu import native
        # the native splice pump moves the child's raw stdout in the
        # kernel — compression needs the bytes in userspace, so a
        # negotiated codec takes the python pipeline instead
        if not compress and native.enabled() \
                and writer.get_extra_info("socket") is not None:
            await self._send_native(dataset, name, src, size, writer,
                                    progress_cb)
            return
        proc = await asyncio.create_subprocess_exec(
            "tar", "-C", str(src), "-cf", "-", ".",
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        # drain stderr CONCURRENTLY: a tar emitting more warnings than
        # the pipe buffer would block on stderr and stall stdout short
        # of EOF, deadlocking the copy pipeline below
        t_err = asyncio.create_task(proc.stderr.read())
        try:
            with wirestream.recorded_stage("send", dataset,
                                           compress) as st:
                st.raw, st.wire = await wirestream.pipeline_copy(
                    proc.stdout.read, writer, codec=compress,
                    progress=(lambda d: progress_cb(d, size))
                    if progress_cb else None)
        except asyncio.CancelledError:
            # our caller was cancelled (server shutdown, peer-handler
            # teardown): same cleanup, then let the cancel propagate —
            # `except Exception` alone would leak the drainer task and
            # leave tar blocked on its full stdout pipe forever
            await drain_and_reap(proc, t_err)
            raise
        except Exception as e:
            # receiver went away mid-stream: kill tar first, or reading its
            # stderr to EOF below would block on the full stdout pipe
            await drain_and_reap(proc, t_err)
            raise StorageError("send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e
        try:
            err = await t_err
            rc = await proc.wait()
        except asyncio.CancelledError:
            # cancellation landing on the post-stream awaits must
            # still reap the child
            await drain_and_reap(proc, t_err)
            raise
        if rc != 0:
            raise StorageError("tar send failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))

    async def _send_native(self, dataset: str, name: str, src,
                           size: int | None,
                           writer: asyncio.StreamWriter,
                           progress_cb: ProgressCb | None) -> None:
        """MANATEE_NATIVE=1 bulk path: tar's stdout is spliced into the
        peer socket by the native pump — fd-lifetime/cancellation
        protocol shared with ZfsBackend in
        storage.base.pump_child_to_socket."""
        def on_progress(total: int) -> None:
            if progress_cb:
                progress_cb(total, size)

        proc, t_err = await pump_child_to_socket(
            ["tar", "-C", str(src), "-cf", "-", "."], writer,
            on_progress=on_progress,
            label="native send of %s@%s" % (dataset, name))
        try:
            err = await t_err
            rc = await proc.wait()
        except asyncio.CancelledError:
            # the pump finished but a cancel cut the tail awaits: the
            # child must still be reaped (zfs sibling reaps in exactly
            # this window)
            await drain_and_reap(proc, t_err)
            raise
        except Exception as e:
            await drain_and_reap(proc, t_err)
            raise StorageError("native send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e
        if rc != 0:
            raise StorageError("tar send failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))

    async def recv(
        self,
        dataset: str,
        reader: asyncio.StreamReader,
        progress_cb: ProgressCb | None = None,
        expect_stream_id: str | None = None,
    ) -> None:
        await faults.point("storage.recv")
        hdr_line = await reader.readline()
        if not hdr_line:
            raise StorageError("empty recv stream")
        try:
            hdr = json.loads(hdr_line)
            snapname = hdr["snapshot"]
            size = hdr.get("size")
        except (json.JSONDecodeError, KeyError, TypeError):
            raise StorageError("bad recv stream header: %r" % hdr_line) from None
        # stream identity, BEFORE any dataset mutation: a cancelled
        # restore's job can dial back into the port its successor
        # rebound, and receiving the stale stream would race (and
        # corrupt) the fresh attempt's dataset.  A header without a
        # stream id (an old sender) cannot be verified and passes.
        wirestream.check_stream_id(hdr, expect_stream_id)
        # the snapshot name came off the wire: refuse anything that is not
        # a single safe path component
        if (not isinstance(snapname, str) or not snapname
                or "/" in snapname or "\\" in snapname
                or snapname in (".", "..") or snapname in _RESERVED):
            raise StorageError("bad snapshot name in stream: %r" % (snapname,))
        # compression is whatever the SENDER named in the header (it
        # only ever names a codec we offered); an absent key — an old
        # sender — is raw
        codec = hdr.get("compression")
        feed = wirestream.make_feed(reader, codec)

        if self._exists_sync(dataset):
            raise StorageError(
                "recv target exists: %s (isolate or destroy it first)" % dataset)
        await self.create(dataset)
        data = self._dspath(dataset) / "@data"

        try:
            proc = await asyncio.create_subprocess_exec(
                "tar", "-C", str(data), "-xf", "-",
                stdin=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
        except BaseException:
            # a cancel landing on the spawn (a topology change
            # cancelling the restore in its first milliseconds) must
            # not strand the just-created dataset: it would shadow
            # every later attempt with 'recv target exists'
            await self._destroy_quietly(dataset)
            raise
        # drain stderr CONCURRENTLY with the feed: a tar emitting more
        # warnings than the pipe buffer ('implausibly old time stamp',
        # unknown extended headers) would block on stderr, stop
        # reading stdin, and wedge the drain() below forever
        t_err = asyncio.create_task(proc.stderr.read())
        seen = {"raw": 0}

        def _prog(d: int) -> None:
            seen["raw"] = d          # raw (post-inflate) bytes fed to tar
            if progress_cb:
                progress_cb(d, size)

        try:
            with wirestream.recorded_stage("recv", dataset,
                                           codec) as st:
                err, rc = await pump_socket_to_child(
                    proc, feed, t_err, on_progress=_prog,
                    label="recv into %s" % dataset)
                st.raw = seen["raw"]
                st.wire = feed.wire_bytes if codec else st.raw
        except BaseException:
            # restore aborted (cancel, dead stream, anything): the
            # helper already reaped the child; remove the partial
            # dataset — leaving it would fail the NEXT restore attempt
            # with 'recv target exists' until an operator intervenes
            await self._destroy_quietly(dataset)
            raise
        if rc != 0:
            await self._destroy_quietly(dataset)
            raise StorageError("tar recv failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))
        try:
            # preserve the received snapshot on the receiver, like
            # zfs recv
            snapdir = self._dspath(dataset) / "@snapshots" / snapname
            await asyncio.to_thread(shutil.copytree, data, snapdir,
                                    symlinks=True)
            meta = self._load_meta(dataset)
            meta["snaps"][snapname] = time.time()
            meta["mounted"] = False  # zfs recv -u: received unmounted
            self._save_meta(dataset, meta)
        except BaseException:
            # ANY failure past this point — cancel, ENOSPC, perms —
            # strands a half-recorded dataset that blocks every later
            # restore with 'recv target exists': remove it like any
            # other aborted restore
            await self._destroy_quietly(dataset)
            raise

    async def _destroy_quietly(self, dataset: str) -> None:
        """Abort-path cleanup: the dataset vanishing concurrently (a
        rebuild isolating/renaming it — the cross-process race the
        storm tier documents) means the removal's goal is achieved; a
        raise here would MASK the original abort cause."""
        try:
            await self.destroy(dataset, recursive=True)
        except (StorageError, OSError):
            # OSError: destroy's rmtree/iterdir hit the vanish mid-way.
            # StorageError can also mean a META-LESS partial (this very
            # abort landed inside create(), before the meta save):
            # destroy() cannot see it, so clear the debris directly —
            # leaving it would fail every later recv with
            # 'File exists' until an operator intervened.
            try:
                p = self._dspath(dataset)
            except StorageError:
                return
            if p.exists() and not (p / "@meta.json").exists():
                try:
                    await asyncio.to_thread(shutil.rmtree, p)
                except OSError:
                    pass
