"""Directory-based storage backend for machines without ZFS.

Functional parity with the zfs backend at the interface level:
hierarchical datasets, mount/unmount visibility at a mountpoint,
point-in-time snapshots, rename-with-children (isolation), and tar-framed
send/recv bulk streams.  Snapshots are full copies — correct (unlike
hardlink farms) even when the consumer (PostgreSQL) rewrites files in
place; this backend optimizes for fidelity in tests, not disk usage.

On-disk layout under the backend root:

    datasets/<a>/<b>/...        nested dirs, one per dataset path component
        @data/                  the dataset's live content
        @snapshots/<name>/      snapshot content
        @meta.json              {mountpoint, mounted, props, snaps:{name:ctime}}

Mounting is emulated with a symlink: <mountpoint> -> .../@data, so
unmounted data really is invisible at the mountpoint, as with zfs.
"""

from __future__ import annotations

import asyncio
import hashlib
import json
import os
import shutil
import tempfile
import threading
import time
import zlib
from pathlib import Path

from manatee_tpu import faults
from manatee_tpu.storage import stream as wirestream
from manatee_tpu.storage.base import (
    ProgressCb,
    Snapshot,
    StorageBackend,
    StorageError,
    is_epoch_ms_snapshot,
    pump_child_to_socket,
    pump_socket_to_child,
    snapshot_name_now,
)
from manatee_tpu.utils.executil import drain_and_reap

_RESERVED = {"@data", "@snapshots", "@meta.json", "@manifests"}
# the keys every @meta.json carries (create() writes exactly these).
# Together with _RESERVED this IS the on-disk contract `manatee-adm
# doctor` verifies (manatee_tpu/doctor.py imports both) — change them
# here and the verifier follows.
META_KEYS = ("mountpoint", "mounted", "props", "snaps")

# cap on the compressed delta-detail blob (deletion list + target
# manifest) a recv will read off the wire — a corrupt header length
# must not make the receiver allocate unboundedly
MAX_DELTA_DETAIL = 256 << 20


# ---- per-snapshot content manifests (the delta plane's ground truth)

def _sha256_file(p: Path) -> str:
    h = hashlib.sha256()
    with open(p, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def manifest_scan(root: str | Path, with_hash: bool = True) -> dict:
    """Walk a snapshot (or @data) directory into a manifest map:
    relpath -> entry, where entry is ``{"t": "f", "size", "mtime",
    "m", "h"}`` for files, ``{"t": "l", "lnk"}`` for symlinks,
    ``{"t": "d", "m"}`` for directories (``m`` = permission bits — a
    chmod with unchanged bytes must still ship, or full and
    incremental restores would yield different datasets).
    ``with_hash=False`` (doctor's structural check) skips the content
    hashes.  Pure/synchronous so it can run under
    ``asyncio.to_thread`` and offline in the doctor alike."""
    root = Path(root)
    files: dict[str, dict] = {}
    for dirpath, dirnames, filenames in os.walk(root):
        dp = Path(dirpath)
        for n in list(dirnames):
            p = dp / n
            if p.is_symlink():
                files[p.relative_to(root).as_posix()] = {
                    "t": "l", "lnk": os.readlink(p)}
                dirnames.remove(n)    # never follow into the target
            else:
                files[p.relative_to(root).as_posix()] = {
                    "t": "d", "m": p.stat().st_mode & 0o7777}
        for n in filenames:
            p = dp / n
            if p.is_symlink():
                files[p.relative_to(root).as_posix()] = {
                    "t": "l", "lnk": os.readlink(p)}
                continue
            st = p.stat()
            ent: dict = {"t": "f", "size": st.st_size,
                         "mtime": round(st.st_mtime, 6),
                         "m": st.st_mode & 0o7777}
            if with_hash:
                ent["h"] = _sha256_file(p)
            files[p.relative_to(root).as_posix()] = ent
    return files


def manifest_entry_key(ent: dict | None, with_hash: bool = True):
    """The comparable identity of one manifest entry.  mtime is
    DELIBERATELY excluded: unchanged files keep the receiver's base
    clone timestamps, which legitimately differ from the sender's —
    content (type/size/mode/hash, link target) is the verdict."""
    if not isinstance(ent, dict):
        return None
    t = ent.get("t")
    if t == "f":
        return ("f", ent.get("size"), ent.get("m"),
                ent.get("h") if with_hash else None)
    if t == "l":
        return ("l", ent.get("lnk"))
    if t == "d":
        return ("d", ent.get("m"))
    return ("?",)


def manifest_delta(base_files: dict, tgt_files: dict) \
        -> tuple[list[str], list[str]]:
    """(changed-or-added paths, deleted paths) between two manifests —
    what an incremental send ships and what the receiver removes."""
    changed = sorted(
        p for p, e in tgt_files.items()
        if manifest_entry_key(e) != manifest_entry_key(
            base_files.get(p)))
    deleted = sorted(p for p in base_files if p not in tgt_files)
    return changed, deleted


def manifest_diff_paths(got: dict, want: dict,
                        with_hash: bool = True) -> list[str]:
    """Paths on which two manifests disagree (either direction) — the
    post-apply verification and the doctor's structural check share
    this so the two verdicts cannot drift."""
    bad = [p for p, e in want.items()
           if manifest_entry_key(e, with_hash)
           != manifest_entry_key(got.get(p), with_hash)]
    bad += [p for p in got if p not in want]
    return sorted(set(bad))


def _check_wire_relpath(path) -> str:
    """A path that came off the wire (delta manifest / deletion list)
    must be a safe relative path before it is allowed anywhere near a
    filesystem operation."""
    if not isinstance(path, str) or not path or path.startswith("/") \
            or "\\" in path or "\x00" in path \
            or any(comp in ("", ".", "..") for comp in path.split("/")):
        raise StorageError("unsafe path in delta stream: %r" % (path,))
    return path


class DirBackend(StorageBackend):
    def __init__(self, root: str | Path):
        self.root = Path(root)
        (self.root / "datasets").mkdir(parents=True, exist_ok=True)
        self._sweep_meta_tmp()

    # ---- internals ----

    def _sweep_meta_tmp(self, min_age_s: float = 60.0) -> None:
        """Startup cleanup of ``@meta.json.tmp-<pid>-<tid>`` files —
        and their ``@manifests/*.json.tmp-*`` siblings — a crashed
        save never renamed into place: the same discipline coordd
        applies to its snapshot tmp orphans.  Only files older than
        *min_age_s* go: a sibling process (the snapshotter saving this
        dataset's meta right now) has an in-flight tmp that is
        milliseconds old, and unlinking it would fail that save."""
        now = time.time()

        def aged_unlink(p: Path) -> None:
            try:
                if now - p.stat().st_mtime >= min_age_s:
                    p.unlink()
            except OSError:
                pass

        base = self.root / "datasets"
        for dirpath, dirnames, filenames in os.walk(base):
            if "@manifests" in dirnames:
                # crashed manifest writes strand tmps too; nothing
                # else ever visits them (the doctor only notes them)
                try:
                    for p in (Path(dirpath) / "@manifests").iterdir():
                        if ".json.tmp" in p.name:
                            aged_unlink(p)
                except OSError:
                    pass
            # never descend into dataset content
            dirnames[:] = [n for n in dirnames
                           if n not in ("@data", "@snapshots",
                                        "@manifests")]
            for name in filenames:
                if name.startswith("@meta.json.tmp"):
                    aged_unlink(Path(dirpath) / name)

    def _dspath(self, dataset: str) -> Path:
        if not dataset or dataset.startswith("/") or ".." in dataset.split("/"):
            raise StorageError("bad dataset name: %r" % dataset)
        for comp in dataset.split("/"):
            if comp in _RESERVED or not comp:
                raise StorageError("bad dataset name: %r" % dataset)
        return self.root / "datasets" / dataset

    def _meta_path(self, dataset: str) -> Path:
        return self._dspath(dataset) / "@meta.json"

    def _load_meta(self, dataset: str) -> dict:
        try:
            return json.loads(self._meta_path(dataset).read_text())
        except FileNotFoundError:
            raise StorageError("dataset does not exist: %s" % dataset) from None

    def _save_meta(self, dataset: str, meta: dict) -> None:
        # crash-safe install, same discipline as coordd's snapshot
        # path: tmp write, fsync the FILE (rename-before-data can
        # install an empty/truncated meta — the very damage
        # `manatee-adm doctor` classifies), atomic rename, fsync the
        # parent dir so the rename itself survives a power loss.
        # DELIBERATELY synchronous from the event loop: every caller
        # is a load-modify-save section whose atomicity the loop
        # guarantees only while there is no await between the load
        # and the installed save — pushing the fsyncs to a thread
        # would let a cancelled transition's orphaned save land AFTER
        # a successor's, reinstating stale meta.  Meta is tiny and
        # saves are rare (snapshots, mounts, transitions), so the
        # bounded fsync stall is the cheaper side of the trade.
        # That invariant is MACHINE-CHECKED now: mnt-lint's
        # atomic-section-broken rule pairs _load_meta with _save_meta
        # through the loaded value and fires on any await between
        # them, and the callers below carry explicit
        # `atomic-section` annotations the same rule verifies.
        # The tmp name is per-writer-unique: the sitter AND the
        # snapshotter both save this dataset's meta, and a SHARED tmp
        # path lets one writer truncate the file another is about to
        # rename into place — installing torn meta (the storm suite
        # caught exactly that once the fsync widened the window)
        p = self._meta_path(dataset)
        tmp = p.with_name("%s.tmp-%d-%d"
                          % (p.name, os.getpid(),
                             threading.get_ident()))
        with open(tmp, "w") as f:
            f.write(json.dumps(meta, indent=2))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, p)
        try:
            fd = os.open(p.parent, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        except OSError:
            pass

    def _exists_sync(self, dataset: str) -> bool:
        return self._meta_path(dataset).exists()

    def _mountlink(self, dataset: str) -> Path | None:
        meta = self._load_meta(dataset)
        return Path(meta["mountpoint"]) if meta.get("mountpoint") else None

    # ---- dataset lifecycle ----

    async def exists(self, dataset: str) -> bool:
        return self._exists_sync(dataset)

    async def create(self, dataset: str, *, mountpoint: str | None = None) -> None:
        if self._exists_sync(dataset):
            raise StorageError("dataset exists: %s" % dataset)
        if "/" in dataset and not self._exists_sync(dataset.rpartition("/")[0]):
            # zfs parity: the parent dataset must exist (a bare top-level
            # name plays the role of a pool root)
            raise StorageError("parent dataset does not exist: %s"
                               % dataset.rpartition("/")[0])
        p = self._dspath(dataset)
        if p.exists():
            # @meta.json is the existence marker (doctor's
            # dir-without-meta debris class): a create/recv cancelled
            # between the mkdirs and the meta save strands exactly
            # this shape, and destroy() cannot see it — without this
            # sweep every later create of the same dataset dies on
            # mkdir FileExistsError FOREVER (a restore-wedge the
            # overlapped takeover's tighter cancel timing exposed in
            # tier-1).  Only a CHILDLESS meta-less dir is debris; one
            # holding child datasets is load-bearing structure.
            children = [c.name for c in p.iterdir()
                        if c.name not in _RESERVED]
            if children:
                raise StorageError(
                    "dataset path %s exists without metadata and has "
                    "children %s" % (dataset, children))
            await asyncio.to_thread(shutil.rmtree, p)
        (p / "@data").mkdir(parents=True)
        (p / "@snapshots").mkdir()
        self._save_meta(dataset, {
            "mountpoint": mountpoint,
            "mounted": False,
            "props": {"canmount": "on"},
            "snaps": {},
        })

    async def destroy(self, dataset: str, *, recursive: bool = False) -> None:
        p = self._dspath(dataset)
        if not self._exists_sync(dataset):
            raise StorageError("dataset does not exist: %s" % dataset)
        children = [c.name for c in p.iterdir()
                    if c.is_dir() and c.name not in _RESERVED]
        if children and not recursive:
            raise StorageError("dataset %s has children %s (need recursive)"
                               % (dataset, children))
        for child in children:
            await self.destroy("%s/%s" % (dataset, child), recursive=True)
        if await self.is_mounted(dataset):
            await self.unmount(dataset)
        await asyncio.to_thread(shutil.rmtree, p)
        # prune now-empty parent plain dirs up to datasets/
        parent = p.parent
        base = self.root / "datasets"
        while parent != base and not any(parent.iterdir()) \
                and not (parent / "@meta.json").exists():
            parent.rmdir()
            parent = parent.parent

    async def rename(self, old: str, new: str) -> None:
        po, pn = self._dspath(old), self._dspath(new)
        if not self._exists_sync(old):
            raise StorageError("dataset does not exist: %s" % old)
        if pn.exists():
            raise StorageError("rename target exists: %s" % new)
        was_mounted = await self.is_mounted(old)
        pn.parent.mkdir(parents=True, exist_ok=True)
        await asyncio.to_thread(os.rename, po, pn)
        if was_mounted:
            # zfs keeps a renamed dataset mounted; re-point the symlink at
            # the moved @data so the mountpoint stays live
            mp = Path(self._load_meta(new)["mountpoint"])
            if mp.is_symlink():
                os.unlink(mp)
            os.symlink((pn / "@data").resolve(), mp)

    # ---- properties / mounting ----

    async def get_prop(self, dataset: str, prop: str) -> str | None:
        meta = self._load_meta(dataset)
        if prop == "mountpoint":
            return meta.get("mountpoint")
        if prop == "mounted":
            return "yes" if meta.get("mounted") else "no"
        return meta.get("props", {}).get(prop)

    async def set_prop(self, dataset: str, prop: str, value: str) -> None:
        # mnt-lint: atomic-section=set-prop
        meta = self._load_meta(dataset)
        if prop == "mountpoint":
            meta["mountpoint"] = value
        else:
            meta.setdefault("props", {})[prop] = value
        self._save_meta(dataset, meta)
        # mnt-lint: end-atomic-section

    async def inherit_prop(self, dataset: str, prop: str) -> None:
        # mnt-lint: atomic-section=inherit-prop
        meta = self._load_meta(dataset)
        meta.get("props", {}).pop(prop, None)
        self._save_meta(dataset, meta)
        # mnt-lint: end-atomic-section

    async def set_mountpoint(self, dataset: str, mountpoint: str) -> None:
        was_mounted = await self.is_mounted(dataset)
        if was_mounted:
            await self.unmount(dataset)
        await self.set_prop(dataset, "mountpoint", mountpoint)
        if was_mounted:
            await self.mount(dataset)

    async def get_mountpoint(self, dataset: str) -> str | None:
        return (await self.get_prop(dataset, "mountpoint"))

    async def mount(self, dataset: str) -> None:
        # mnt-lint: atomic-section=mount
        meta = self._load_meta(dataset)
        mp = meta.get("mountpoint")
        if not mp:
            raise StorageError("dataset %s has no mountpoint" % dataset)
        link = Path(mp)
        target = self._dspath(dataset) / "@data"
        if link.is_symlink():
            if os.path.realpath(link) == str(target.resolve()):
                meta["mounted"] = True
                self._save_meta(dataset, meta)
                return
            raise StorageError("mountpoint %s busy (-> %s)"
                               % (mp, os.path.realpath(link)))
        if link.exists():
            raise StorageError("mountpoint %s exists and is not a mount" % mp)
        link.parent.mkdir(parents=True, exist_ok=True)
        os.symlink(target.resolve(), link)
        meta["mounted"] = True
        self._save_meta(dataset, meta)
        # mnt-lint: end-atomic-section

    async def unmount(self, dataset: str) -> None:
        # mnt-lint: atomic-section=unmount
        meta = self._load_meta(dataset)
        mp = meta.get("mountpoint")
        if mp and Path(mp).is_symlink():
            # only unlink if the mountpoint is OUR mount — another dataset
            # may own that path now
            ours = str((self._dspath(dataset) / "@data").resolve())
            if os.path.realpath(mp) == ours:
                os.unlink(mp)
        meta["mounted"] = False
        self._save_meta(dataset, meta)
        # mnt-lint: end-atomic-section

    async def is_mounted(self, dataset: str) -> bool:
        # ground truth = the symlink, not the meta flag (mnttab-verify
        # parity, lib/zfsClient.js:251-437)
        meta = self._load_meta(dataset)
        mp = meta.get("mountpoint")
        if not mp or not Path(mp).is_symlink():
            return False
        return os.path.realpath(mp) == str((self._dspath(dataset) / "@data").resolve())

    # ---- snapshots ----

    def _manifest_path(self, dataset: str, name: str) -> Path:
        return self._dspath(dataset) / "@manifests" / ("%s.json" % name)

    def _write_manifest(self, dataset: str, name: str,
                        files: dict) -> None:
        """Atomic install (tmp + rename): a torn manifest would read
        as unparseable and be lazily recomputed, but never as a
        half-truth the delta plane could ship."""
        p = self._manifest_path(dataset, name)
        p.parent.mkdir(exist_ok=True)
        tmp = p.with_name("%s.tmp-%d-%d"
                          % (p.name, os.getpid(),
                             threading.get_ident()))
        tmp.write_text(json.dumps({"snapshot": name, "files": files},
                                  separators=(",", ":")))
        os.replace(tmp, p)

    async def snapshot_manifest(self, dataset: str, name: str) -> dict:
        """The per-snapshot content manifest (path -> size/mtime/hash),
        written at snapshot time and BACKFILLED LAZILY here for
        snapshots that predate the manifest plane (or whose manifest
        was torn by a crash): snapshot directories are immutable after
        creation, so a recompute from the directory is always ground
        truth."""
        snapdir = self._dspath(dataset) / "@snapshots" / name
        if not snapdir.is_dir():
            raise StorageError("no such snapshot: %s@%s"
                               % (dataset, name))
        p = self._manifest_path(dataset, name)
        try:
            man = json.loads(await asyncio.to_thread(p.read_text))
            files = man["files"]
            if not isinstance(files, dict):
                raise ValueError("files is not an object")
            return files
        except FileNotFoundError:
            pass
        except (ValueError, KeyError, OSError):
            pass          # unreadable/torn: recompute from the dir
        def scan_and_install():
            files = manifest_scan(snapdir)
            self._write_manifest(dataset, name, files)
            return files

        return await asyncio.to_thread(scan_and_install)

    async def snapshot(self, dataset: str, name: str | None = None) -> Snapshot:
        # error:StorageError models a failed disk write at snapshot
        # time (callers like _snapshot_safe must tolerate it)
        await faults.point("storage.snapshot")
        name = name or snapshot_name_now()
        meta = self._load_meta(dataset)
        if name in meta["snaps"]:
            raise StorageError("snapshot exists: %s@%s" % (dataset, name))
        src = self._dspath(dataset) / "@data"
        dst = self._dspath(dataset) / "@snapshots" / name

        def copy_and_scan():
            # manifest written at snapshot time, describing the
            # SNAPSHOT dir (not @data, which keeps changing under a
            # live database): exactly what a delta sender will ship
            # from.  Content is hashed DURING the copy — one read per
            # file, not a second full pass, since the transition
            # snapshot sits near the failover path.
            hashes: dict[str, str] = {}

            def copy_fn(s: str, d: str) -> None:
                h = hashlib.sha256()
                with open(s, "rb") as fi, open(d, "wb") as fo:
                    for chunk in iter(lambda: fi.read(1 << 20), b""):
                        h.update(chunk)
                        fo.write(chunk)
                shutil.copystat(s, d)       # copy2 parity (mtime)
                hashes[str(Path(d))] = h.hexdigest()

            shutil.copytree(src, dst, symlinks=True,
                            copy_function=copy_fn)
            files = manifest_scan(dst, with_hash=False)
            for rel, ent in files.items():
                if ent.get("t") == "f":
                    ent["h"] = hashes.get(str(dst / rel)) \
                        or _sha256_file(dst / rel)
            self._write_manifest(dataset, name, files)
            return files

        await asyncio.to_thread(copy_and_scan)
        now = time.time()
        # mnt-lint: atomic-section=snapshot-record
        # RE-load: the copy ran in a worker thread while the loop kept
        # serving, so a concurrent load-modify-save (set_prop, mount,
        # another snapshot) may have installed fresh meta — saving the
        # copy we loaded before the await would silently reinstate the
        # stale value (exactly the torn-meta class mnt-lint's
        # atomic-section-broken rule exists to catch; it flagged this
        # site on its first tree-wide run)
        meta = self._load_meta(dataset)
        if name in meta["snaps"]:
            raise StorageError("snapshot exists: %s@%s" % (dataset, name))
        meta["snaps"][name] = now
        self._save_meta(dataset, meta)
        # mnt-lint: end-atomic-section
        return Snapshot(dataset, name, now)

    async def list_snapshots(self, dataset: str) -> list[Snapshot]:
        meta = self._load_meta(dataset)
        snaps = [Snapshot(dataset, n, t) for n, t in meta["snaps"].items()]
        snaps.sort(key=lambda s: (s.creation, s.name))
        return snaps

    async def destroy_snapshot(self, dataset: str, name: str) -> None:
        """Idempotent: the snapshotter's GC and a sitter's restore run
        in SEPARATE processes, so the dataset (or just this snapshot)
        can vanish between any two steps here — absence, however it
        came about, means the deletion's goal is achieved (the
        extended-storm race: a rebuild isolates/replaces the dataset
        mid-GC-pass, and raising here fed the stuck-snapshot alarm
        spuriously)."""
        try:
            meta = self._load_meta(dataset)
        except StorageError:
            return               # dataset replaced/renamed away
        if name not in meta["snaps"]:
            return               # another pass (or a restore) got it
        try:
            await asyncio.to_thread(
                shutil.rmtree,
                self._dspath(dataset) / "@snapshots" / name)
        except FileNotFoundError:
            pass
        except OSError as e:
            raise StorageError("cannot destroy snapshot %s@%s: %s"
                               % (dataset, name, e)) from None
        try:
            # the manifest follows its snapshot out (doctor would
            # otherwise report it as an orphan)
            self._manifest_path(dataset, name).unlink()
        except OSError:
            pass
        try:
            meta = self._load_meta(dataset)
        except StorageError:
            return
        meta["snaps"].pop(name, None)
        self._save_meta(dataset, meta)

    # ---- bulk streams ----
    #
    # Frame: one JSON header line {"snapshot": ..., "size": ...}\n followed
    # by a tar stream of the snapshot content (role of `zfs send`,
    # lib/backupSender.js:172-180).

    async def estimate_send_size(self, dataset: str, name: str) -> int | None:
        src = self._dspath(dataset) / "@snapshots" / name
        if not src.exists():
            raise StorageError("no such snapshot: %s@%s" % (dataset, name))

        def du(p: Path) -> int:
            total = 0
            for f in p.rglob("*"):
                if f.is_file() and not f.is_symlink():
                    total += f.stat().st_size
            return total

        return await asyncio.to_thread(du, src)

    async def send(
        self,
        dataset: str,
        name: str,
        writer: asyncio.StreamWriter,
        progress_cb: ProgressCb | None = None,
        compress: str | None = None,
        stream_id: str | None = None,
        from_snapshot: str | None = None,
    ) -> None:
        src = self._dspath(dataset) / "@snapshots" / name
        if not src.exists():
            raise StorageError("no such snapshot: %s@%s" % (dataset, name))
        if from_snapshot:
            await self._send_delta(dataset, name, from_snapshot, src,
                                   writer, progress_cb, compress,
                                   stream_id)
            return
        await faults.point("storage.send")
        size = await self.estimate_send_size(dataset, name)
        hdr = {"snapshot": name, "size": size}
        if compress:
            # named in the per-stream header so the receiver keys its
            # decompressor off the wire, not off config agreement
            hdr["compression"] = compress
        if stream_id:
            hdr["stream"] = stream_id
        header = json.dumps(hdr) + "\n"
        try:
            writer.write(header.encode())
            await writer.drain()
        except Exception as e:
            raise StorageError("send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e
        from manatee_tpu import native
        # the native splice pump moves the child's raw stdout in the
        # kernel — compression needs the bytes in userspace, so a
        # negotiated codec takes the python pipeline instead
        if not compress and native.enabled() \
                and writer.get_extra_info("socket") is not None:
            await self._send_native(dataset, name, src, size, writer,
                                    progress_cb)
            return
        proc = await asyncio.create_subprocess_exec(
            "tar", "-C", str(src), "-cf", "-", ".",
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE,
        )
        # drain stderr CONCURRENTLY: a tar emitting more warnings than
        # the pipe buffer would block on stderr and stall stdout short
        # of EOF, deadlocking the copy pipeline below
        t_err = asyncio.create_task(proc.stderr.read())
        try:
            with wirestream.recorded_stage("send", dataset,
                                           compress) as st:
                st.raw, st.wire = await wirestream.pipeline_copy(
                    proc.stdout.read, writer, codec=compress,
                    progress=(lambda d: progress_cb(d, size))
                    if progress_cb else None)
        except asyncio.CancelledError:
            # our caller was cancelled (server shutdown, peer-handler
            # teardown): same cleanup, then let the cancel propagate —
            # `except Exception` alone would leak the drainer task and
            # leave tar blocked on its full stdout pipe forever
            await drain_and_reap(proc, t_err)
            raise
        except Exception as e:
            # receiver went away mid-stream: kill tar first, or reading its
            # stderr to EOF below would block on the full stdout pipe
            await drain_and_reap(proc, t_err)
            raise StorageError("send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e
        try:
            err = await t_err
            rc = await proc.wait()
        except asyncio.CancelledError:
            # cancellation landing on the post-stream awaits must
            # still reap the child
            await drain_and_reap(proc, t_err)
            raise
        if rc != 0:
            raise StorageError("tar send failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))

    async def _send_native(self, dataset: str, name: str, src,
                           size: int | None,
                           writer: asyncio.StreamWriter,
                           progress_cb: ProgressCb | None) -> None:
        """MANATEE_NATIVE=1 bulk path: tar's stdout is spliced into the
        peer socket by the native pump — fd-lifetime/cancellation
        protocol shared with ZfsBackend in
        storage.base.pump_child_to_socket."""
        def on_progress(total: int) -> None:
            if progress_cb:
                progress_cb(total, size)

        proc, t_err = await pump_child_to_socket(
            ["tar", "-C", str(src), "-cf", "-", "."], writer,
            on_progress=on_progress,
            label="native send of %s@%s" % (dataset, name))
        try:
            err = await t_err
            rc = await proc.wait()
        except asyncio.CancelledError:
            # the pump finished but a cancel cut the tail awaits: the
            # child must still be reaped (zfs sibling reaps in exactly
            # this window)
            await drain_and_reap(proc, t_err)
            raise
        except Exception as e:
            await drain_and_reap(proc, t_err)
            raise StorageError("native send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e
        if rc != 0:
            raise StorageError("tar send failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))

    async def _send_delta(self, dataset: str, name: str, base: str,
                          src: Path, writer: asyncio.StreamWriter,
                          progress_cb: ProgressCb | None,
                          compress: str | None,
                          stream_id: str | None) -> None:
        """Incremental send: header + compressed detail blob (deletion
        list, changed list, full target manifest) + a tar of only the
        changed/added paths.  The manifests are the diff's ground
        truth; both are loaded (lazily backfilled) from this dataset's
        manifest store.  Small by construction, so the delta always
        takes the python pipeline — the native splice pump's win is
        full-dataset streams."""
        await faults.point("storage.delta.send")
        if not (self._dspath(dataset) / "@snapshots" / base).is_dir():
            raise StorageError("delta base does not exist: %s@%s"
                               % (dataset, base))
        base_files = await self.snapshot_manifest(dataset, base)
        tgt_files = await self.snapshot_manifest(dataset, name)
        changed, deleted = manifest_delta(base_files, tgt_files)
        for p in changed:
            if "\n" in p:
                # tar -T is line-framed; a newline in a path cannot be
                # shipped safely (pg never creates one)
                raise StorageError("cannot delta-send path with "
                                   "newline: %r" % p)
        size = sum(e.get("size", 0) for p in changed
                   for e in (tgt_files[p],) if e.get("t") == "f")
        detail = {"changed": changed, "deleted": deleted,
                  "manifest": tgt_files}
        blob = zlib.compress(
            json.dumps(detail, separators=(",", ":")).encode())
        hdr = {"snapshot": name, "base": base, "size": size,
               "deltaLen": len(blob)}
        if compress:
            hdr["compression"] = compress
        if stream_id:
            hdr["stream"] = stream_id
        try:
            writer.write(json.dumps(hdr).encode() + b"\n" + blob)
            await writer.drain()
        except Exception as e:
            raise StorageError("delta send of %s@%s aborted: %s"
                               % (dataset, name, e)) from e

        with tempfile.NamedTemporaryFile("w", prefix="mnt-delta-",
                                         suffix=".list") as lf:
            # dirs sort before their contents, so tar creates them
            # first; --no-recursion keeps a changed dir entry from
            # re-shipping its unchanged contents
            for p in changed:
                lf.write("./%s\n" % p)
            lf.flush()
            proc = await asyncio.create_subprocess_exec(
                "tar", "-C", str(src), "--no-recursion",
                "--verbatim-files-from", "-T", lf.name, "-cf", "-",
                stdout=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
            t_err = asyncio.create_task(proc.stderr.read())
            try:
                with wirestream.recorded_stage(
                        "send", dataset, compress,
                        basis="incremental") as st:
                    st.raw, st.wire = await wirestream.pipeline_copy(
                        proc.stdout.read, writer, codec=compress,
                        progress=(lambda d: progress_cb(d, size))
                        if progress_cb else None)
                    # the detail blob is wire traffic too: without it
                    # the bench's incremental-vs-full ratio would not
                    # charge the manifest's cost
                    st.raw += len(blob)
                    st.wire += len(blob)
            except asyncio.CancelledError:
                await drain_and_reap(proc, t_err)
                raise
            except Exception as e:
                await drain_and_reap(proc, t_err)
                raise StorageError("delta send of %s@%s aborted: %s"
                                   % (dataset, name, e)) from e
            try:
                err = await t_err
                rc = await proc.wait()
            except asyncio.CancelledError:
                await drain_and_reap(proc, t_err)
                raise
        if rc != 0:
            raise StorageError("tar delta send failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))

    async def recv(
        self,
        dataset: str,
        reader: asyncio.StreamReader,
        progress_cb: ProgressCb | None = None,
        expect_stream_id: str | None = None,
    ) -> None:
        await faults.point("storage.recv")
        hdr_line = await reader.readline()
        if not hdr_line:
            raise StorageError("empty recv stream")
        try:
            hdr = json.loads(hdr_line)
            snapname = hdr["snapshot"]
            size = hdr.get("size")
        except (json.JSONDecodeError, KeyError, TypeError):
            raise StorageError("bad recv stream header: %r" % hdr_line) from None
        # stream identity, BEFORE any dataset mutation: a cancelled
        # restore's job can dial back into the port its successor
        # rebound, and receiving the stale stream would race (and
        # corrupt) the fresh attempt's dataset.  A header without a
        # stream id (an old sender) cannot be verified and passes.
        wirestream.check_stream_id(hdr, expect_stream_id)
        # the snapshot name came off the wire: refuse anything that is not
        # a single safe path component
        if (not isinstance(snapname, str) or not snapname
                or "/" in snapname or "\\" in snapname
                or snapname in (".", "..") or snapname in _RESERVED):
            raise StorageError("bad snapshot name in stream: %r" % (snapname,))
        # compression is whatever the SENDER named in the header (it
        # only ever names a codec we offered); an absent key — an old
        # sender — is raw
        codec = hdr.get("compression")
        feed = wirestream.make_feed(reader, codec)

        if self._exists_sync(dataset):
            raise StorageError(
                "recv target exists: %s (isolate or destroy it first)" % dataset)
        await self.create(dataset)
        data = self._dspath(dataset) / "@data"

        try:
            proc = await asyncio.create_subprocess_exec(
                "tar", "-C", str(data), "-xf", "-",
                stdin=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
        except BaseException:
            # a cancel landing on the spawn (a topology change
            # cancelling the restore in its first milliseconds) must
            # not strand the just-created dataset: it would shadow
            # every later attempt with 'recv target exists'
            await self._destroy_quietly(dataset)
            raise
        # drain stderr CONCURRENTLY with the feed: a tar emitting more
        # warnings than the pipe buffer ('implausibly old time stamp',
        # unknown extended headers) would block on stderr, stop
        # reading stdin, and wedge the drain() below forever
        t_err = asyncio.create_task(proc.stderr.read())
        seen = {"raw": 0}

        def _prog(d: int) -> None:
            seen["raw"] = d          # raw (post-inflate) bytes fed to tar
            if progress_cb:
                progress_cb(d, size)

        try:
            with wirestream.recorded_stage("recv", dataset,
                                           codec) as st:
                err, rc = await pump_socket_to_child(
                    proc, feed, t_err, on_progress=_prog,
                    label="recv into %s" % dataset)
                st.raw = seen["raw"]
                st.wire = feed.wire_bytes if codec else st.raw
        except BaseException:
            # restore aborted (cancel, dead stream, anything): the
            # helper already reaped the child; remove the partial
            # dataset — leaving it would fail the NEXT restore attempt
            # with 'recv target exists' until an operator intervenes
            await self._destroy_quietly(dataset)
            raise
        if rc != 0:
            await self._destroy_quietly(dataset)
            raise StorageError("tar recv failed (rc=%d): %s"
                               % (rc, err.decode("utf-8", "replace")))
        try:
            # preserve the received snapshot on the receiver, like
            # zfs recv
            snapdir = self._dspath(dataset) / "@snapshots" / snapname
            await asyncio.to_thread(shutil.copytree, data, snapdir,
                                    symlinks=True)
            meta = self._load_meta(dataset)
            meta["snaps"][snapname] = time.time()
            meta["mounted"] = False  # zfs recv -u: received unmounted
            self._save_meta(dataset, meta)
        except BaseException:
            # ANY failure past this point — cancel, ENOSPC, perms —
            # strands a half-recorded dataset that blocks every later
            # restore with 'recv target exists': remove it like any
            # other aborted restore
            await self._destroy_quietly(dataset)
            raise

    # ---- incremental rebuild (delta) ----

    delta_in_place = False

    def supports_delta(self) -> bool:
        return True

    async def list_children(self, dataset: str) -> list[str]:
        p = self._dspath(dataset)
        if not self._exists_sync(dataset):
            return []
        return sorted("%s/%s" % (dataset, c.name) for c in p.iterdir()
                      if c.is_dir() and c.name not in _RESERVED
                      and (c / "@meta.json").exists())

    async def delta_candidates(
            self, dataset: str,
            fallback: str | None = None) -> tuple[list[str], str | None]:
        for src in (dataset, fallback):
            if not src or not self._exists_sync(src):
                continue
            names = [s.name for s in await self.list_snapshots(src)
                     if is_epoch_ms_snapshot(s.name)]
            if names:
                return names, src
        return [], None

    async def sweep_delta_debris(self, dataset: str) -> bool:
        """A dataset whose meta still carries the ``applying`` marker
        is a delta apply that died between create and the verified
        install: destroy it.  The caller treats a sweep as doubt and
        forces this attempt FULL — the crash proved nothing about why
        the apply died."""
        if not self._exists_sync(dataset):
            return False
        try:
            meta = self._load_meta(dataset)
        except StorageError:
            return False
        if not meta.get("applying"):
            return False
        await self.destroy(dataset, recursive=True)
        return True

    async def recv_delta(
        self,
        dataset: str,
        reader: asyncio.StreamReader,
        *,
        base: str,
        base_src: str | None = None,
        progress_cb: ProgressCb | None = None,
        expect_stream_id: str | None = None,
    ) -> None:
        """Apply an incremental stream: clone the local copy of *base*
        (held by *base_src* — typically the isolated predecessor
        dataset) into a fresh dataset, extract the changed files,
        apply the deletions, and VERIFY the result against the
        stream's target manifest before anything is recorded.  Any
        mismatch — a divergent base, torn transfer, anything —
        destroys the partial and raises; the restore client then
        retries full.  Divergence can cost a re-transfer, never a
        wrong dataset."""
        hdr_line = await reader.readline()
        if not hdr_line:
            raise StorageError("empty delta recv stream")
        try:
            hdr = json.loads(hdr_line)
            snapname = hdr["snapshot"]
            size = hdr.get("size")
            dlen = int(hdr["deltaLen"])
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            raise StorageError("bad delta stream header: %r"
                               % hdr_line) from None
        wirestream.check_stream_id(hdr, expect_stream_id)
        if hdr.get("base") != base:
            # a full stream, or a delta against some other base: either
            # way NOT what was negotiated — refuse before any mutation
            raise StorageError(
                "delta stream names base %r, expected %r"
                % (hdr.get("base"), base))
        if (not isinstance(snapname, str) or not snapname
                or "/" in snapname or "\\" in snapname
                or snapname in (".", "..") or snapname in _RESERVED):
            raise StorageError("bad snapshot name in stream: %r"
                               % (snapname,))
        if not 0 <= dlen <= MAX_DELTA_DETAIL:
            raise StorageError("implausible delta detail length %d"
                               % dlen)
        try:
            # the blob rides the wire right behind the header; a
            # sender that stalls inside it is a dead transfer, not a
            # slow one
            blob = await asyncio.wait_for(reader.readexactly(dlen),
                                          600)
            # the cap must bound the DECOMPRESSED size too: zlib
            # expands up to ~1000:1, and a small wire blob of
            # compressed zeros would otherwise allocate gigabytes
            # before any validation ran
            d = zlib.decompressobj()
            raw = d.decompress(blob, MAX_DELTA_DETAIL)
            if d.unconsumed_tail:
                raise StorageError(
                    "delta detail blob inflates past the %d-byte cap"
                    % MAX_DELTA_DETAIL)
            detail = json.loads(raw + d.flush())
            deleted = [_check_wire_relpath(p)
                       for p in detail["deleted"]]
            changed = [_check_wire_relpath(p)
                       for p in detail["changed"]]
            manifest = detail["manifest"]
            if not isinstance(manifest, dict):
                raise StorageError("delta manifest is not an object")
            for p in manifest:
                _check_wire_relpath(p)
        except StorageError:
            raise
        except (asyncio.IncompleteReadError, ValueError, KeyError,
                TypeError, zlib.error) as e:
            raise StorageError("bad delta detail blob: %s" % e) \
                from None
        codec = hdr.get("compression")
        feed = wirestream.make_feed(reader, codec)

        base_src = base_src or dataset
        srcdir = self._dspath(base_src) / "@snapshots" / base
        if not srcdir.is_dir():
            raise StorageError("no local copy of delta base %s@%s"
                               % (base_src, base))
        if self._exists_sync(dataset):
            raise StorageError(
                "recv target exists: %s (isolate or destroy it first)"
                % dataset)
        await self.create(dataset)
        try:
            # the applying marker makes a half-applied dataset
            # self-describing debris: sweep_delta_debris destroys it
            # and the next restore attempt goes full
            meta = self._load_meta(dataset)
            meta["applying"] = hdr.get("stream") or snapname
            self._save_meta(dataset, meta)
            # error:StorageError models an apply that dies after the
            # dataset materialized; crash here is the half-applied
            # debris the sweep scenario proves is swept + retried full
            await faults.point("storage.delta.apply")
            data = self._dspath(dataset) / "@data"
            await asyncio.to_thread(shutil.copytree, srcdir, data,
                                    symlinks=True, dirs_exist_ok=True)
            # paths whose TYPE flipped (file->dir, dir->symlink, ...)
            # must be cleared before extraction: tar will not replace
            # a directory with a file
            await asyncio.to_thread(
                self._clear_type_flips, data, changed, manifest)
            proc = await asyncio.create_subprocess_exec(
                "tar", "-C", str(data), "-xf", "-",
                stdin=asyncio.subprocess.PIPE,
                stderr=asyncio.subprocess.PIPE,
            )
            t_err = asyncio.create_task(proc.stderr.read())
            seen = {"raw": 0}

            def _prog(d: int) -> None:
                seen["raw"] = d
                if progress_cb:
                    progress_cb(d, size)

            with wirestream.recorded_stage("recv", dataset, codec,
                                           basis="incremental") as st:
                err, rc = await pump_socket_to_child(
                    proc, feed, t_err, on_progress=_prog,
                    label="delta recv into %s" % dataset)
                st.raw = seen["raw"] + len(blob)
                st.wire = (feed.wire_bytes if codec else seen["raw"]) \
                    + len(blob)
            if rc != 0:
                raise StorageError(
                    "tar delta recv failed (rc=%d): %s"
                    % (rc, err.decode("utf-8", "replace")))
            await asyncio.to_thread(self._apply_deletions, data,
                                    deleted, manifest)
            got = await asyncio.to_thread(manifest_scan, data)
            bad = manifest_diff_paths(got, manifest)
            if bad:
                raise StorageError(
                    "delta apply DIVERGED from the sender's target "
                    "manifest at %d path(s) (first: %s) — base %r is "
                    "not the sender's base; retry full"
                    % (len(bad), ", ".join(bad[:5]), base))
            # success: preserve the received snapshot + its manifest,
            # exactly like a full recv preserves the streamed snapshot
            snapdir = self._dspath(dataset) / "@snapshots" / snapname
            await asyncio.to_thread(shutil.copytree, data, snapdir,
                                    symlinks=True)
            await asyncio.to_thread(self._write_manifest, dataset,
                                    snapname, manifest)
            meta = self._load_meta(dataset)
            meta["snaps"][snapname] = time.time()
            meta["mounted"] = False
            meta.pop("applying", None)
            self._save_meta(dataset, meta)
        except BaseException:
            # any abort — divergence, dead stream, cancel, fault —
            # removes the partial; the base content is untouched in
            # base_src, so nothing is lost but the transfer
            await self._destroy_quietly(dataset)
            raise

    @staticmethod
    def _clear_type_flips(data: Path, changed: list[str],
                          manifest: dict) -> None:
        for p in changed:
            tgt = data / p
            ent = manifest.get(p)
            if ent is None or not (tgt.is_symlink() or tgt.exists()):
                continue
            on_disk = ("l" if tgt.is_symlink()
                       else "d" if tgt.is_dir() else "f")
            if on_disk != ent.get("t") or on_disk in ("l",):
                if tgt.is_dir() and not tgt.is_symlink():
                    shutil.rmtree(tgt)
                else:
                    tgt.unlink()

    @staticmethod
    def _apply_deletions(data: Path, deleted: list[str],
                         manifest: dict) -> None:
        # deepest-first so directories empty before their own removal;
        # a path already absent is fine (the delta describes the
        # target state, and absent IS that state).
        #
        # A deleted path whose ANCESTOR the delta replaced with a
        # non-directory is moot — the old descendant went with the old
        # ancestor — and must be SKIPPED, not resolved: if the new
        # ancestor is a symlink (a pg_tblspc-style link), resolving
        # the old path through it would delete files OUTSIDE the
        # dataset.  (Deletions under symlinks cannot arise any other
        # way: manifest_scan never descends into them, so only a type
        # flip puts a symlink above a base-manifest path.)
        def ancestor_replaced(p: str) -> bool:
            parts = p.split("/")
            for i in range(1, len(parts)):
                ent = manifest.get("/".join(parts[:i]))
                if isinstance(ent, dict) and ent.get("t") != "d":
                    return True
            return False

        for p in sorted(deleted, reverse=True):
            if ancestor_replaced(p):
                continue
            tgt = data / p
            try:
                if tgt.is_dir() and not tgt.is_symlink():
                    shutil.rmtree(tgt)
                else:
                    tgt.unlink()
            except FileNotFoundError:
                pass
            except NotADirectoryError:
                # some component is (already) a non-directory: the old
                # path cannot exist under it — equally moot
                pass

    async def _destroy_quietly(self, dataset: str) -> None:
        """Abort-path cleanup: the dataset vanishing concurrently (a
        rebuild isolating/renaming it — the cross-process race the
        storm tier documents) means the removal's goal is achieved; a
        raise here would MASK the original abort cause."""
        try:
            await self.destroy(dataset, recursive=True)
        except (StorageError, OSError):
            # OSError: destroy's rmtree/iterdir hit the vanish mid-way.
            # StorageError can also mean a META-LESS partial (this very
            # abort landed inside create(), before the meta save):
            # destroy() cannot see it, so clear the debris directly —
            # leaving it would fail every later recv with
            # 'File exists' until an operator intervened.
            try:
                p = self._dspath(dataset)
            except StorageError:
                return
            if p.exists() and not (p / "@meta.json").exists():
                try:
                    await asyncio.to_thread(shutil.rmtree, p)
                except OSError:
                    pass
