"""PostgresMgr — owns the database child process and its configuration.

Reference parity map (lib/postgresMgr.js):

- role reconfiguration contract {role: primary|sync|async|none, upstream,
  downstream} (:758-845);
- primary transition: prepare database (mount/create dataset, initdb if
  empty) → drop recovery config → force read-only → restart → storage
  snapshot → background wait-for-standby-catchup → enable writes + SIGHUP
  (_primary :1115-1184, _waitForStandby :1037-1105);
- standby-only change on a running primary = conf rewrite + SIGHUP
  (_updateStandby :1195-1260);
- standby transition: stop → mount dataset → rewrite upstream conf →
  restart, falling back to a FULL restore from the upstream's backupUrl
  on any failure (_standby :1282-1460);
- stop = SIGINT → SIGQUIT → SIGKILL escalation, never a clean shutdown
  (_stop :1484-1541; docs/xlog-diverge.md:12-15 explains why);
- health check every healthChkInterval with timeout → unhealthy
  (:1550-1646);
- serialized queries to our own database (:1989-2172);
- replication catch-up: downstream's flush must reach sent, with
  replicationTimeout bounding NO-PROGRESS intervals (_checkRepl
  :2390-2555);
- cancelable in-flight transitions (:379-385, 1123-1131) — a restore can
  take hours and must be interruptible by the next topology change.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import signal
import time
from pathlib import Path
from typing import Awaitable, Callable

from manatee_tpu import faults
from manatee_tpu.health.telemetry import STATUS_EVERY
from manatee_tpu.obs import get_journal, get_registry, record_span, span
from manatee_tpu.pg.engine import Engine, PgError, parse_pg_url
from manatee_tpu.state.types import INITIAL_WAL
from manatee_tpu.storage.base import StorageBackend, StorageError
from manatee_tpu.utils.aio import cancel_requests

log = logging.getLogger("manatee.pg")

_REG = get_registry()
_RECONF_DUR = _REG.histogram(
    "pg_reconfigure_duration_seconds",
    "role reconfiguration latency (restore time included)", ("role",))
_PROBE_DUR = _REG.histogram(
    "pg_probe_duration_seconds", "health-probe round-trip latency")
_PROBE_FLIPS = _REG.counter(
    "pg_probe_flips_total", "health verdict flips", ("to",))
_RESTORES = _REG.counter(
    "pg_restores_total", "full restores from an upstream backup server",
    ("result",))
# the exposition surface of health/telemetry.py: raw (un-normalized)
# replay lag and the failure-prediction score, per peer, on /metrics —
# the gauges the router and the prober's lag feed read
_REPL_LAG = _REG.gauge(
    "replication_lag_seconds",
    "standby replay lag from the last status observation", ("peer",))
_HEALTH_SCORE = _REG.gauge(
    "health_score",
    "failure-prediction score from the telemetry window (0..1)",
    ("peer",))


class NeedsRestoreError(PgError):
    """The local database cannot serve this role; a restore from the
    upstream's backup server is required."""


DEFAULTS = {
    "opsTimeout": 60.0,
    "healthChkInterval": 1.0,
    "healthChkTimeout": 5.0,
    "replicationTimeout": 60.0,
    # catch-up poll cadence; the reference hardwires 1 s
    # (lib/postgresMgr.js:2429) — configurable here so failover time is
    # not floored by the poll
    "replPollInterval": 1.0,
    # bound on the in-place promotion call (pg_promote wait): far above
    # the sub-second healthy case, far below opsTimeout — a wedged
    # server must fail over to the restart path in seconds, not stall
    # the takeover.  Config-tunable (etc/sitter.json promoteWait) like
    # every comparable knob; a slow-disk host that needs longer should
    # not pay an unnecessary restart (VERDICT r4 weak #5)
    "promoteWait": 5.0,
    "singleton": False,
}

# telemetry-status collection cadence, in health ticks: liveness probes
# every tick stay single-query cheap; the (possibly multi-query) status
# op for lag/WAL features runs on every Nth tick.  The canonical value
# lives in health/telemetry.py (training data and the deployed-path
# eval are masked to the same cadence).
_STATUS_EVERY = STATUS_EVERY


class PostgresMgr:
    def __init__(self, *, engine: Engine, storage: StorageBackend,
                 config: dict,
                 restore_fn: Callable[[dict], Awaitable[None]] | None = None):
        """*config*: peer_id, host, port, datadir, dataset, plus the
        DEFAULTS knobs (etc/sitter.json parity).  *restore_fn(upstream)*
        performs the bulk restore (wired to the backup client)."""
        self.engine = engine
        self.storage = storage
        self.cfg = dict(DEFAULTS)
        self.cfg.update(config)
        self.restore_fn = restore_fn

        self.peer_id = self.cfg["peer_id"]
        self.host = self.cfg.get("host", "127.0.0.1")
        self.port = int(self.cfg["port"])
        self.datadir = str(self.cfg["datadir"])
        self.dataset = self.cfg.get("dataset")

        self._proc: asyncio.subprocess.Process | None = None
        self._applied: dict | None = None   # last successful role config
        # signature of the last server config actually written to the
        # datadir: identical regenerations are skipped (no write, no
        # SIGHUP) — a no-op reconfigure re-drive must not cost a
        # config-reload cycle on the takeover path.  None = unknown
        # (datadir replaced by initdb/restore/mount: must rewrite).
        self._conf_sig: tuple | None = None
        self._online = False
        self._health_task: asyncio.Task | None = None
        self._catchup_task: asyncio.Task | None = None
        self._repoint_task: asyncio.Task | None = None
        self._exit_watch: asyncio.Task | None = None
        self._reconf_lock = asyncio.Lock()
        self._query_lock = asyncio.Lock()   # serialized local queries
        self._last_xlog = INITIAL_WAL
        self._listeners: dict[str, list[Callable]] = {}
        self._closed = False
        self._log_fh = None

        from manatee_tpu.health.telemetry import (
            FAILED_PROBE_LATENCY_MS,
            NumpyScorer,
            TelemetryRing,
        )
        self._failed_probe_latency_ms = FAILED_PROBE_LATENCY_MS
        self.telemetry = TelemetryRing()
        self._scorer = NumpyScorer(self.cfg.get("healthModelWeights"))
        self.health_score: float | None = None
        # recorded-trace capture (closes the predictor's sim-to-real
        # loop): when telemetryDump names a file, every probe tick's RAW
        # features land there as JSONL, so real chaos/integration runs
        # produce evaluation/training data for health.train
        self._telemetry_dump = self.cfg.get("telemetryDump")
        self._dump_fh = None

    # ---- events ----

    def on(self, event: str, cb: Callable) -> None:
        self._listeners.setdefault(event, []).append(cb)

    def _emit(self, event: str, payload=None) -> None:
        for cb in self._listeners.get(event, []):
            try:
                cb(payload)
            except Exception:
                log.exception("pg listener for %s failed", event)

    # ---- lifecycle ----

    async def start_manager(self) -> None:
        """Initial probe + health loop; emits 'init' {setup, online}
        (lib/postgresMgr.js:401-421)."""
        setup = self.engine.is_initialized(self.datadir)
        self._health_task = asyncio.create_task(self._health_loop())
        self._emit("init", {"setup": setup, "online": False})

    async def close(self) -> None:
        """Crash-only shutdown: the child is shot in the head, never a
        clean postgres shutdown (lib/shard.js:78-93)."""
        self._closed = True
        try:
            await self._cancel_catchup()
            await self._cancel_repoint()
            for t in (self._health_task, self._exit_watch):
                if t:
                    t.cancel()
            # reap: their finallys complete before the process goes away
            await asyncio.gather(
                *(t for t in (self._health_task, self._exit_watch) if t),
                return_exceptions=True)
        finally:
            # crash-only contract: the child is shot even if close()
            # itself is cancelled mid-reap (the kill() in _kill_proc is
            # synchronous, so it lands before any further await)
            await self._kill_proc()
            # pooled psql coprocesses die with the manager
            with contextlib.suppress(Exception):
                await self.engine.aclose()
            if self._log_fh:
                self._log_fh.close()
            if self._dump_fh:
                self._dump_fh.close()

    @property
    def online(self) -> bool:
        return self._online

    @property
    def running(self) -> bool:
        return self._proc is not None and self._proc.returncode is None

    def status(self) -> dict:
        return {
            "peer_id": self.peer_id,
            "online": self._online,
            "running": self.running,
            "pid": self._proc.pid if self.running else None,
            "setup": self.engine.is_initialized(self.datadir),
            "role": (self._applied or {}).get("role"),
            "lastXlog": self._last_xlog,
            "healthScore": self.health_score,
            "healthTelemetry": self.telemetry.last_tick(),
        }

    # ---- queries ----

    async def _local_query(self, op: dict, timeout: float = 5.0) -> dict:
        async with self._query_lock:
            return await self.engine.query(self.host, self.port, op,
                                           timeout)

    async def get_xlog_location(self) -> str:
        """Current WAL position; falls back to the last observed position
        when the database is down (lib/postgresMgr.js:868-899)."""
        try:
            res = await self._local_query({"op": "status"}, 5.0)
            self._last_xlog = res["xlog_location"]
        except PgError:
            pass
        return self._last_xlog

    # ---- reconfiguration ----

    async def reconfigure(self, pgcfg: dict) -> None:
        """{role, upstream, downstream} — the contract of
        lib/postgresMgr.js:758-845.  Cancelable; serialized."""
        # cancel long-running background transitions BEFORE taking the
        # lock: the re-point watchdog's forced restore runs UNDER
        # _reconf_lock (potentially for hours), so cancelling only
        # after acquisition would WAIT OUT the restore instead of
        # interrupting it — a write outage for the restore's duration
        # on every topology change (cancelable-transition parity,
        # lib/postgresMgr.js:379-385)
        await self._cancel_repoint()
        await self._cancel_catchup()
        async with self._reconf_lock:
            role = pgcfg.get("role")
            log.info("%s: reconfigure -> %s", self.peer_id, role)
            journal = get_journal()
            journal.record("pg.reconfigure.begin", role=role,
                           peer_id=self.peer_id)
            # again under the lock: a reconfigure that was mid-flight
            # when we pre-cancelled may have armed fresh tasks on its
            # way out
            await self._cancel_catchup()
            await self._cancel_repoint()
            t0 = time.monotonic()
            try:
                with span("pg.reconfigure", role=str(role),
                          peer_id=self.peer_id):
                    if role == "primary":
                        if self._applied and self._applied.get("role") \
                                == "primary" and self.running:
                            await self._update_standby(pgcfg)
                        else:
                            await self._primary(pgcfg)
                    elif role in ("sync", "async"):
                        await self._standby(pgcfg)
                    elif role == "none":
                        await self._stop()
                    else:
                        raise PgError("bad role: %r" % role)
            except asyncio.CancelledError:
                journal.record("pg.reconfigure.cancelled", role=role)
                raise
            except Exception as e:
                _RECONF_DUR.observe(time.monotonic() - t0,
                                    role=str(role))
                journal.record("pg.reconfigure.failed", role=role,
                               error=str(e))
                raise
            _RECONF_DUR.observe(time.monotonic() - t0, role=str(role))
            journal.record("pg.reconfigure.done", role=role)
            self._applied = pgcfg

    async def _cancel_repoint(self) -> None:
        t, self._repoint_task = self._repoint_task, None
        if t and not t.done():
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                # as in _cancel_catchup: if WE are being cancelled,
                # propagate rather than resume a cancelled reconfigure
                if cancel_requests(asyncio.current_task()):
                    raise
            except Exception:
                pass

    async def _cancel_catchup(self) -> None:
        t, self._catchup_task = self._catchup_task, None
        if t and not t.done():
            t.cancel()
            try:
                await t
            except asyncio.CancelledError:
                # if WE are being cancelled (topology changed again while
                # awaiting the child's teardown), propagate — otherwise
                # the supposedly-cancelled reconfigure would continue
                if cancel_requests(asyncio.current_task()):
                    raise
            except Exception:
                pass

    # -- config generation --

    def _apply_conf(self, *, read_only: bool,
                    sync_standby_ids: list[str],
                    upstream: dict | None) -> bool:
        """Regenerate the server config ONLY when it differs from what
        was last written to this datadir; returns True when a write
        happened (callers pair a True with the reload/restart that
        makes it take effect).  The signature covers every input
        write_config folds into the files; anything that replaces the
        datadir's content behind our back (initdb, restore, mount)
        clears :attr:`_conf_sig` so the next apply always writes."""
        sig = (bool(read_only), tuple(sync_standby_ids),
               (upstream or {}).get("pgUrl"))
        if sig == self._conf_sig:
            return False
        try:
            self.engine.write_config(
                self.datadir, host=self.host, port=self.port,
                peer_id=self.peer_id, read_only=read_only,
                sync_standby_ids=sync_standby_ids, upstream=upstream)
        except Exception:
            self._conf_sig = None
            raise
        self._conf_sig = sig
        return True

    # -- primary --

    async def _primary(self, pgcfg: dict) -> None:
        """(lib/postgresMgr.js:1115-1184)"""
        downstream = pgcfg.get("downstream")
        singleton = bool(self.cfg.get("singleton"))
        sync_ids = [downstream["id"]] if downstream else []
        # the overlapped-takeover barrier (state/machine.py): writes
        # must not re-enable until the takeover's cluster-state CAS
        # write is durable.  The promote itself is safe to run
        # concurrently with the CAS (the database stays read-only).
        gate = pgcfg.get("commitGate")
        # In-place promotion (pg_promote(), PostgreSQL 12+): a RUNNING
        # standby taking over exits recovery via conf rewrite + reload —
        # no database restart in the takeover path, and no down-window
        # at all (strictly safer than the restart: there is no moment
        # the WAL could gain a shutdown checkpoint).  Everything else —
        # read-only-until-caught-up, the transition snapshot, the
        # catchup watcher — is identical to the restart path.
        # gate on HEALTH, not mere process liveness: a wedged-but-alive
        # database would absorb the SIGHUP without acting on it, and
        # only the restart path's kill escalation recovers it
        promoted = False
        with span("pg.promote") as psp:
            # an injected PgError fails the whole reconfigure and the
            # state machine's retry loop backs off and re-drives it
            await faults.point("pg.promote")
            if (self.running and self._online
                    and self.engine.promotable_in_place
                    and self._applied
                    and self._applied.get("role") in ("sync", "async")):
                log.info("%s: promoting in place (no restart)",
                         self.peer_id)
                if self._apply_conf(read_only=not singleton,
                                    sync_standby_ids=sync_ids,
                                    upstream=None):
                    self._reload()
                try:
                    # a healthy server promotes in well under a second;
                    # a short bound means a JUST-wedged one (health
                    # raced the gate) costs seconds before the restart
                    # fallback, not a full opsTimeout stall in the
                    # takeover path
                    await self.engine.promote_in_place(
                        self.host, self.port,
                        timeout=float(self.cfg["promoteWait"]))
                    promoted = True
                except (PgError, asyncio.TimeoutError) as e:
                    # fall back to the restart path, which recovers any
                    # server state the in-place attempt left behind
                    log.warning("%s: in-place promotion failed (%s); "
                                "restarting instead", self.peer_id, e)
            psp.attrs["mode"] = "reload" if promoted else "restart"
            if not promoted:
                await self._stop()
                await self._prepare_database()
                # read-only until the sync catches up — taking writes
                # before the sync is established risks data loss on the
                # next failover
                self._apply_conf(read_only=not singleton,
                                 sync_standby_ids=sync_ids,
                                 upstream=None)
                await self._start()
        # the catchup watcher arms BEFORE the transition snapshot: the
        # snapshot (a full dataset copy on the dir backend) is not a
        # prerequisite for write-enable, so it must not serialize ahead
        # of the catchup wait on the failover critical path — the two
        # overlap, and reconfigure still returns only after the
        # snapshot completes (its failure stays non-fatal either way)
        if downstream:
            self._catchup_task = asyncio.create_task(
                self._wait_for_standby(downstream["id"], sync_ids,
                                       gate))
        await self._snapshot_safe()

    async def _update_standby(self, pgcfg: dict) -> None:
        """Already primary; only the downstream changed: conf rewrite +
        SIGHUP (lib/postgresMgr.js:1195-1260)."""
        downstream = pgcfg.get("downstream")
        singleton = bool(self.cfg.get("singleton"))
        sync_ids = [downstream["id"]] if downstream else []
        if self._apply_conf(read_only=not singleton,
                            sync_standby_ids=sync_ids, upstream=None):
            self._reload()
        if downstream:
            self._catchup_task = asyncio.create_task(
                self._wait_for_standby(downstream["id"], sync_ids,
                                       pgcfg.get("commitGate")))

    async def _wait_for_standby(self, standby_id: str,
                                sync_ids: list[str],
                                gate: asyncio.Event | None = None
                                ) -> None:
        """Poll replication status until the downstream catches up
        (sent == flush), bounded by replicationTimeout of NO progress,
        then enable writes (lib/postgresMgr.js:1037-1105, 2390-2555).

        *gate* (overlapped takeover): write-enable additionally waits
        for the takeover's cluster-state CAS write to be durable — the
        downstream may ALREADY be streaming from us (it was our async
        in the old chain), so catchup alone is not evidence that the
        topology committed."""
        last_flush: str | None = None
        deadline = time.monotonic() + float(self.cfg["replicationTimeout"])
        with span("pg.catchup", standby=standby_id):
            while not self._closed:
                # stall here keeps the new primary read-only — the
                # stalled-takeover drill; delay stretches the window
                await faults.point("pg.catchup")
                try:
                    res = await self._local_query({"op": "status"}, 5.0)
                    row = next((r for r in res.get("replication", [])
                                if r["application_name"] == standby_id),
                               None)
                    if row and row.get("state") == "streaming":
                        if row["flush_lsn"] != last_flush:
                            last_flush = row["flush_lsn"]
                            deadline = time.monotonic() + \
                                float(self.cfg["replicationTimeout"])
                        if row["sent_lsn"] == row["flush_lsn"]:
                            if gate is not None:
                                # caught up, but the takeover's durable
                                # write may still be in flight: writes
                                # only re-enable once it lands (the
                                # state machine sets the gate, or
                                # cancels us on a lost CAS race)
                                await gate.wait()
                            log.info("%s: standby %s caught up at %s; "
                                     "enabling writes", self.peer_id,
                                     standby_id, row["flush_lsn"])
                            if self._apply_conf(
                                    read_only=False,
                                    sync_standby_ids=sync_ids,
                                    upstream=None):
                                self._reload()
                            self._emit("writable", standby_id)
                            return
                    if time.monotonic() > deadline:
                        log.error("%s: standby %s made no replication "
                                  "progress in %ss; still waiting",
                                  self.peer_id, standby_id,
                                  self.cfg["replicationTimeout"])
                        self._emit("replicationTimeout", standby_id)
                        deadline = time.monotonic() + \
                            float(self.cfg["replicationTimeout"])
                except PgError as e:
                    log.debug("catchup poll error: %s", e)
                await asyncio.sleep(float(self.cfg["replPollInterval"]))

    # -- standby --

    async def _standby(self, pgcfg: dict, *,
                       force_restore: bool = False) -> None:
        """(lib/postgresMgr.js:1282-1460).  *force_restore* skips both
        the live re-point fast path and the local-boot attempt — the
        re-point watchdog uses it when the stream never attached."""
        upstream = pgcfg["upstream"]
        # Live upstream re-point (PostgreSQL 13 semantics): a RUNNING
        # standby whose upstream merely changed rewrites conf and
        # reloads instead of restarting — this is the failover-critical
        # hop (the new sync must attach to the new primary before
        # writes re-enable), and skipping the database restart takes a
        # process boot out of the takeover path.  If the new upstream
        # refuses the stream (divergence), simpg/fakepg exit non-zero
        # exactly as at boot (crash-only supervision recovers); real
        # PostgreSQL's walreceiver retries FOREVER instead, so for
        # engines with lingering_repoint_failure a watchdog polls
        # pg_stat_wal_receiver and forces the restore path if the
        # stream never attaches (ADVICE r4).
        # health-gated like the promotion fast path: a wedged process
        # never handles the reload; only a restart recovers it
        if (not force_restore and self.running and self._online
                and self.engine.reloadable_upstream
                and self._applied
                and self._applied.get("role") in ("sync", "async")
                # the running db must actually BE a standby: an
                # applied config with no upstream booted it
                # non-recovery, and no reload can flip a running
                # primary-mode process into recovery — only the
                # restart path below can
                and self._applied.get("upstream")):
            log.info("%s: re-pointing standby upstream to %s (reload, "
                     "no restart)", self.peer_id, upstream.get("id"))
            with span("pg.repoint", upstream=upstream.get("id")):
                await faults.point("pg.repoint")
                if self._apply_conf(read_only=True,
                                    sync_standby_ids=[],
                                    upstream=upstream):
                    self._reload()
            if self.engine.lingering_repoint_failure:
                self._repoint_task = asyncio.create_task(
                    self._repoint_watchdog(pgcfg))
            return
        try:
            if force_restore:
                raise NeedsRestoreError(
                    "re-point watchdog: stream never attached")
            await self._stop()
            await self._ensure_dataset_mounted(create=False)
            if not self.engine.is_initialized(self.datadir):
                raise NeedsRestoreError("no local database")
            self._apply_conf(read_only=True, sync_standby_ids=[],
                             upstream=upstream)
            await self._start(allow_restore_exit=True)
        except asyncio.CancelledError:
            raise
        except (PgError, StorageError) as e:
            # ANY failure becoming a standby ⇒ full restore from the
            # upstream's backup server (lib/postgresMgr.js:1363-1374)
            if self.restore_fn is None:
                raise
            log.warning("%s: standby setup failed (%s); restoring from "
                        "%s", self.peer_id, e, upstream.get("backupUrl"))
            await self._stop()
            self._emit("restoreStart", upstream)
            get_journal().record("restore.start",
                                 upstream=upstream.get("id"),
                                 url=upstream.get("backupUrl"),
                                 reason=str(e))
            with span("pg.restore", upstream=upstream.get("id")):
                try:
                    # error:StorageError = a restore that fails before
                    # the first byte; stall = one wedged indefinitely
                    # (heal with `fault clear` — the transition stays
                    # cancelable throughout)
                    await faults.point("pg.restore")
                    await self.restore_fn(upstream)
                except asyncio.CancelledError:
                    raise
                except Exception as re_err:
                    _RESTORES.inc(result="failed")
                    get_journal().record("restore.failed",
                                         upstream=upstream.get("id"),
                                         error=str(re_err))
                    raise
                _RESTORES.inc(result="ok")
                get_journal().record("restore.done",
                                     upstream=upstream.get("id"))
                self._emit("restoreDone", upstream)
                # the restore replaced the datadir wholesale: whatever
                # config it carried is not ours
                self._conf_sig = None
                await self._ensure_dataset_mounted(create=False)
                self._apply_conf(read_only=True, sync_standby_ids=[],
                                 upstream=upstream)
                # replay: boot the restored dataset and chew through
                # its WAL until the server answers health probes — the
                # second half of a restore's wall-clock cost
                with span("pg.replay"):
                    await self._start()
        # real-postgres engines linger on a refused stream at BOOT too
        # (allow_restore_exit only catches an exiting child): every
        # standby transition arms the attachment watchdog, not just
        # the reload fast path (code-review r5)
        if self.engine.lingering_repoint_failure:
            self._repoint_task = asyncio.create_task(
                self._repoint_watchdog(pgcfg))

    async def _upstream_reachable(self, upstream: dict) -> bool:
        """Cheap TCP probe of the upstream database port: separates
        'reachable but refuses our stream' (divergence — restore is
        the right escalation) from 'temporarily unreachable' (outage —
        wait, like the walreceiver itself would)."""
        try:
            _scheme, host, port = parse_pg_url(upstream["pgUrl"])
        except Exception:
            return True        # unparseable: fail open (old behavior)
        try:
            _r, w = await asyncio.wait_for(
                asyncio.open_connection(host, port), 2.0)
        except (OSError, asyncio.TimeoutError):
            return False
        w.close()
        # bounded drain of the half-closed transport: each watchdog
        # poll otherwise leaks it until GC (ADVICE r5)
        with contextlib.suppress(Exception):
            await asyncio.wait_for(w.wait_closed(), 2.0)
        return True

    async def _attached_quiet(self, upstream: dict) -> bool:
        try:
            return await self.engine.upstream_attached(
                self.host, self.port, upstream, 5.0)
        except PgError:
            return False

    async def _status_quiet(self) -> dict | None:
        try:
            return await self._local_query({"op": "status"}, 5.0)
        except PgError:
            return None

    async def _repoint_watchdog(self, pgcfg: dict) -> None:
        """After a standby transition on a real-postgres engine, verify
        the walreceiver actually attaches to the NEW upstream: a
        refused stream (divergence) leaves the server running and
        retrying forever, looking healthy in recovery while the
        restore path never triggers (ADVICE r4).  No attachment AND no
        recovery progress within replicationTimeout — while the
        upstream is REACHABLE — ⇒ force the full restore path.

        Two things extend the deadline, exactly like the catchup
        loop's no-PROGRESS semantics (a healthy standby must never be
        wiped for waiting):

        - the REPLAY position advancing — e.g. a returning standby
          chewing through a local pg_wal backlog before its
          walreceiver ever starts (during which receive_lsn is NULL:
          progress must be read from replay, not receive);
        - the upstream being unreachable — an outage is
          indistinguishable from divergence at the walreceiver level
          (pg_stat_wal_receiver is empty either way), and a real
          walreceiver just keeps retrying an outage; wiping the local
          dataset to restore from a peer that is down only
          crash-loops.  Only reachable-but-never-attached counts
          toward the divergence verdict."""
        upstream = pgcfg["upstream"]
        poll = max(0.2, float(self.cfg["replPollInterval"]))
        repl_timeout = float(self.cfg["replicationTimeout"])
        deadline = time.monotonic() + repl_timeout
        last_replay: str | None = None
        while not self._closed and time.monotonic() < deadline:
            # the attachment probe and the replay-progress read are
            # independent questions about the same server: ask them
            # concurrently instead of serializing two query round
            # trips per poll tick
            attached, res = await asyncio.gather(
                self._attached_quiet(upstream),
                self._status_quiet())
            if attached:
                return
            progressed = False
            if res is not None:
                replay = res.get("replay_location") \
                    or res.get("xlog_location")
                if replay is not None and replay != last_replay:
                    if last_replay is not None:
                        progressed = True
                        deadline = time.monotonic() + repl_timeout
                    last_replay = replay
            # only probe when this iteration saw neither attachment nor
            # replay progress — the only case where the unreachable
            # extension matters (every probe forks a real backend on
            # the upstream just to see EOF)
            if not progressed \
                    and not await self._upstream_reachable(upstream):
                deadline = time.monotonic() + repl_timeout
            await asyncio.sleep(poll)
        if self._closed:
            return
        log.warning("%s: standby never attached to reachable upstream "
                    "%s (and made no recovery progress); forcing the "
                    "restore path", self.peer_id, upstream.get("id"))
        async with self._reconf_lock:
            # only if the topology has not moved on meanwhile
            if self._applied is not pgcfg or self._closed:
                return
            try:
                await self._standby(pgcfg, force_restore=True)
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # the database is deliberately stopped at this point:
                # swallowing the failure would park the peer out of
                # the chain forever.  Crash-only escalation (MANTA-997
                # parity): the sitter exits, supervision restarts the
                # peer, and the boot path retries the restore.
                log.exception("%s: forced restore after re-point "
                              "failure did not complete", self.peer_id)
                self._emit("error",
                           "forced restore failed: %s" % e)

    # -- database preparation --

    async def _prepare_database(self) -> None:
        """Mount or create the dataset; initdb if empty
        (lib/postgresMgr.js:1806-1987)."""
        await self._ensure_dataset_mounted(create=True)
        if not self.engine.is_initialized(self.datadir):
            log.info("%s: initializing fresh database", self.peer_id)
            await self.engine.initdb(self.datadir)
            self._conf_sig = None    # fresh datadir: nothing written yet

    async def _ensure_dataset_mounted(self, *, create: bool) -> None:
        if not self.dataset:
            Path(self.datadir).mkdir(parents=True, exist_ok=True)
            return
        if not await self.storage.exists(self.dataset):
            if not create:
                raise NeedsRestoreError("dataset %s missing" % self.dataset)
            await self.storage.create(self.dataset,
                                      mountpoint=self.datadir)
            self._conf_sig = None    # brand-new dataset at the datadir
        if not await self.storage.is_mounted(self.dataset):
            await self.storage.set_mountpoint(self.dataset, self.datadir)
            await self.storage.mount(self.dataset)
            # a (re)mount can change what lives at the datadir: the
            # cached config signature no longer describes those files
            self._conf_sig = None

    async def _snapshot_safe(self) -> None:
        """Snapshot at primary-transition time
        (lib/postgresMgr.js:1158-1160); failures are non-fatal."""
        if not self.dataset:
            return
        try:
            await self.storage.snapshot(self.dataset)
        except StorageError as e:
            log.warning("%s: transition snapshot failed: %s",
                        self.peer_id, e)

    # -- process control --

    async def _start(self, allow_restore_exit: bool = False) -> None:
        """Spawn and poll health until up, bounded by opsTimeout
        (lib/postgresMgr.js:1695-1794)."""
        if self.running:
            return
        argv = self.engine.start_argv(self.datadir)
        if self._log_fh is None:
            logpath = self.cfg.get(
                "pgLogFile", str(Path(self.datadir).parent
                                 / ("pg-%d.log" % self.port)))
            # worker thread: a degraded disk must not stall the loop
            # on the failover path
            self._log_fh = await asyncio.to_thread(open, logpath, "ab")
        self._proc = await asyncio.create_subprocess_exec(
            *argv, stdout=self._log_fh, stderr=self._log_fh,
            env=self.engine.child_env())
        log.info("%s: started db pid=%d", self.peer_id, self._proc.pid)
        boot_start = time.monotonic()
        deadline = boot_start + float(self.cfg["opsTimeout"])
        while time.monotonic() < deadline:
            if self._proc.returncode is not None:
                rc = self._proc.returncode
                self._proc = None
                if allow_restore_exit:
                    raise NeedsRestoreError(
                        "database exited rc=%d during standby boot" % rc)
                raise PgError("database exited rc=%d during boot" % rc)
            if await self.engine.health(self.host, self.port, 1.0):
                self._online = True
                # boot complete: only NOW is an exit "unexpected" —
                # exits during boot are handled by this loop (and may
                # legitimately mean "needs restore")
                self._exit_watch = asyncio.create_task(
                    self._watch_exit(self._proc))
                return
            # fine-grained early, coarser later: boot completes in tens
            # of ms for the sim engine and this poll is squarely on the
            # failover-to-writable path
            await asyncio.sleep(
                0.05 if time.monotonic() - boot_start < 2.0 else 0.2)
        raise PgError("database did not come up within opsTimeout")

    async def _watch_exit(self, proc: asyncio.subprocess.Process) -> None:
        """Unexpected database death is unrecoverable: the reference logs
        fatal and emits 'error' so the sitter exits and the supervisor
        restarts the whole peer (lib/postgresMgr.js:1711-1755,
        MANTA-997).  Deliberate stops null out self._proc first, so this
        only fires for deaths we did not cause."""
        await proc.wait()
        if self._closed or self._proc is not proc:
            return
        self._proc = None
        self._online = False
        log.critical("%s: database exited unexpectedly (rc=%s); "
                     "emitting error (crash-only: the peer should exit)",
                     self.peer_id, proc.returncode)
        self._emit("error", "postgres exited unexpectedly (rc=%s)"
                   % proc.returncode)

    async def _stop(self) -> None:
        """SIGINT → SIGQUIT → SIGKILL escalation
        (lib/postgresMgr.js:1484-1541)."""
        proc = self._proc
        self._proc = None
        self._online = False
        if proc is None or proc.returncode is not None:
            return
        step = max(0.5, float(self.cfg["opsTimeout"]) / 6.0)
        for sig in (signal.SIGINT, signal.SIGQUIT, signal.SIGKILL):
            try:
                proc.send_signal(sig)
            except ProcessLookupError:
                return
            try:
                await asyncio.wait_for(proc.wait(), step)
                return
            except asyncio.TimeoutError:
                continue
        await proc.wait()

    async def _kill_proc(self) -> None:
        proc = self._proc
        self._proc = None
        if proc and proc.returncode is None:
            with contextlib.suppress(ProcessLookupError):
                proc.kill()
            with contextlib.suppress(Exception):
                await proc.wait()

    def _reload(self) -> None:
        """SIGHUP (conf reload) — lib/postgresMgr.js:1003, 2338-2345."""
        if self.running:
            with contextlib.suppress(ProcessLookupError):
                self._proc.send_signal(signal.SIGHUP)

    async def _restart(self) -> None:
        await self._stop()
        await self._start()

    # -- health --

    async def _health_loop(self) -> None:
        """Reactive semantics verbatim from the reference
        (lib/postgresMgr.js:1550-1646): probe every healthChkInterval,
        declare unhealthy when the probe fails/times out.  On top, each
        tick feeds the telemetry ring (latency, timeout, lag, WAL
        stall, flaps) and the failure-prediction score is refreshed —
        an early-warning signal exposed at GET /state and by
        `manatee-adm pg-status` long before the hard timeout trips."""
        interval = float(self.cfg["healthChkInterval"])
        timeout = float(self.cfg["healthChkTimeout"])
        tick = 0
        while not self._closed:
            await asyncio.sleep(interval)
            tick += 1
            if not self.running:
                if self._online:
                    self._online = False
                    self._probe_flip("offline", "not running")
                    self._emit("unhealthy", "not running")
                continue
            # LIVENESS keeps the reference's contract verbatim: one
            # cheap probe per tick, healthChkTimeout bounding it
            # (lib/postgresMgr.js:1550-1646)
            t0 = time.monotonic()
            t0_wall = time.time()
            ok = await self.engine.health(self.host, self.port, timeout)
            latency_ms = (time.monotonic() - t0) * 1000.0
            _PROBE_DUR.observe(latency_ms / 1000.0)
            # TELEMETRY piggybacks on a subset of ticks (the status op
            # may be several queries on a real engine); its failure
            # never flips liveness — missing lag/wal is just unknown
            st: dict | None = None
            if ok and tick % _STATUS_EVERY == 0:
                try:
                    st = await asyncio.wait_for(
                        self.engine.status(self.host, self.port, timeout),
                        timeout)
                except (PgError, asyncio.TimeoutError):
                    st = None
            self._record_telemetry(ok, latency_ms, st)
            flipped = None
            if ok and not self._online:
                self._online = True
                flipped = "online"
                self._probe_flip("online", None)
                self._emit("healthy", None)
            elif not ok and self._online:
                self._online = False
                flipped = "offline"
                self._probe_flip("offline", "health check failed")
                self._emit("unhealthy", "health check failed")
            if flipped or not ok:
                # the probe→verdict→act hop, as a span — but only for
                # ticks that carry signal (failures and verdict flips):
                # a healthy heartbeat every interval would just evict
                # other spans from the ring.  Deliberately AMBIENT
                # (trace/parent None): probes precede any transition
                # they might trigger, so there is no trace to join —
                # they are read from the raw GET /spans feed, not from
                # `manatee-adm trace` trees.
                record_span("sitter.probe", ts=t0_wall,
                            dur=latency_ms / 1000.0,
                            status="ok" if ok else "error",
                            trace_id=None, parent_id=None,
                            peer_id=self.peer_id,
                            **({"verdict": flipped} if flipped else {}))

    def _probe_flip(self, to: str, why: str | None) -> None:
        _PROBE_FLIPS.inc(to=to)
        get_journal().record("probe.flip", to=to, why=why,
                             peer_id=self.peer_id)

    def _record_telemetry(self, ok: bool, latency_ms: float,
                          st: dict | None) -> None:
        from manatee_tpu.state.types import parse_lsn
        wal = None
        lag = None
        in_recovery = False
        if st:
            in_recovery = bool(st.get("in_recovery"))
            lag = st.get("replay_lag_seconds")
            try:
                wal = parse_lsn(st["xlog_location"])
            except (KeyError, ValueError, TypeError):
                wal = None
        self.telemetry.add(
            latency_ms=(latency_ms if ok
                        else self._failed_probe_latency_ms),
            timed_out=not ok, lag_s=lag, wal_lsn=wal,
            in_recovery=in_recovery)
        if lag is not None:
            _REPL_LAG.set(float(lag), peer=self.peer_id)
        elif st and not in_recovery:
            _REPL_LAG.set(0.0, peer=self.peer_id)  # primaries: no lag
        if self._scorer.available and self.telemetry.ready():
            self.health_score = self._scorer.score(
                self.telemetry.window_array())
        if self.health_score is not None:
            _HEALTH_SCORE.set(float(self.health_score),
                              peer=self.peer_id)
        if self._telemetry_dump:
            self._dump_tick(ok, latency_ms, lag, wal, in_recovery)

    def _dump_tick(self, ok: bool, latency_ms: float, lag, wal,
                   in_recovery: bool) -> None:
        """One JSONL line per probe tick: the ring's RAW inputs plus the
        liveness verdict, so offline evaluation can replay exactly what
        the deployed path saw (health.train evaluate_recorded)."""
        import json as _json
        try:
            if self._dump_fh is None:
                self._dump_fh = open(self._telemetry_dump, "a")
            self._dump_fh.write(_json.dumps({
                "ts": round(time.time(), 3),
                "peer": self.peer_id,
                "latency_ms": round(latency_ms, 3),
                "timed_out": not ok,
                "lag_s": lag,
                "wal_lsn": wal,
                "in_recovery": in_recovery,
                "online": self._online,
                "score": (round(self.health_score, 4)
                          if self.health_score is not None else None),
            }) + "\n")
            self._dump_fh.flush()
        except OSError:
            self._telemetry_dump = None   # capture must never hurt HA
