"""PostgreSQL management layer (reference: lib/postgresMgr.js, 2556 lines).

:class:`manatee_tpu.pg.manager.PostgresMgr` owns the database child
process and all of its configuration, behind a pluggable *engine*:

- :class:`manatee_tpu.pg.postgres.PostgresEngine` drives real
  ``postgres``/``initdb`` binaries (production);
- :class:`manatee_tpu.pg.simpg.SimPgEngine` drives
  ``manatee_tpu.pg.simpg`` — an in-repo simulated postgres child process
  with real TCP queries, real WAL streaming replication (synchronous
  acks, cascading), standby recovery config, and postgres signal
  semantics — so the full manager and the fault-injection suite run on
  machines without PostgreSQL installed.
"""

from manatee_tpu.pg.manager import PostgresMgr

__all__ = ["PostgresMgr"]
