"""Database engine abstraction for PostgresMgr.

Separates WHAT the manager does (lifecycle, transitions, health,
replication checks — lib/postgresMgr.js) from HOW a concrete database is
driven.  Two engines:

- SimPgEngine → manatee_tpu.pg.simpg child processes (dev/test images);
- PostgresEngine → real postgres/initdb (manatee_tpu.pg.postgres).

The engine query surface is structured (dicts), modeled on the exact
queries the reference issues: ``select current_time`` health probes
(lib/postgresMgr.js:1550-1646), ``pg_stat_replication`` rows with
sent/write/flush/replay LSNs and sync_state (:2390-2555),
``pg_current_wal_lsn``/``pg_last_wal_receive_lsn`` (:868-899), and
``pg_is_in_recovery``.
"""

from __future__ import annotations

import abc
import asyncio
import json
import sys
from pathlib import Path
from urllib.parse import urlparse


class PgError(Exception):
    pass


class PgQueryTimeout(PgError):
    pass


def parse_pg_url(url: str) -> tuple[str, str, int]:
    """Returns (scheme, host, port).  'tcp://postgres@10.0.0.1:5432/postgres'
    (the reference's pgUrl shape, lib/shard.js:39-54) or 'sim://host:port'."""
    u = urlparse(url)
    if not u.hostname or not u.port:
        raise PgError("bad pg url: %r" % url)
    return u.scheme, u.hostname, int(u.port)


class Engine(abc.ABC):
    """Driver for one local database instance plus remote status queries."""

    scheme = "?"
    # True when a RUNNING standby can re-point its walreceiver at a new
    # upstream via conf rewrite + reload (primary_conninfo became
    # reloadable in PostgreSQL 13) — the failover-critical hop skips a
    # full database restart
    reloadable_upstream = False
    # True when a RUNNING standby can exit recovery without a restart:
    # the manager writes the primary config, reloads, then awaits
    # promote_in_place() (pg_promote(), PostgreSQL 12+).  Demotion
    # always restarts, like real postgres.
    promotable_in_place = False

    # True when a standby whose re-pointed stream is REFUSED (diverged)
    # keeps running and retrying forever instead of exiting — real
    # PostgreSQL walreceiver semantics.  The manager then arms a
    # watchdog after each live re-point: if the stream never attaches
    # to the new upstream within replicationTimeout it forces the
    # restore path (ADVICE r4).  simpg/fakepg default to exit-on-
    # refusal, where crash-only supervision already covers it.
    lingering_repoint_failure = False

    async def promote_in_place(self, host: str, port: int,
                               timeout: float = 30.0) -> None:
        """Finish an in-place promotion on the running server.  The
        default is a no-op for engines whose conf reload already exits
        recovery (simpg); PostgresEngine issues SELECT pg_promote()."""
        return None

    async def upstream_attached(self, host: str, port: int,
                                upstream: dict,
                                timeout: float = 5.0) -> bool:
        """Is the walreceiver streaming from *upstream*?  Consulted by
        the re-point watchdog; only meaningful for engines with
        lingering_repoint_failure (PostgresEngine reads
        pg_stat_wal_receiver)."""
        return True

    async def aclose(self) -> None:
        """Release engine-held resources (PostgresEngine kills its
        pooled psql coprocesses here); default engines hold none."""
        return None

    # -- local cluster management --

    @abc.abstractmethod
    def is_initialized(self, datadir: str) -> bool: ...

    @abc.abstractmethod
    async def initdb(self, datadir: str) -> None: ...

    @abc.abstractmethod
    def start_argv(self, datadir: str) -> list[str]: ...

    def child_env(self) -> dict | None:
        """Extra environment for the spawned database process (None =
        inherit unchanged)."""
        return None

    @abc.abstractmethod
    def write_config(self, datadir: str, *, host: str, port: int,
                    peer_id: str,
                    read_only: bool,
                    sync_standby_ids: list[str],
                    upstream: dict | None) -> None:
        """Write the full server config for a role.  *upstream* is a
        PeerInfo dict (standby mode: primary_conninfo) or None (primary).
        The reference's analogue regenerates postgresql.conf from the
        template plus recovery.conf / standby.signal for PG>=12
        (lib/postgresMgr.js:2200-2336)."""

    # -- queries (local or remote) --

    @abc.abstractmethod
    async def query(self, host: str, port: int, op: dict,
                    timeout: float = 5.0) -> dict:
        """Issue one structured query; raises PgError/PgQueryTimeout."""

    async def query_url(self, url: str, op: dict,
                        timeout: float = 5.0) -> dict:
        _, host, port = parse_pg_url(url)
        return await self.query(host, port, op, timeout)

    async def health(self, host: str, port: int,
                     timeout: float = 5.0) -> bool:
        try:
            res = await self.query(host, port, {"op": "health"}, timeout)
            return bool(res.get("ok"))
        except PgError:
            return False

    async def status(self, host: str, port: int,
                     timeout: float = 5.0) -> dict:
        return await self.query(host, port, {"op": "status"}, timeout)


class SimPgEngine(Engine):
    """Engine for the simulated postgres (manatee_tpu.pg.simpg)."""

    scheme = "sim"
    reloadable_upstream = True   # simpg implements the PG13 semantics
    promotable_in_place = True   # ... and pg_promote() (PG12+)

    def is_initialized(self, datadir: str) -> bool:
        from manatee_tpu.pg.simpg import VERSION_FILE
        return (Path(datadir) / VERSION_FILE).exists()

    async def initdb(self, datadir: str) -> None:
        from manatee_tpu.pg.simpg import CONF_NAME, VERSION, VERSION_FILE
        d = Path(datadir)
        d.mkdir(parents=True, exist_ok=True)
        if self.is_initialized(datadir):
            raise PgError("already initialized: %s" % datadir)

        def _write() -> None:        # worker thread: off the loop
            (d / VERSION_FILE).write_text(VERSION + "\n")
            (d / CONF_NAME).write_text(json.dumps({
                "port": 0, "read_only": True,
                "synchronous_standby_names": [],
                "primary_conninfo": None,
            }))

        await asyncio.to_thread(_write)

    def start_argv(self, datadir: str) -> list[str]:
        return [sys.executable, "-m", "manatee_tpu.pg.simpg",
                "-D", str(datadir)]

    def child_env(self) -> dict | None:
        # the child must be able to import this package regardless of the
        # parent's cwd
        import os
        import manatee_tpu
        pkg_root = str(Path(manatee_tpu.__file__).parent.parent)
        env = dict(os.environ)
        parts = [pkg_root] + ([env["PYTHONPATH"]]
                              if env.get("PYTHONPATH") else [])
        env["PYTHONPATH"] = os.pathsep.join(parts)
        return env

    def write_config(self, datadir: str, *, host: str, port: int,
                     peer_id: str, read_only: bool,
                     sync_standby_ids: list[str],
                     upstream: dict | None) -> None:
        from manatee_tpu.pg.simpg import CONF_NAME
        conninfo = None
        if upstream is not None:
            _s, uhost, uport = parse_pg_url(upstream["pgUrl"])
            conninfo = {"host": uhost, "port": uport}
        conf = {
            "host": host,
            "port": port,
            "peer_id": peer_id,
            "read_only": read_only,
            "synchronous_standby_names": sync_standby_ids,
            "primary_conninfo": conninfo,
        }
        p = Path(datadir) / CONF_NAME
        tmp = p.with_name(p.name + ".tmp")
        tmp.write_text(json.dumps(conf, indent=2))
        tmp.replace(p)

    async def query(self, host: str, port: int, op: dict,
                    timeout: float = 5.0) -> dict:
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(host, port), timeout)
        except (OSError, asyncio.TimeoutError) as e:
            raise PgError("cannot connect to %s:%d: %s"
                          % (host, port, e)) from None
        try:
            writer.write((json.dumps(op) + "\n").encode())
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), timeout)
            if not line:
                raise PgError("connection closed by %s:%d" % (host, port))
            res = json.loads(line)
        except asyncio.TimeoutError:
            raise PgQueryTimeout("query timed out after %ss" % timeout) \
                from None
        except (ConnectionError, json.JSONDecodeError) as e:
            raise PgError(str(e)) from None
        finally:
            writer.close()
        if not res.get("ok") and "error" in res:
            raise PgError(res["error"])
        return res
