"""simpg — a simulated PostgreSQL server process.

Runnable as ``python -m manatee_tpu.pg.simpg -D <datadir>``.  It models
exactly the PostgreSQL surface the control plane depends on
(lib/postgresMgr.js), with real processes, sockets, and files:

- a data directory created by "initdb" (``SimPgEngine.initdb``) holding
  a WAL file (JSON-lines of ``{lsn, value}`` records) and config;
- a TCP server speaking newline-JSON for the queries the manager issues:
  health ("select current_time", :1550-1646), replication status
  (pg_stat_replication, :2390-2555), xlog position (:868-899),
  pg_is_in_recovery, plus INSERT/SELECT for availability tests;
- **synchronous replication**: with ``synchronous_standby_names`` set,
  an insert does not ack until the named standby has flushed that
  record (the guarantee docs/user-guide.md:79-84 relies on);
- **streaming + cascading replication**: a standby connects to its
  upstream (``primary_conninfo`` in the conf), pulls records from its
  flush point, acks flush positions, and serves replication to its own
  downstream in turn;
- **recovery config**: with primary_conninfo set the server is a
  standby (in_recovery=True, read-only); without it, a primary;
- **divergence detection**: a standby whose WAL is ahead of (or
  inconsistent with) its upstream refuses to stream and exits, forcing
  the manager down its restore path (docs/xlog-diverge.md analogue);
- postgres signal semantics: SIGINT = fast shutdown, SIGQUIT =
  immediate, SIGHUP = reload of the reloadable GUCs — read_only,
  synchronous_standby_names, and (modern-postgres parity)
  primary_conninfo: a changed upstream re-points the walreceiver live
  (PG13+), a REMOVED one promotes in place (pg_promote(), PG12+).
  Demotion (gaining a primary_conninfo while running as primary) still
  requires a restart, like real postgres.

LSNs are rendered "0/XXXXXXX" like postgres so the control plane's LSN
arithmetic (pg-lsn parity) is exercised for real.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import signal
import sys
import time
from pathlib import Path

CONF_NAME = "simpg.conf"
WAL_NAME = "wal.jsonl"
VERSION_FILE = "SIMPG_VERSION"
VERSION = "12.0"


def lsn_str(n: int) -> str:
    return "%X/%08X" % (n >> 32, n & 0xFFFFFFFF)


def read_conf(datadir: Path) -> dict:
    return json.loads((datadir / CONF_NAME).read_text())


class Wal:
    """Append-only record log; lsn = 1 + index (0 reserved for 'nothing')."""

    def __init__(self, datadir: Path):
        self.path = datadir / WAL_NAME
        self.records: list[dict] = []
        self._run = hashlib.sha1()
        # prefix_digests[k] = digest of records[:k]; maintained
        # incrementally so the replication handshake stays O(1) even
        # against a storm-grown WAL (recomputing per reconnect would be
        # quadratic over a kill/reconnect churn)
        self.prefix_digests: list[str] = [self._run.hexdigest()]
        if self.path.exists():
            for line in self.path.read_text().splitlines():
                if line.strip():
                    self._track(json.loads(line))
        self._fh = open(self.path, "a")

    def _track(self, rec: dict) -> None:
        self.records.append(rec)
        self._run.update(json.dumps(
            [rec["lsn"], rec["value"], rec["ts"]]).encode())
        self._run.update(b"\x00")
        self.prefix_digests.append(self._run.hexdigest())

    @property
    def last_lsn(self) -> int:
        return len(self.records)

    def append(self, value, ts: float | None = None) -> int:
        rec = {"lsn": self.last_lsn + 1, "value": value,
               "ts": ts if ts is not None else time.time()}
        self._track(rec)
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())
        return rec["lsn"]

    def get_from(self, lsn: int) -> list[dict]:
        return self.records[lsn:]

    def digest_to(self, lsn: int) -> str:
        """Digest of the WAL prefix up to *lsn* — the sim's analogue of
        PostgreSQL's timeline-history check.  An equal-LENGTH but
        divergent-CONTENT history (old primary and new primary both
        wrote record N) is invisible to the from_lsn comparison alone;
        the digest makes any content divergence refuse the stream."""
        return self.prefix_digests[lsn]


class SimPgServer:
    def __init__(self, datadir: Path):
        self.datadir = datadir
        self.conf = read_conf(datadir)
        self.wal = Wal(datadir)
        self.port = int(self.conf["port"])
        self.peer_id = self.conf.get("peer_id", "?")
        # replication bookkeeping: standby_id -> {sent, flush, replay}
        self.downstreams: dict[str, dict] = {}
        self._repl_waiters: list[asyncio.Event] = []
        self._upstream_task: asyncio.Task | None = None
        self._server: asyncio.AbstractServer | None = None
        self._stopping = False
        self.last_replay_ts: float | None = None
        # standby-side upstream link health: replay in simpg is
        # synchronous on receive, so connected == caught up (lag 0);
        # when the link is down, lag = time since last upstream contact
        self._upstream_ok = False
        self._upstream_contact: float | None = None
        self._boot_ts = time.time()

    # ---- role helpers ----

    @property
    def in_recovery(self) -> bool:
        return bool(self.conf.get("primary_conninfo"))

    @property
    def read_only(self) -> bool:
        return self.in_recovery or bool(self.conf.get("read_only"))

    def sync_names(self) -> list[str]:
        return self.conf.get("synchronous_standby_names") or []

    # ---- lifecycle ----

    async def run(self) -> None:
        loop = asyncio.get_running_loop()
        stop = asyncio.Event()

        def fast_shutdown():
            # SIGINT: abort connections, flush, exit 0
            self._stopping = True
            stop.set()

        def immediate_shutdown():
            # SIGQUIT: die NOW, no checkpoint (crash-consistent state)
            os._exit(2)

        def reload_conf():
            try:
                newconf = read_conf(self.datadir)
            except (OSError, json.JSONDecodeError):
                return
            # reloadable GUCs (postgres parity): read_only,
            # synchronous_standby_names — and, as of PostgreSQL 13,
            # primary_conninfo: a running standby re-points its
            # walreceiver at the new upstream without a restart
            self.conf["read_only"] = newconf.get("read_only")
            self.conf["synchronous_standby_names"] = \
                newconf.get("synchronous_standby_names")
            new_upstream = newconf.get("primary_conninfo")
            if self.in_recovery and new_upstream and \
                    new_upstream != self.conf.get("primary_conninfo"):
                self.conf["primary_conninfo"] = new_upstream
                if self._upstream_task:
                    self._upstream_task.cancel()
                self._upstream_ok = False
                self._upstream_task = asyncio.create_task(
                    self._stream_from_upstream())
            elif self.in_recovery and not new_upstream:
                # pg_promote() parity (PostgreSQL 12+): exit recovery
                # IN PLACE — stop the walreceiver, keep the WAL and the
                # process, start taking writes per read_only.  (The
                # reverse, demoting a primary, still requires a restart
                # — exactly like real postgres.)
                self.conf["primary_conninfo"] = None
                if self._upstream_task:
                    self._upstream_task.cancel()
                    self._upstream_task = None
                self._upstream_ok = False
                sys.stderr.write("simpg %s promoted in place\n"
                                 % self.peer_id)
                sys.stderr.flush()
            self._wake_repl_waiters()

        loop.add_signal_handler(signal.SIGINT, fast_shutdown)
        loop.add_signal_handler(signal.SIGTERM, fast_shutdown)
        loop.add_signal_handler(signal.SIGQUIT, immediate_shutdown)
        loop.add_signal_handler(signal.SIGHUP, reload_conf)

        if self.in_recovery:
            # probe the upstream for divergence BEFORE opening our
            # listener: a diverged standby must fail its boot (so the
            # manager takes the restore path) rather than answer health
            # checks and die moments later.  An unreachable upstream is
            # fine — the background streamer keeps retrying.
            await self._probe_upstream_divergence()

        self._server = await asyncio.start_server(
            self._handle_conn, self.conf.get("host", "127.0.0.1"),
            self.port)
        sys.stderr.write("simpg %s listening on %d (recovery=%s)\n"
                         % (self.peer_id, self.port, self.in_recovery))
        sys.stderr.flush()

        if self.in_recovery:
            self._upstream_task = asyncio.create_task(
                self._stream_from_upstream())

        await stop.wait()
        if self._upstream_task:
            self._upstream_task.cancel()
            try:
                await self._upstream_task
            except asyncio.CancelledError:
                pass
            except Exception:
                pass       # a dying streamer's last error is moot
        self._server.close()

    # ---- upstream replication (we are a standby) ----

    async def _probe_upstream_divergence(self) -> None:
        conninfo = self.conf["primary_conninfo"]
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(conninfo["host"],
                                        int(conninfo["port"])), 2.0)
        except (OSError, asyncio.TimeoutError):
            return  # upstream down; not a divergence verdict
        try:
            # distinct id: the probe must never collide with the real
            # stream's registration on the upstream
            req = {"op": "replicate", "from_lsn": self.wal.last_lsn,
                   "prefix_digest": self.wal.digest_to(self.wal.last_lsn),
                   "standby_id": self.peer_id + ":probe"}
            writer.write((json.dumps(req) + "\n").encode())
            await writer.drain()
            hello = json.loads(await asyncio.wait_for(
                reader.readline(), 2.0))
            if not hello.get("ok"):
                sys.stderr.write("simpg: boot replication probe refused: "
                                 "%s\n" % hello.get("error"))
                sys.stderr.flush()
                os._exit(3)
        except (OSError, ValueError, json.JSONDecodeError,
                asyncio.TimeoutError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _stream_from_upstream(self) -> None:
        conninfo = self.conf["primary_conninfo"]
        while not self._stopping:
            writer = None
            try:
                reader, writer = await asyncio.wait_for(
                    asyncio.open_connection(
                        conninfo["host"], int(conninfo["port"])), 5.0)
                req = {"op": "replicate", "from_lsn": self.wal.last_lsn,
                       "prefix_digest": self.wal.digest_to(
                           self.wal.last_lsn),
                       "standby_id": self.peer_id}
                writer.write((json.dumps(req) + "\n").encode())
                await writer.drain()
                hello = json.loads(await reader.readline())
                if not hello.get("ok"):
                    # divergence: our WAL is ahead of/inconsistent with
                    # upstream — a real standby would fail to stream;
                    # exit non-zero so the manager goes down its restore
                    # path (lib/postgresMgr.js:1363-1374)
                    sys.stderr.write("simpg: replication refused: %s\n"
                                     % hello.get("error"))
                    sys.stderr.flush()
                    os._exit(3)
                self._upstream_ok = True
                self._upstream_contact = time.time()
                while True:
                    line = await reader.readline()
                    if not line:
                        break
                    rec = json.loads(line)
                    self.wal.append(rec["value"], rec.get("ts"))
                    self.last_replay_ts = time.time()
                    self._upstream_contact = self.last_replay_ts
                    self._wake_repl_waiters()
                    ack = {"flush": self.wal.last_lsn}
                    writer.write((json.dumps(ack) + "\n").encode())
                    await writer.drain()
            except (OSError, ValueError, json.JSONDecodeError,
                    asyncio.TimeoutError):
                pass
            finally:
                # every exit — refused hello, broken stream, cancel —
                # must close the socket: before this finally each
                # reconnect iteration (and a live re-point's cancel)
                # leaked the previous connection's fd (mnt-lint:
                # cancel-unsafe-acquire)
                if writer is not None:
                    writer.close()
                # a cancelled ex-streamer (live upstream re-point) must
                # not clobber the link state its replacement owns
                if self._upstream_task is asyncio.current_task():
                    self._upstream_ok = False
            await asyncio.sleep(0.2)

    # ---- serving connections ----

    def _wake_repl_waiters(self) -> None:
        for ev in self._repl_waiters:
            ev.set()

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            line = await reader.readline()
            if not line:
                return
            req = json.loads(line)
            if req.get("op") == "replicate":
                await self._serve_replication(req, reader, writer)
                return
            # simple request/response session: first request already read
            while True:
                resp = await self._dispatch(req)
                writer.write((json.dumps(resp) + "\n").encode())
                await writer.drain()
                line = await reader.readline()
                if not line:
                    break
                req = json.loads(line)
        except asyncio.CancelledError:
            pass       # engine teardown cancels handler tasks
        except (ConnectionError, json.JSONDecodeError):
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _serve_replication(self, req: dict,
                                 reader: asyncio.StreamReader,
                                 writer: asyncio.StreamWriter) -> None:
        standby_id = req.get("standby_id", "?")
        from_lsn = int(req.get("from_lsn", 0))
        if from_lsn > self.wal.last_lsn:
            writer.write((json.dumps(
                {"ok": False,
                 "error": "requested start %s beyond local wal %s "
                          "(diverged)" % (lsn_str(from_lsn),
                                          lsn_str(self.wal.last_lsn))}
            ) + "\n").encode())
            await writer.drain()
            return
        digest = req.get("prefix_digest")
        if digest is not None and digest != self.wal.digest_to(from_lsn):
            # same LENGTH is not same HISTORY: an old primary killed
            # right after appending record N that the takeover sync
            # never saw rejoins with from_lsn == our last_lsn but a
            # conflicting record N — content divergence must refuse
            # the stream exactly like the beyond-wal case (PostgreSQL's
            # timeline check; docs/xlog-diverge.md)
            writer.write((json.dumps(
                {"ok": False,
                 "error": "wal prefix at %s does not match ours "
                          "(diverged)" % lsn_str(from_lsn)}
            ) + "\n").encode())
            await writer.drain()
            return
        writer.write((json.dumps({"ok": True}) + "\n").encode())
        await writer.drain()
        st = {"sent": from_lsn, "flush": from_lsn, "replay": from_lsn,
              "sync_state": "async"}
        self.downstreams[standby_id] = st

        async def read_acks():
            while True:
                line = await reader.readline()
                if not line:
                    return
                try:
                    ack = json.loads(line)
                except json.JSONDecodeError:
                    continue
                st["flush"] = max(st["flush"], int(ack.get("flush", 0)))
                st["replay"] = st["flush"]
                self._wake_repl_waiters()

        ack_task = asyncio.create_task(read_acks())
        try:
            cursor = from_lsn
            while True:
                if ack_task.done():
                    break   # standby hung up (EOF on the ack stream)
                recs = self.wal.get_from(cursor)
                for rec in recs:
                    writer.write((json.dumps(rec) + "\n").encode())
                    cursor = rec["lsn"]
                    st["sent"] = cursor
                    # drain PER RECORD: a standby replaying a deep
                    # backlog must exert backpressure here, not buffer
                    # the whole backlog in our transport (drain is a
                    # no-op while below the high-water mark)
                    await writer.drain()
                await writer.drain()
                # wait for new records; idle-poll timeout just loops
                ev = asyncio.Event()
                self._repl_waiters.append(ev)
                try:
                    if self.wal.last_lsn == cursor:
                        try:
                            await asyncio.wait_for(ev.wait(), 0.5)
                        except asyncio.TimeoutError:
                            pass
                finally:
                    self._repl_waiters.remove(ev)
        except (ConnectionError, asyncio.TimeoutError, OSError):
            pass
        finally:
            ack_task.cancel()
            try:
                await ack_task
            except asyncio.CancelledError:
                pass       # the cancel we just requested
            except Exception:
                pass       # ack reader died with the connection
            # a newer connection for the same standby may have replaced
            # our entry; never pop someone else's registration
            if self.downstreams.get(standby_id) is st:
                del self.downstreams[standby_id]

    def _fake_lag(self) -> float | None:
        try:
            return float((self.datadir / "fake_lag")
                         .read_text().strip())
        except (OSError, ValueError):
            return None

    async def _dispatch(self, req: dict) -> dict:
        op = req.get("op")
        slow = self.datadir / "fake_slow"
        if slow.exists():
            # gradual-degradation knob (fakepg parity): delay every
            # reply by this many seconds — ramping it produces the
            # latency-climb signature the health predictor fires on,
            # which the operator playbook's scripted test drives
            try:
                delay = await asyncio.to_thread(slow.read_text)
                await asyncio.sleep(float(delay.strip()))
            except (ValueError, OSError):
                pass
        if op == "health":
            # "select current_time" analogue
            return {"ok": True, "now": time.time()}
        if op == "status":
            fake_lag = self._fake_lag()
            repl = []
            syncs = self.sync_names()
            for sid, st in self.downstreams.items():
                if sid.endswith(":probe"):
                    continue   # boot probes are not real standbys
                repl.append({
                    "application_name": sid,
                    "state": "streaming",
                    "sent_lsn": lsn_str(st["sent"]),
                    "write_lsn": lsn_str(st["flush"]),
                    "flush_lsn": lsn_str(st["flush"]),
                    "replay_lsn": lsn_str(st["replay"]),
                    "sync_state": "sync" if sid in syncs else "async",
                })
            return {
                "ok": True,
                "in_recovery": self.in_recovery,
                "read_only": self.read_only,
                "xlog_location": lsn_str(self.wal.last_lsn),
                # the sim applies WAL synchronously: replay == receive
                "replay_location": lsn_str(self.wal.last_lsn),
                "replication": repl,
                # caught-up standbys report 0 however long the cluster
                # has been idle; a severed upstream link reports time
                # since last contact (the signal that actually predicts
                # trouble) — mirrors the receive==replay guard in the
                # real engine's lag query.  A fake_lag file (fakepg
                # parity) overrides it for degradation tests — only in
                # recovery: a real primary can never report replay lag
                "replay_lag_seconds": (
                    None if not self.in_recovery
                    else fake_lag if fake_lag is not None
                    else 0.0 if self._upstream_ok
                    else max(0.0, time.time() - (
                        self._upstream_contact or self._boot_ts))),
                "version": VERSION,
            }
        if op == "insert":
            if self.read_only:
                return {"ok": False,
                        "error": "cannot execute INSERT in a read-only "
                                 "transaction"}
            lsn = self.wal.append(req.get("value"))
            self._wake_repl_waiters()   # push-driven replication
            syncs = self.sync_names()
            if syncs:
                # synchronous_commit: wait for the sync standby to flush
                ok = await self._wait_sync_flush(syncs, lsn,
                                                 float(req.get(
                                                     "timeout", 10.0)))
                if not ok:
                    return {"ok": False,
                            "error": "canceling wait for synchronous "
                                     "replication (timeout)"}
            return {"ok": True, "lsn": lsn_str(lsn)}
        if op == "select":
            rows = [r["value"] for r in self.wal.records]
            try:
                limit = int(req.get("limit") or 0)
            except (TypeError, ValueError):
                limit = 0
            if limit > 0:
                # bounded tail read: constant reply cost however long
                # the WAL grows — what read-QPS benchmarks drive
                rows = rows[-limit:]
            return {"ok": True, "rows": rows}
        return {"ok": False, "error": "unknown op %r" % op}

    async def _wait_sync_flush(self, syncs: list[str], lsn: int,
                               timeout: float) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for sid in syncs:
                st = self.downstreams.get(sid)
                if st and st["flush"] >= lsn:
                    return True
            ev = asyncio.Event()
            self._repl_waiters.append(ev)
            try:
                await asyncio.wait_for(
                    ev.wait(), max(0.01, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                pass
            finally:
                self._repl_waiters.remove(ev)
        return False


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="simulated postgres")
    p.add_argument("-D", "--datadir", required=True)
    args = p.parse_args(argv)
    datadir = Path(args.datadir)
    if not (datadir / VERSION_FILE).exists():
        sys.stderr.write(
            'simpg: directory "%s" is not a database cluster directory\n'
            % datadir)
        sys.exit(1)
    server = SimPgServer(datadir)
    try:
        asyncio.run(server.run())
    except KeyboardInterrupt:
        pass


if __name__ == "__main__":
    main()
