"""Interactive driver for PostgresMgr — the manual testing REPL.

Reference parity: test/postgresMgrRepl.js (:62-109) — drive a peer's
database manager directly against its sitter config, without the state
machine: status / start (as primary) / standby URL / stop / xlog /
health / insert / select / quit.

Usage:  python -m manatee_tpu.pg.repl -f sitter.json
"""

from __future__ import annotations

import asyncio
import json
import sys

from manatee_tpu.daemons.common import parse_daemon_args
from manatee_tpu.shard import Shard
from manatee_tpu.utils.logutil import setup_logging
from manatee_tpu.utils.validation import load_json_config

HELP = """commands:
  status                  manager status
  start                   reconfigure as singleton primary
  standby URL             reconfigure as sync of the peer at pg URL
                          (e.g. sim://127.0.0.1:10002)
  none                    stop the database (role none)
  xlog                    current WAL position
  health                  one health probe
  insert VALUE            write a row (primary only)
  select                  read all rows
  quit
"""


async def repl(cfg: dict) -> None:
    shard = Shard(cfg)   # build managers; do NOT start the state machine
    pg = shard.pg
    await pg.start_manager()
    print("pg manager ready (%s); 'help' for commands" % pg.peer_id)
    loop = asyncio.get_running_loop()
    while True:
        line = await loop.run_in_executor(None, sys.stdin.readline)
        if not line:
            break
        parts = line.strip().split(None, 1)
        if not parts:
            continue
        cmd, arg = parts[0], (parts[1] if len(parts) > 1 else "")
        try:
            if cmd == "help":
                print(HELP)
            elif cmd == "status":
                print(json.dumps(pg.status(), indent=2))
            elif cmd == "start":
                pg.cfg["singleton"] = True
                await pg.reconfigure({"role": "primary",
                                      "upstream": None,
                                      "downstream": None})
                print("primary (singleton), writable")
            elif cmd == "standby":
                await pg.reconfigure({
                    "role": "sync",
                    "upstream": {"id": arg, "pgUrl": arg,
                                 "backupUrl": ""},
                    "downstream": None})
                print("standby of %s" % arg)
            elif cmd == "none":
                await pg.reconfigure({"role": "none"})
                print("stopped")
            elif cmd == "xlog":
                print(await pg.get_xlog_location())
            elif cmd == "health":
                ok = await pg.engine.health(pg.host, pg.port, 2.0)
                print("healthy" if ok else "UNHEALTHY")
            elif cmd == "insert":
                print(await pg._local_query(
                    {"op": "insert", "value": arg}))
            elif cmd == "select":
                print(await pg._local_query({"op": "select"}))
            elif cmd in ("quit", "exit"):
                break
            else:
                print("unknown command %r; 'help' for help" % cmd)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            print("error: %s" % e)
    await pg.close()


def main(argv=None) -> None:
    args = parse_daemon_args("PostgresMgr interactive driver", argv)
    setup_logging("pg-repl", args.verbose)
    cfg = load_json_config(args.config, None, name="sitter config")
    asyncio.run(repl(cfg))


if __name__ == "__main__":
    main()
