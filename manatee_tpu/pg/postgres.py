"""PostgresEngine — drives real postgres/initdb binaries.

Config-generation parity with the reference:

- postgresql.conf regenerated from a shipped template plus programmatic
  key rewrites (lib/postgresMgr.js:2282-2336, etc/postgresql.conf):
  wal_level=hot_standby, synchronous_commit=remote_write, fsync=on,
  full_page_writes=off, hot_standby=on;
- synchronous_standby_names quoted for >=9.6 (lib/postgresMgr.js:184-191);
- standby recovery config: recovery.conf with standby_mode=on +
  primary_conninfo for PG<12; standby.signal + primary_conninfo in
  postgresql.conf for PG>=12 (lib/postgresMgr.js:601-607, 2200-2260);
- WAL naming translations xlog/location vs wal/lsn by major version
  (lib/postgresMgr.js:139-161, 649-677);
- initdb run as the postgres OS user (lib/postgresMgr.js:1806-1987).

Queries go through psql(1) so no driver dependency is needed; the result
is normalized to the same structured dicts SimPgEngine returns.  This
engine requires real binaries and is exercised only on hosts that have
them (the dev image does not).

Hot-path queries ride a POOLED LONG-LIVED psql coprocess per database
(:class:`PsqlSession`): one spawn amortized over every probe tick and
catchup poll, instead of fork+exec+connect per statement — the dominant
cost of the takeover critical path on real engines (the PR 3 analyzer
attributes ~150ms per spawn on a loaded box).  Sessions are framed with
``\\echo`` sentinel markers carrying psql's ``:ERROR`` variable, spawn
on demand, and session failures fall back to the original one-shot
path — except a death AFTER a mutating statement was submitted, which
surfaces as PgError rather than risk double-execution — so the pool is
strictly an optimization (disable outright with
``MANATEE_PSQL_SESSION=0`` or the ``pgSessionPool`` sitter config key).
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import logging
import os
import re
from pathlib import Path

log = logging.getLogger("manatee.pg.engine")

from manatee_tpu.pg.engine import Engine, PgError, PgQueryTimeout, parse_pg_url
from manatee_tpu.utils import ConfFile, ExecError, run
from manatee_tpu.utils.confparser import quote_conf_value
from manatee_tpu.utils.pgversion import pg_strip_minor

DEFAULT_TEMPLATE = {
    "listen_addresses": "'0.0.0.0'",
    "wal_level": "hot_standby",
    "synchronous_commit": "remote_write",
    "fsync": "on",
    "full_page_writes": "off",
    "hot_standby": "on",
    "max_wal_senders": "10",
    "wal_keep_segments": "100",
}


_SCOPE_RE = re.compile(r"^(common|\d+(\.\d+)*)$")


def merge_overrides(overrides: dict | None, version: str) -> dict:
    """pg_overrides.json semantics (lib/postgresMgr.js:118-137, 527-560):
    tunables are merged by scope, least to most specific —
    ``common`` -> major (e.g. "9.6") -> full version (e.g. "9.6.3").
    A dict with NO scope-shaped keys at all is treated as common; a
    scoped dict contributes nothing for versions it does not mention."""
    if not overrides:
        return {}
    if not any(_SCOPE_RE.match(str(k)) for k in overrides):
        return dict(overrides)   # genuinely flat: all of it is 'common'
    out: dict = {}
    for scope in ("common", pg_strip_minor(version), version):
        out.update(overrides.get(scope) or {})
    return out


def resolve_versioned_paths(base_dir: str, version: str) -> dict:
    """Multi-version layout (resolveVersionedPaths,
    lib/postgresMgr.js:569-634): binaries and data live in per-version
    directories with a ``current`` symlink naming the active one:

        <base>/<version>/bin/...     e.g. /opt/postgresql/12.0/bin
        <base>/current -> <version>

    Returns {"bin": ..., "version_dir": ..., "current": ...}."""
    base = Path(base_dir)
    vdir = base / version
    return {
        "bin": str(vdir / "bin"),
        "version_dir": str(vdir),
        "current": str(base / "current"),
    }


def set_current_version(base_dir: str, version: str) -> None:
    """Repoint <base>/current at <version> atomically."""
    base = Path(base_dir)
    tmp = base / (".current-%d" % os.getpid())
    if tmp.is_symlink() or tmp.exists():
        tmp.unlink()
    os.symlink(version, tmp)
    os.replace(tmp, base / "current")


def wal_function_names(major: str) -> dict:
    """xlog/location (<10) vs wal/lsn (>=10) naming
    (lib/postgresMgr.js:139-161)."""
    if float(major.split(".")[0]) >= 10:
        return {
            "current": "pg_current_wal_lsn()",
            "receive": "pg_last_wal_receive_lsn()",
            "replay": "pg_last_wal_replay_lsn()",
            "replay_ts": "pg_last_xact_replay_timestamp()",
            "stat_sent": "sent_lsn",
            "stat_flush": "flush_lsn",
            "stat_write": "write_lsn",
            "stat_replay": "replay_lsn",
        }
    return {
        "current": "pg_current_xlog_location()",
        "receive": "pg_last_xlog_receive_location()",
        "replay": "pg_last_xlog_replay_location()",
        "replay_ts": "pg_last_xact_replay_timestamp()",
        "stat_sent": "sent_location",
        "stat_flush": "flush_location",
        "stat_write": "write_location",
        "stat_replay": "replay_location",
    }


class PsqlSessionDied(PgError):
    """The pooled psql coprocess died mid-exchange.  *submitted* says
    whether any statement of the batch had already been handed to the
    coprocess: if so, the server MAY have executed it, and replaying
    through the one-shot path could double-execute (a pg_promote that
    already promoted errors 'recovery is not in progress'; a probe
    INSERT lands twice) — so only UNsubmitted deaths are retried."""

    def __init__(self, msg: str, *, submitted: bool = False):
        super().__init__(msg)
        self.submitted = submitted


class PsqlSessionBusy(PgError):
    """The pooled session's lock stayed held past the caller's
    timeout (a slow statement ahead in the queue, e.g. a bounded
    pg_promote wait); callers fall back to the one-shot path so the
    pool never makes a probe SLOWER than the pre-pool behavior."""


class PsqlSession:
    """One long-lived ``psql`` coprocess bound to a single database.

    Statements are written to the coprocess's stdin one at a time,
    each followed by ``\\echo <marker> :ERROR`` — psql prints the
    marker line (with true/false for the statement's outcome) after
    the statement's own output, which frames the reply stream without
    any protocol support from the server.  The marker carries a
    per-session random token plus a sequence number, so no plausible
    result row can collide with it (same reasoning as the one-shot
    batch path's section marker).

    Crash semantics: a coprocess that exits (server restart, kill -9,
    connection loss) surfaces as :class:`PsqlSessionDied`; the session
    discards it and respawns on the next call, and the ENGINE falls
    back to the one-shot path for the query in flight when that is
    safe (read-only batches, or nothing submitted yet) — a session
    failure costs one extra spawn, never a wrong answer."""

    def __init__(self, engine: "PostgresEngine", host: str, port: int):
        self.engine = engine
        self.host = host
        self.port = port
        self._proc: asyncio.subprocess.Process | None = None
        self._lock = asyncio.Lock()
        self._err_task: asyncio.Task | None = None
        self._err_buf: list[str] = []
        self._token = os.urandom(8).hex()
        self._seq = 0
        self.spawns = 0          # exposed for the reuse tests

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.returncode is None

    async def run(self, sqls: list[str], timeout: float) -> list[str]:
        """Run *sqls* in order over the pooled coprocess; returns one
        output string per statement.  Raises PgError for a statement
        the server rejected, PgQueryTimeout when the exchange exceeds
        *timeout* (the coprocess is then in an unknown state and is
        killed), PsqlSessionDied when the coprocess itself died."""
        # the session serializes callers: the LOCK WAIT counts against
        # the caller's timeout too, or a slow statement ahead in the
        # queue (pg_promote's promoteWait) would delay a health
        # probe's verdict far past its configured bound
        acquired = False
        try:
            try:
                await asyncio.wait_for(self._lock.acquire(), timeout)
                acquired = True
            except asyncio.TimeoutError:
                raise PsqlSessionBusy(
                    "psql session busy for %ss (statement ahead in "
                    "the queue still running)" % timeout) from None
            if not self.alive:
                try:
                    await self._spawn(timeout)
                except asyncio.CancelledError:
                    # a cancel mid-spawn/handshake would otherwise
                    # leave a LIVE coprocess whose unread handshake
                    # reply desyncs the next caller's framing
                    await self._close_locked()
                    raise
            try:
                return await asyncio.wait_for(self._run_locked(sqls),
                                              timeout)
            except asyncio.TimeoutError:
                # mid-statement: replies could arrive for a statement
                # we gave up on — the session is out of sync, kill it
                await self._close_locked()
                raise PgQueryTimeout(
                    "psql session query timed out after %ss"
                    % timeout) from None
            except PsqlSessionDied:
                await self._close_locked()
                raise
            except PgError:
                raise
            except asyncio.CancelledError:
                # the exchange was cut mid-reply: same out-of-sync
                # hazard as the timeout
                await self._close_locked()
                raise
            except OSError as e:
                # transport-level failure mid-exchange (reset pipe,
                # reader error): classify as a died session so the
                # engine retries one-shot — a raw OSError would
                # escape Engine.health()'s PgError filter and kill
                # the caller's loop outright
                await self._close_locked()
                raise PsqlSessionDied("psql session I/O failed: %s"
                                      % e, submitted=True) from None
        finally:
            if acquired:
                self._lock.release()

    async def close(self) -> None:
        async with self._lock:
            await self._close_locked()

    # -- internals --

    async def _spawn(self, timeout: float) -> None:
        argv = [self.engine._cmd("psql"), "-h", self.host,
                "-p", str(self.port), "-U", self.engine.pg_user,
                "-d", "postgres", "-qAt", "-F", "\x1f"]
        env = dict(os.environ)
        env["PGCONNECT_TIMEOUT"] = str(max(1, int(timeout)))
        self._proc = await asyncio.create_subprocess_exec(
            *argv, stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.PIPE, env=env)
        self.spawns += 1
        self._err_buf = []
        self._err_task = asyncio.create_task(
            self._drain_stderr(self._proc))
        # handshake: a bare marker proves the connection is up before
        # the first statement is committed to this transport (psql
        # connects at startup and exits on failure)
        try:
            await asyncio.wait_for(self._exchange_marker_only(), timeout)
        except (asyncio.TimeoutError, PsqlSessionDied, OSError) as e:
            # OSError: the coprocess connected-and-exited and the
            # handshake write hit the closed pipe — the same
            # server-down shape as an EOF, and it must surface as
            # PgError (below), never escape raw into the health loop
            err = self._take_stderr() or str(e)
            await self._close_locked()
            if "timeout" in err:
                raise PgQueryTimeout(err) from None
            raise PgError(err.strip() or "psql session failed to start") \
                from None

    async def _drain_stderr(self, proc) -> None:
        try:
            while True:
                line = await proc.stderr.readline()
                if not line:
                    return
                self._err_buf.append(line.decode("utf-8", "replace"))
        except asyncio.CancelledError:
            raise
        except Exception:
            return

    def _take_stderr(self) -> str:
        text, self._err_buf = "".join(self._err_buf), []
        return text

    async def _await_stderr(self) -> str:
        """The error text lands on a DIFFERENT pipe than the marker
        that reported it; give the drain task a brief window to
        deliver it before giving up on the detail."""
        for _ in range(3):
            if self._err_buf:
                break
            await asyncio.sleep(0.01)
        return self._take_stderr()

    def _mark(self) -> str:
        self._seq += 1
        return "\x1e--psql-%s-%d--" % (self._token, self._seq)

    async def _exchange_marker_only(self) -> None:
        mark = self._mark()
        self._proc.stdin.write(("\\echo %s\n" % mark).encode())
        await self._proc.stdin.drain()
        while True:
            raw = await self._proc.stdout.readline()
            if not raw:
                raise PsqlSessionDied("psql session exited during "
                                      "handshake")
            if raw.decode("utf-8", "replace").rstrip("\n") == mark:
                return

    async def _run_locked(self, sqls: list[str]) -> list[str]:
        out: list[str] = []
        for sql in sqls:
            # scope stderr to THIS statement: real psql emits NOTICEs/
            # WARNINGs for successful statements too, and a long-lived
            # session would otherwise attribute the whole backlog to
            # the next failure (and a stale 'timeout' line would
            # misclassify it as PgQueryTimeout)
            self._err_buf.clear()
            mark = self._mark()
            # the fake (and the protocol) are line-framed; engine
            # statements are single-line by construction, so the
            # collapse is a no-op in practice
            stmt = " ".join(sql.splitlines())
            try:
                self._proc.stdin.write(
                    ("%s\n\\echo %s :ERROR\n" % (stmt, mark)).encode())
                await self._proc.stdin.drain()
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                # the write MAY have reached the coprocess before it
                # died: conservatively submitted (no replay)
                raise PsqlSessionDied("psql session died: %s" % e,
                                      submitted=True) from None
            lines: list[str] = []
            failed = False
            while True:
                raw = await self._proc.stdout.readline()
                if not raw:
                    raise PsqlSessionDied(
                        "psql session died mid-statement: %s"
                        % (await self._await_stderr()).strip(),
                        submitted=True)
                line = raw.decode("utf-8", "replace")
                line = line[:-1] if line.endswith("\n") else line
                if line.startswith(mark):
                    failed = line[len(mark):].strip() == "true"
                    break
                lines.append(line)
            if failed:
                err = (await self._await_stderr()).strip()
                if "timeout" in err:
                    raise PgQueryTimeout(err)
                raise PgError(err or "psql statement failed")
            out.append("\n".join(lines))
        return out

    async def _close_locked(self) -> None:
        proc, self._proc = self._proc, None
        task, self._err_task = self._err_task, None
        if proc is not None and proc.returncode is None:
            with contextlib.suppress(ProcessLookupError):
                proc.kill()
        if proc is not None:
            with contextlib.suppress(Exception):
                await proc.wait()
        if task is not None:
            task.cancel()
            with contextlib.suppress(asyncio.CancelledError, Exception):
                await task


class PostgresEngine(Engine):
    scheme = "tcp"

    def __init__(self, *, pg_bin_dir: str = "", version: str = "12.0",
                 pg_user: str = "postgres", use_sudo: bool = True,
                 template: dict | None = None,
                 template_file: str | None = None,
                 hba_file: str | None = None,
                 overrides: dict | None = None,
                 session_pool: bool | None = None):
        """*template_file*: a shipped postgresql.conf to regenerate from
        (etc/postgresql.conf; the reference always rewrites starting
        from its shipped per-major template, lib/postgresMgr.js:
        2278-2336) — takes precedence over *template*/DEFAULT_TEMPLATE.
        *hba_file*: a shipped pg_hba.conf installed into the datadir
        after initdb (lib/postgresMgr.js:1954-1956)."""
        self.bin = Path(pg_bin_dir) if pg_bin_dir else None
        self.version = version
        self.major = pg_strip_minor(version)
        self.pg_user = pg_user
        self.use_sudo = use_sudo
        if template_file:
            self.template = dict(ConfFile.read(template_file).items())
        else:
            self.template = dict(template or DEFAULT_TEMPLATE)
        self.hba_file = hba_file
        # primary_conninfo is reloadable from PostgreSQL 13: a running
        # standby re-points its walreceiver without a restart
        self.reloadable_upstream = float(self.major) >= 13
        # pg_promote() exists from PostgreSQL 12: takeover without a
        # database restart (promote_in_place below)
        self.promotable_in_place = float(self.major) >= 12
        # pg_overrides.json-style tunables merged over the template by
        # scope: common -> major -> full version
        # (lib/postgresMgr.js:118-137, 527-560)
        self.template.update(merge_overrides(overrides, version))
        # pooled psql coprocess per (host, port): statements on the
        # probe/catchup hot path stop paying fork+exec+connect.  The
        # \echo :ERROR framing needs psql >= 12; default on, killable
        # with MANATEE_PSQL_SESSION=0 (or session_pool=False)
        if session_pool is None:
            session_pool = os.environ.get(
                "MANATEE_PSQL_SESSION", "1") != "0"
        self.session_pool = bool(session_pool) \
            and float(self.major.split(".")[0]) >= 12
        self._sessions: dict[tuple[str, int], PsqlSession] = {}

    def _cmd(self, name: str) -> str:
        return str(self.bin / name) if self.bin else name

    # -- local cluster management --

    def is_initialized(self, datadir: str) -> bool:
        return (Path(datadir) / "PG_VERSION").exists()

    async def initdb(self, datadir: str) -> None:
        argv = [self._cmd("initdb"), "-D", str(datadir), "-E", "UTF8"]
        if self.use_sudo:
            argv = ["sudo", "-u", self.pg_user] + argv
        try:
            await run(argv, timeout=300)
        except ExecError as e:
            raise PgError("initdb failed: %s" % e) from None
        await self.install_hba(datadir)

    async def install_hba(self, datadir: str) -> None:
        """Replace the initdb-generated access-control file with the
        shipped one (lib/postgresMgr.js:1954-1956 'installing access
        control file').  Under use_sudo the datadir belongs to the
        postgres user (mode 0700), so the copy must run as that user
        too; otherwise an atomic write-and-rename, like replacefile
        (lib/common.js:22-60)."""
        if not self.hba_file:
            return
        dst = Path(datadir) / "pg_hba.conf"
        if self.use_sudo:
            try:
                await run(["sudo", "-u", self.pg_user, "cp",
                           str(self.hba_file), str(dst)], timeout=30)
            except ExecError as e:
                raise PgError("installing pg_hba.conf failed: %s"
                              % e) from None
            return
        def _copy() -> None:        # worker thread: off the loop
            tmp = dst.with_name(dst.name + ".tmp")
            tmp.write_text(Path(self.hba_file).read_text())
            tmp.replace(dst)

        try:
            await asyncio.to_thread(_copy)
        except OSError as e:
            raise PgError("installing pg_hba.conf failed: %s" % e) from None

    def start_argv(self, datadir: str) -> list[str]:
        return [self._cmd("postgres"), "-D", str(datadir)]

    def write_config(self, datadir: str, *, host: str, port: int,
                     peer_id: str, read_only: bool,
                     sync_standby_ids: list[str],
                     upstream: dict | None) -> None:
        d = Path(datadir)
        conf = ConfFile(dict(self.template))
        conf.set("port", str(port))
        conf.set("default_transaction_read_only",
                 "on" if read_only else "off")
        if sync_standby_ids:
            names = ",".join('"%s"' % s for s in sync_standby_ids)
            # >= 9.6 takes the num-sync form (lib/postgresMgr.js:184-191)
            if float(self.major) >= 9.6:
                conf.set("synchronous_standby_names",
                         quote_conf_value("1 (%s)" % names))
            else:
                conf.set("synchronous_standby_names",
                         quote_conf_value(names))
        else:
            conf.delete("synchronous_standby_names")
        # wal_keep_segments was removed in PG 13 (wal_keep_size replaces it)
        if int(self.major.split(".")[0]) >= 13:
            if "wal_keep_segments" in conf:
                conf.delete("wal_keep_segments")
                conf.set("wal_keep_size", "'1600MB'")

        is_modern = int(self.major.split(".")[0]) >= 12
        recovery = d / "recovery.conf"
        signal = d / "standby.signal"
        if upstream is None:
            # primary: drop all recovery configuration
            # (lib/postgresMgr.js:1145-1152)
            for f in (recovery, signal):
                if f.exists():
                    f.unlink()
        else:
            _s, uhost, uport = parse_pg_url(upstream["pgUrl"])
            conninfo = ("host=%s port=%d user=%s application_name=%s"
                        % (uhost, uport, self.pg_user, peer_id))
            if is_modern:
                conf.set("primary_conninfo", quote_conf_value(conninfo))
                signal.touch()
                if recovery.exists():
                    recovery.unlink()
            else:
                rc = ConfFile({
                    "standby_mode": "'on'",
                    "primary_conninfo": quote_conf_value(conninfo),
                })
                rc.write(recovery)
        conf.write(d / "postgresql.conf")

    # real walreceivers retry a refused stream forever (no exit): the
    # manager's re-point watchdog polls upstream_attached instead
    lingering_repoint_failure = True

    async def promote_in_place(self, host: str, port: int,
                               timeout: float = 30.0) -> None:
        """SELECT pg_promote(wait := true): exit recovery on the
        RUNNING server (PostgreSQL 12+) — the restart-free takeover.
        Raises PgError if the server does not report promotion."""
        out = (await self._psql(
            host, port,
            "SELECT pg_promote(true, %d);" % max(1, int(timeout)),
            timeout + 5.0)).strip()
        if out != "t":
            raise PgError("pg_promote did not complete: %r" % out)

    async def upstream_attached(self, host: str, port: int,
                                upstream: dict,
                                timeout: float = 5.0) -> bool:
        """pg_stat_wal_receiver: streaming, and from the expected
        host/port?  Empty result = no walreceiver at all."""
        _s, uhost, uport = parse_pg_url(upstream["pgUrl"])
        out = (await self._psql(
            host, port,
            "SELECT status || '\x1f' || conninfo "
            "FROM pg_stat_wal_receiver;", timeout,
            replay_safe=True)).strip()
        if not out:
            return False
        status, _sep, conninfo = out.partition("\x1f")
        tokens = conninfo.split()
        return (status == "streaming"
                and "host=%s" % uhost in tokens
                and "port=%d" % uport in tokens)

    # -- queries via psql --

    def _session(self, host: str, port: int) -> PsqlSession:
        key = (host, port)
        s = self._sessions.get(key)
        if s is None:
            s = self._sessions[key] = PsqlSession(self, host, port)
        return s

    async def aclose(self) -> None:
        """Kill every pooled psql coprocess (manager/harness
        teardown)."""
        sessions, self._sessions = list(self._sessions.values()), {}
        for s in sessions:
            await s.close()

    async def _exec(self, host: str, port: int, sqls: list[str],
                    timeout: float, *, replay_safe: bool = False
                    ) -> list[str]:
        """Statement batch over the pooled session when enabled,
        one-shot psql otherwise.  A BUSY session (lock held past the
        timeout by a slow statement) and a session that died before
        any statement of this batch was submitted fall back to the
        one-shot path — the pool never makes a query slower or less
        available than the pre-pool behavior.  A death AFTER
        submission falls back only for *replay_safe* (read-only)
        batches: the server may already have executed a submitted
        statement, and replaying a mutating one could double-execute
        (pg_promote errors 'recovery is not in progress'; the probe
        INSERT lands twice) — those surface as PgError and the
        caller's own retry logic decides."""
        if self.session_pool:
            try:
                return await self._session(host, port).run(sqls, timeout)
            except PsqlSessionDied as e:
                if e.submitted and not replay_safe:
                    raise PgError(str(e)) from None
                log.debug("psql session to %s:%d died (%s); one-shot "
                          "fallback", host, port, e)
            except PsqlSessionBusy as e:
                log.debug("psql session to %s:%d busy (%s); one-shot "
                          "fallback", host, port, e)
        if len(sqls) == 1:
            return [await self._psql_oneshot(host, port, sqls[0],
                                             timeout)]
        return await self._psql_sections_oneshot(host, port, sqls,
                                                 timeout)

    async def _psql(self, host: str, port: int, sql: str,
                    timeout: float, *, replay_safe: bool = False
                    ) -> str:
        return (await self._exec(host, port, [sql], timeout,
                                 replay_safe=replay_safe))[0]

    async def _psql_sections(self, host: str, port: int,
                             sqls: list[str], timeout: float, *,
                             replay_safe: bool = False) -> list[str]:
        return await self._exec(host, port, sqls, timeout,
                                replay_safe=replay_safe)

    async def _psql_oneshot(self, host: str, port: int, sql: str,
                            timeout: float) -> str:
        argv = [self._cmd("psql"), "-h", host, "-p", str(port),
                "-U", self.pg_user, "-d", "postgres",
                "-At", "-F", "\x1f", "-c", sql]
        env = dict(os.environ)
        env["PGCONNECT_TIMEOUT"] = str(int(timeout))
        try:
            res = await run(argv, timeout=timeout, env=env)
        except ExecError as e:
            if "timeout" in e.result.stderr:
                raise PgQueryTimeout(str(e)) from None
            raise PgError(e.result.stderr.strip() or str(e)) from None
        return res.stdout

    # one psql process per SQL statement is too slow for a status op
    # that needs five of them: the health loops of a few peers plus a
    # `verify` sweep would spend the whole box spawning interpreters
    # (observed as alternating status-timeout ticks under chaos with
    # engine=postgres).  psql >= 9.6 accepts repeated -c, one
    # connection, results printed in order — so a multi-statement op
    # costs ONE spawn, with marker rows delimiting the sections.
    # The marker carries a fixed random token so no plausible result
    # row (e.g. an adversarial application_name of "\x1e") can
    # collide with it and shift the section split (ADVICE r4)
    _SECTION_RS = "\x1e--manatee-section-9f4b2c17ab5e--"

    async def _psql_sections_oneshot(self, host: str, port: int,
                                     sqls: list[str], timeout: float
                                     ) -> list[str]:
        if float(self.major) < 9.6:
            # pre-9.6 psql has no repeated -c: sequential fallback
            return [await self._psql_oneshot(host, port, s, timeout)
                    for s in sqls]
        # ON_ERROR_STOP: real psql's default is to CONTINUE past a
        # failed -c and still exit 0 — a mid-batch error would leave an
        # empty section that parses as wrong values (in_recovery False
        # on a standby).  With it, psql exits nonzero at the first
        # error, surfacing as PgError exactly like the single-statement
        # path (the fake psql stops at the first error natively).
        argv = [self._cmd("psql"), "-h", host, "-p", str(port),
                "-U", self.pg_user, "-d", "postgres",
                "-At", "-F", "\x1f", "-v", "ON_ERROR_STOP=1"]
        for i, s in enumerate(sqls):
            if i:
                argv += ["-c", "SELECT '%s';" % self._SECTION_RS]
            argv += ["-c", s]
        env = dict(os.environ)
        env["PGCONNECT_TIMEOUT"] = str(int(timeout))
        try:
            res = await run(argv, timeout=timeout, env=env)
        except ExecError as e:
            if "timeout" in e.result.stderr:
                raise PgQueryTimeout(str(e)) from None
            raise PgError(e.result.stderr.strip() or str(e)) from None
        # NB: split on "\n" explicitly — str.splitlines() treats the
        # \x1e record separator itself as a line boundary and would
        # swallow the markers
        out = res.stdout[:-1] if res.stdout.endswith("\n") else res.stdout
        sections: list[list[str]] = [[]]
        for line in out.split("\n"):
            if line == self._SECTION_RS:
                sections.append([])
            else:
                sections[-1].append(line)
        if len(sections) != len(sqls):
            raise PgError("psql returned %d sections for %d statements"
                          % (len(sections), len(sqls)))
        return ["\n".join(s) for s in sections]

    async def query(self, host: str, port: int, op: dict,
                    timeout: float = 5.0) -> dict:
        kind = op.get("op")
        w = wal_function_names(self.major)
        if kind == "health":
            await self._psql(host, port, "SELECT current_time;",
                             timeout, replay_safe=True)
            return {"ok": True}
        if kind == "status":
            # the whole op is ONE psql spawn (see _psql_sections);
            # role-dependent statements branch in SQL via CASE so the
            # batch needs no round trip to learn the role first
            in_rec_sql = "SELECT pg_is_in_recovery();"
            xlog_sql = ("SELECT CASE WHEN pg_is_in_recovery() "
                        "THEN %s ELSE %s END;"
                        % (w["receive"], w["current"]))
            # the REPLAY position, separately: receive_lsn is NULL for
            # the whole local-pg_wal replay a restarting standby does
            # before its walreceiver ever starts, so "is recovery
            # making progress" (the re-point watchdog's question) must
            # read replay, not receive
            replay_sql = ("SELECT CASE WHEN pg_is_in_recovery() "
                          "THEN %s ELSE %s END;"
                          % (w["replay"], w["current"]))
            # a fully-caught-up standby reports 0 regardless of how
            # long the cluster has been idle: bare
            # now() - pg_last_xact_replay_timestamp() reads as
            # ever-growing "lag" on a quiescent cluster (the
            # reference documents this caveat; we fix it).  The 0
            # short-circuit additionally requires a LIVE walreceiver
            # — a severed replication link must read as growing lag,
            # not as caught-up (receive goes static after the link
            # dies, so receive==replay alone would mask it).
            if float(self.major) >= 9.6:
                live = "EXISTS (SELECT 1 FROM pg_stat_wal_receiver)"
                lag_expr = ("CASE WHEN %s AND %s = %s THEN 0 "
                            "ELSE EXTRACT(EPOCH FROM (now() - %s)) END"
                            % (live, w["receive"], w["replay"],
                               w["replay_ts"]))
            else:
                # no pg_stat_wal_receiver before 9.6: keep the
                # reference's raw form (with its documented caveat)
                lag_expr = ("EXTRACT(EPOCH FROM (now() - %s))"
                            % w["replay_ts"])
            lag_sql = ("SELECT CASE WHEN pg_is_in_recovery() "
                       "THEN (%s)::text ELSE NULL END;" % lag_expr)
            repl_sql = ("SELECT application_name, state, %s, %s, %s, "
                        "%s, sync_state FROM pg_stat_replication;"
                        % (w["stat_sent"], w["stat_write"],
                           w["stat_flush"], w["stat_replay"]))
            ro_sql = "SHOW default_transaction_read_only;"
            sec = await self._psql_sections(
                host, port,
                [in_rec_sql, xlog_sql, replay_sql, lag_sql, repl_sql,
                 ro_sql],
                timeout, replay_safe=True)
            in_rec = sec[0].strip() == "t"
            xlog = sec[1].strip()
            replay = sec[2].strip()
            lag = sec[3].strip()
            lag_s = float(lag) if in_rec and lag else None
            rows = sec[4]
            repl = []
            for line in rows.splitlines():
                if not line.strip():
                    continue
                f = line.split("\x1f")
                repl.append({
                    "application_name": f[0], "state": f[1],
                    "sent_lsn": f[2], "write_lsn": f[3],
                    "flush_lsn": f[4], "replay_lsn": f[5],
                    "sync_state": f[6],
                })
            ro = sec[5].strip() == "on"
            return {"ok": True, "in_recovery": in_rec,
                    "read_only": in_rec or ro,
                    "xlog_location": xlog or "0/0000000",
                    "replay_location": replay or "0/0000000",
                    "replication": repl, "replay_lag_seconds": lag_s,
                    "version": self.version}
        if kind == "insert":
            val = json.dumps(op.get("value"))
            await self._psql(
                host, port,
                "CREATE TABLE IF NOT EXISTS manatee_probe (v text); "
                "INSERT INTO manatee_probe VALUES (%s);"
                % quote_conf_value(val), timeout)
            return {"ok": True}
        if kind == "select":
            out = await self._psql(
                host, port, "SELECT v FROM manatee_probe;", timeout,
                replay_safe=True)
            return {"ok": True,
                    "rows": [json.loads(x) for x in out.splitlines() if x]}
        raise PgError("unknown op %r" % kind)
