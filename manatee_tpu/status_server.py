"""Status HTTP server — operator/automation introspection per peer.

Reference parity: lib/statusServer.js — restify server on
``postgresPort + 1`` with:

- ``GET /``        route list (:62-75)
- ``GET /ping``    200/503 from the PG health state (:78-97)
- ``GET /state``   the state machine's debugState() (:100-109)
- ``GET /restore`` the restore client's current job (:111-121)

Beyond parity (the reference predates both conventions; its operators
scrape bunyan logs):

- ``GET /metrics`` Prometheus text format: the state-derived gauges
  below plus the whole process-wide obs registry (transition counters,
  failover/reconfigure/RPC latency histograms, probe flips, ...);
- ``GET /events``  this peer's ring-buffer event journal
  (``?since=SEQ&limit=N``) — the per-peer feed `manatee-adm events`
  merges into the shard timeline;
- ``GET /spans``   this peer's completed-span ring
  (``?since=SEQ&limit=N&trace=ID``) plus its open spans — the per-peer
  feed `manatee-adm trace` reassembles into the cross-peer tree;
- ``GET /profile`` folded-stack output of the sampling profiler and
  ``GET /tasks`` the live asyncio task census (``obs/profile.py``) —
  mounted, like every introspection route above, through the shared
  table in ``daemons/common.attach_obs_routes``;
- ``GET/POST/DELETE /faults`` the sitter process's live fault-injection
  surface (`manatee_tpu.faults`): list armed rules + the failpoint
  catalog, arm by spec, disarm — what `manatee-adm fault` talks to.

Fleet mode (``manatee-sitter --fleet``, docs/user-guide.md): ONE
status server fronts every shard the process runs.  Per-shard routes
live under ``/shards/<name>/...`` (``ping``/``state``/``restore``),
``GET /shards`` lists them, the legacy single-shard paths keep working
(bound to the first shard, so probes written for one-shard sitters
stay valid), and ``/metrics`` carries a ``shard`` label on every
state-derived gauge.  ``/events``/``/spans``/``/faults`` stay
process-wide — journal, spans, and fault registry are per process.
"""

from __future__ import annotations

import logging

from aiohttp import web

from manatee_tpu.daemons.common import attach_obs_routes
from manatee_tpu.obs import get_journal, get_registry

log = logging.getLogger("manatee.status")


class _ShardEntry:
    """One shard's introspection surfaces (fleet mode runs several)."""

    __slots__ = ("name", "pg_mgr", "state_machine", "restore_client")

    def __init__(self, name, pg_mgr, state_machine, restore_client):
        self.name = name
        self.pg_mgr = pg_mgr
        self.state_machine = state_machine
        self.restore_client = restore_client


class StatusServer:
    def __init__(self, *, host: str = "0.0.0.0", port: int,
                 pg_mgr=None, state_machine=None, restore_client=None,
                 shards: list[tuple] | None = None):
        """Single-shard form: pass *pg_mgr*/*state_machine*/
        *restore_client*.  Fleet form: pass *shards* as an ordered list
        of ``(name, pg_mgr, state_machine, restore_client)`` tuples —
        the first entry also answers the legacy single-shard routes."""
        self.host = host
        self.port = port
        if shards is not None:
            if not shards:
                raise ValueError("fleet status server needs >= 1 shard")
            self._entries = [_ShardEntry(*s) for s in shards]
            self._fleet = True
        else:
            self._entries = [_ShardEntry(None, pg_mgr, state_machine,
                                         restore_client)]
            self._fleet = False
        first = self._entries[0]
        # legacy accessors (tests and embedders read these)
        self.pg_mgr = first.pg_mgr
        self.state_machine = first.state_machine
        self.restore_client = first.restore_client
        self._runner: web.AppRunner | None = None
        app = web.Application()
        app.router.add_get("/", self._routes)
        app.router.add_get("/ping", self._ping)
        app.router.add_get("/state", self._state)
        app.router.add_get("/restore", self._restore)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/shards", self._shards)
        app.router.add_get("/shards/{shard}/ping", self._ping)
        app.router.add_get("/shards/{shard}/state", self._state)
        app.router.add_get("/shards/{shard}/restore", self._restore)
        # /events, /spans, /history, /alerts, /profile, /tasks, /faults
        # — the shared table every listener mounts (daemons/common.py)
        self._obs_routes = attach_obs_routes(app)
        self._app = app

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        log.info("status server on %s:%d%s", self.host, self.port,
                 " (%d shards)" % len(self._entries)
                 if self._fleet else "")

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    def _entry(self, req: web.Request) -> _ShardEntry | None:
        """The shard a request addresses: ``/shards/<name>/...`` routes
        name one explicitly; the legacy paths mean the first (in
        single-shard mode: only) entry.  None = unknown shard name."""
        name = req.match_info.get("shard")
        if name is None:
            return self._entries[0]
        for e in self._entries:
            if e.name == name:
                return e
        return None

    async def _routes(self, _req: web.Request) -> web.Response:
        routes = ["/ping", "/state", "/restore", "/metrics",
                  "/shards"] + self._obs_routes
        if self._fleet:
            routes += ["/shards/%s/%s" % (e.name, leaf)
                       for e in self._entries
                       for leaf in ("ping", "state", "restore")]
        return web.json_response(routes)

    async def _shards(self, _req: web.Request) -> web.Response:
        # a single-shard sitter's entry is unnamed (no /shards/<name>/
        # routes resolve): report an empty list, not [null] — callers
        # fall back to the legacy routes on fleet=false
        return web.json_response({
            "fleet": self._fleet,
            "shards": [e.name for e in self._entries
                       if e.name is not None],
        })

    async def _ping(self, req: web.Request) -> web.Response:
        e = self._entry(req)
        if e is None:
            return web.json_response({"error": "no such shard"},
                                     status=404)
        healthy = bool(e.pg_mgr and e.pg_mgr.online)
        body = {"healthy": healthy,
                "pg": e.pg_mgr.status() if e.pg_mgr else None}
        if e.name is not None:
            body["shard"] = e.name
        return web.json_response(body, status=200 if healthy else 503)

    async def _state(self, req: web.Request) -> web.Response:
        e = self._entry(req)
        if e is None:
            return web.json_response({"error": "no such shard"},
                                     status=404)
        if e.state_machine is None:
            return web.json_response({"error": "no state machine"},
                                     status=503)
        body = e.state_machine.debug_state()
        if e.pg_mgr is not None:
            # failure-prediction surface (health/telemetry.py): operators
            # and adm warnings read the early-warning score from here
            body["healthScore"] = e.pg_mgr.health_score
            body["healthTelemetry"] = e.pg_mgr.telemetry.last_tick()
        if e.name is not None:
            body["shard"] = e.name
        return web.json_response(body)

    async def _restore(self, req: web.Request) -> web.Response:
        e = self._entry(req)
        if e is None:
            return web.json_response({"error": "no such shard"},
                                     status=404)
        job = (e.restore_client.current_job
               if e.restore_client else None)
        body = {"restore": job}
        if e.name is not None:
            body["shard"] = e.name
        return web.json_response(body)

    async def _metrics(self, _req: web.Request) -> web.Response:
        """Prometheus text exposition: state-derived gauges (labeled
        per shard in fleet mode) + the whole process-wide obs
        registry."""
        from manatee_tpu.obs.process import refresh_process_metrics
        from manatee_tpu.utils.prom import MetricsBuilder, label_str

        refresh_process_metrics()
        b = MetricsBuilder("manatee")
        # family name -> (type, help, [(labelstr, value), ...]) —
        # collected across shards so each family is emitted once
        fams: dict[str, tuple[str, str, list]] = {}

        def metric(name, mtype, help_, value, **labels):
            fam = fams.setdefault(name, (mtype, help_, []))
            fam[2].append((label_str(**labels), value))

        for e in self._entries:
            lb = {} if e.name is None else {"shard": e.name}
            pg = e.pg_mgr
            if pg is not None:
                metric("pg_online", "gauge",
                       "1 when the local database answers health probes",
                       1 if pg.online else 0, **lb)
                # health_score{peer} and replication_lag_seconds{peer}
                # come from the registry (pg/manager._record_telemetry)
                # — emitting a state-derived copy here would duplicate
                # the family in one exposition
                tick = pg.telemetry.last_tick()
                if tick:
                    # normalized feature vector of the last probe
                    # (telemetry.normalize_tick order)
                    names = ("latency", "timed_out", "lag", "wal_stall",
                             "reconnects")
                    for n, v in zip(names, tick):
                        metric("probe_feature", "gauge",
                               "normalized health-probe features, "
                               "last tick", "%.4f" % v,
                               feature=n, **lb)
            sm = e.state_machine
            if sm is not None:
                dbg = sm.debug_state()
                st = dbg.get("clusterState") or {}
                if "generation" in st:
                    metric("generation", "gauge",
                           "durable cluster-state generation",
                           st["generation"], **lb)
                role = dbg.get("role") or "none"
                for r in ("primary", "sync", "async", "deposed",
                          "none"):
                    metric("role", "gauge", "current durable role",
                           1 if r == role else 0, role=r, **lb)
                metric("frozen", "gauge",
                       "1 when the cluster is frozen (no automatic "
                       "transitions)", 1 if st.get("freeze") else 0,
                       **lb)
                metric("cluster_peers", "gauge",
                       "peers in the durable topology incl. deposed",
                       (1 if st.get("primary") else 0)
                       + (1 if st.get("sync") else 0)
                       + len(st.get("async") or [])
                       + len(st.get("deposed") or []), **lb)
            job = (e.restore_client.current_job
                   if e.restore_client else None)
            if job is not None:
                metric("restore_size_bytes", "gauge",
                       "size of the in-flight restore stream",
                       int(job.get("size") or 0), **lb)
                metric("restore_done_bytes", "gauge",
                       "bytes received by the in-flight restore",
                       int(job.get("completed") or 0), **lb)
        for name, (mtype, help_, samples) in fams.items():
            b.metric(name, mtype, help_, samples)
        if self._fleet:
            b.metric("fleet_shards", "gauge",
                     "shards this fleet sitter process runs",
                     len(self._entries))
        b.metric("journal_events", "gauge",
                 "events buffered in the in-memory journal ring",
                 len(get_journal()))
        # the process-wide registry: state_transitions_total, the
        # failover/reconfigure/probe/RPC histograms, restore counters,
        # the coord_connections/coord_sessions/coord_mux_handles
        # amortization gauges — everything components registered via
        # manatee_tpu.obs
        get_registry().render_into(b)
        return web.Response(text=b.render(),
                            content_type="text/plain")
