"""Status HTTP server — operator/automation introspection per peer.

Reference parity: lib/statusServer.js — restify server on
``postgresPort + 1`` with:

- ``GET /``        route list (:62-75)
- ``GET /ping``    200/503 from the PG health state (:78-97)
- ``GET /state``   the state machine's debugState() (:100-109)
- ``GET /restore`` the restore client's current job (:111-121)
"""

from __future__ import annotations

import logging

from aiohttp import web

log = logging.getLogger("manatee.status")


class StatusServer:
    def __init__(self, *, host: str = "0.0.0.0", port: int,
                 pg_mgr=None, state_machine=None, restore_client=None):
        self.host = host
        self.port = port
        self.pg_mgr = pg_mgr
        self.state_machine = state_machine
        self.restore_client = restore_client
        self._runner: web.AppRunner | None = None
        app = web.Application()
        app.router.add_get("/", self._routes)
        app.router.add_get("/ping", self._ping)
        app.router.add_get("/state", self._state)
        app.router.add_get("/restore", self._restore)
        self._app = app

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        log.info("status server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _routes(self, _req: web.Request) -> web.Response:
        return web.json_response(["/ping", "/state", "/restore"])

    async def _ping(self, _req: web.Request) -> web.Response:
        healthy = bool(self.pg_mgr and self.pg_mgr.online)
        body = {"healthy": healthy,
                "pg": self.pg_mgr.status() if self.pg_mgr else None}
        return web.json_response(body, status=200 if healthy else 503)

    async def _state(self, _req: web.Request) -> web.Response:
        if self.state_machine is None:
            return web.json_response({"error": "no state machine"},
                                     status=503)
        body = self.state_machine.debug_state()
        if self.pg_mgr is not None:
            # failure-prediction surface (health/telemetry.py): operators
            # and adm warnings read the early-warning score from here
            body["healthScore"] = self.pg_mgr.health_score
            body["healthTelemetry"] = self.pg_mgr.telemetry.last_tick()
        return web.json_response(body)

    async def _restore(self, _req: web.Request) -> web.Response:
        job = (self.restore_client.current_job
               if self.restore_client else None)
        if job is None:
            return web.json_response({"restore": None})
        return web.json_response({"restore": job})
