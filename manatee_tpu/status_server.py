"""Status HTTP server — operator/automation introspection per peer.

Reference parity: lib/statusServer.js — restify server on
``postgresPort + 1`` with:

- ``GET /``        route list (:62-75)
- ``GET /ping``    200/503 from the PG health state (:78-97)
- ``GET /state``   the state machine's debugState() (:100-109)
- ``GET /restore`` the restore client's current job (:111-121)

Beyond parity (the reference predates both conventions; its operators
scrape bunyan logs):

- ``GET /metrics`` Prometheus text format: the state-derived gauges
  below plus the whole process-wide obs registry (transition counters,
  failover/reconfigure/RPC latency histograms, probe flips, ...);
- ``GET /events``  this peer's ring-buffer event journal
  (``?since=SEQ&limit=N``) — the per-peer feed `manatee-adm events`
  merges into the shard timeline;
- ``GET /spans``   this peer's completed-span ring
  (``?since=SEQ&limit=N&trace=ID``) plus its open spans — the per-peer
  feed `manatee-adm trace` reassembles into the cross-peer tree;
- ``GET/POST/DELETE /faults`` the sitter process's live fault-injection
  surface (`manatee_tpu.faults`): list armed rules + the failpoint
  catalog, arm by spec, disarm — what `manatee-adm fault` talks to.
"""

from __future__ import annotations

import logging
import time

from aiohttp import web

from manatee_tpu import faults
from manatee_tpu.obs import get_journal, get_registry, get_span_store
from manatee_tpu.obs.spans import parse_page_query, spans_http_reply

log = logging.getLogger("manatee.status")


class StatusServer:
    def __init__(self, *, host: str = "0.0.0.0", port: int,
                 pg_mgr=None, state_machine=None, restore_client=None):
        self.host = host
        self.port = port
        self.pg_mgr = pg_mgr
        self.state_machine = state_machine
        self.restore_client = restore_client
        self._runner: web.AppRunner | None = None
        app = web.Application()
        app.router.add_get("/", self._routes)
        app.router.add_get("/ping", self._ping)
        app.router.add_get("/state", self._state)
        app.router.add_get("/restore", self._restore)
        app.router.add_get("/metrics", self._metrics)
        app.router.add_get("/events", self._events)
        app.router.add_get("/spans", self._spans)
        faults.attach_http(app)
        self._app = app

    async def start(self) -> None:
        self._runner = web.AppRunner(self._app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = self._runner.addresses[0][1]
        log.info("status server on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._runner:
            await self._runner.cleanup()

    async def _routes(self, _req: web.Request) -> web.Response:
        return web.json_response(["/ping", "/state", "/restore",
                                  "/metrics", "/events", "/spans",
                                  "/faults"])

    async def _ping(self, _req: web.Request) -> web.Response:
        healthy = bool(self.pg_mgr and self.pg_mgr.online)
        body = {"healthy": healthy,
                "pg": self.pg_mgr.status() if self.pg_mgr else None}
        return web.json_response(body, status=200 if healthy else 503)

    async def _state(self, _req: web.Request) -> web.Response:
        if self.state_machine is None:
            return web.json_response({"error": "no state machine"},
                                     status=503)
        body = self.state_machine.debug_state()
        if self.pg_mgr is not None:
            # failure-prediction surface (health/telemetry.py): operators
            # and adm warnings read the early-warning score from here
            body["healthScore"] = self.pg_mgr.health_score
            body["healthTelemetry"] = self.pg_mgr.telemetry.last_tick()
        return web.json_response(body)

    async def _restore(self, _req: web.Request) -> web.Response:
        job = (self.restore_client.current_job
               if self.restore_client else None)
        if job is None:
            return web.json_response({"restore": None})
        return web.json_response({"restore": job})

    async def _events(self, req: web.Request) -> web.Response:
        """The peer's event journal, oldest first.  ?since=SEQ returns
        only events after that per-process sequence number (incremental
        tailing); ?limit=N keeps the newest N of what remains."""
        journal = get_journal()
        try:
            since, limit = parse_page_query(req.query)
        except ValueError:
            return web.json_response(
                {"error": "since/limit must be integers"}, status=400,
                content_type="application/json")
        return web.json_response({
            "peer": journal.peer,
            "now": round(time.time(), 3),
            "events": journal.events(since=since, limit=limit),
        }, content_type="application/json")

    async def _spans(self, req: web.Request) -> web.Response:
        """The peer's completed spans, oldest first, plus its open
        spans; ?trace=ID filters to one trace's records."""
        body, status = spans_http_reply(get_span_store(), req.query)
        return web.json_response(body, status=status,
                                 content_type="application/json")

    async def _metrics(self, _req: web.Request) -> web.Response:
        """Prometheus text exposition: state-derived gauges + the whole
        process-wide obs registry."""
        from manatee_tpu.utils.prom import MetricsBuilder

        b = MetricsBuilder("manatee")
        metric = b.metric
        pg = self.pg_mgr
        if pg is not None:
            metric("pg_online", "gauge",
                   "1 when the local database answers health probes",
                   1 if pg.online else 0)
            if pg.health_score is not None:
                metric("health_score", "gauge",
                       "learned failure-probability score in [0,1]",
                       "%.4f" % pg.health_score)
            tick = pg.telemetry.last_tick()
            if tick:
                # normalized feature vector of the last probe
                # (telemetry.normalize_tick order)
                names = ("latency", "timed_out", "lag", "wal_stall",
                         "reconnects")
                from manatee_tpu.utils.prom import label_str
                metric("probe_feature", "gauge",
                       "normalized health-probe features, last tick",
                       [(label_str(feature=n), "%.4f" % v)
                        for n, v in zip(names, tick)])
        sm = self.state_machine
        if sm is not None:
            dbg = sm.debug_state()
            st = dbg.get("clusterState") or {}
            if "generation" in st:
                metric("generation", "gauge",
                       "durable cluster-state generation",
                       st["generation"])
            role = dbg.get("role") or "none"
            metric("role", "gauge", "current durable role",
                   [('{role="%s"}' % r, 1 if r == role else 0)
                    for r in ("primary", "sync", "async", "deposed",
                              "none")])
            metric("frozen", "gauge",
                   "1 when the cluster is frozen (no automatic "
                   "transitions)", 1 if st.get("freeze") else 0)
            metric("cluster_peers", "gauge",
                   "peers in the durable topology incl. deposed",
                   (1 if st.get("primary") else 0)
                   + (1 if st.get("sync") else 0)
                   + len(st.get("async") or [])
                   + len(st.get("deposed") or []))
        job = (self.restore_client.current_job
               if self.restore_client else None)
        if job is not None:
            metric("restore_size_bytes", "gauge",
                   "size of the in-flight restore stream",
                   int(job.get("size") or 0))
            metric("restore_done_bytes", "gauge",
                   "bytes received by the in-flight restore",
                   int(job.get("completed") or 0))
        metric("journal_events", "gauge",
               "events buffered in the in-memory journal ring",
               len(get_journal()))
        # the process-wide registry: state_transitions_total, the
        # failover/reconfigure/probe/RPC histograms, restore counters —
        # everything components registered via manatee_tpu.obs
        get_registry().render_into(b)
        return web.Response(text=b.render(),
                            content_type="text/plain")
