"""Cluster state machine (reference: the external `manatee-state-machine`
git dependency, package.json:31 — rebuilt here as a first-class component).
"""

from manatee_tpu.state.types import (
    ClusterState,
    PeerInfo,
    compare_lsn,
    peer_info_from_active,
    role_of,
)
from manatee_tpu.state.machine import PeerStateMachine

__all__ = [
    "ClusterState",
    "PeerInfo",
    "compare_lsn",
    "peer_info_from_active",
    "role_of",
    "PeerStateMachine",
]
