"""JAX-accelerated model checking: vectorized frontier exploration.

The Python explorer (modelcheck.py) rebuilds every BFS child by
replaying its whole action sequence through the real async
``PeerStateMachine`` — one state per Python iteration, so its depth
bound is CPU wall clock.  This module encodes the checker world as a
fixed-shape int32 vector and evaluates transitions + safety invariants
for the *whole frontier* in one vmapped/shard_map'd device step across
the host-platform mesh (ROADMAP item 4).

The encoding is **bijective with the semantic-state quotient** shared
with the Python engine (canon.py): every field of the canonical digest
dict — and nothing else — has a slot in the vector, so deduplicating on
raw vector bytes is exactly deduplicating on the canonical digest.
That bijection is what makes the differential-oracle contract
checkable: matched-depth runs of the two engines must agree exactly on
the reachable semantic-state set and on every violation verdict
(tests/test_mc_array.py), and any divergence replays the offending
action sequence through the Python world for a minimized trace.

Why exact agreement is even possible: in the checker harness
specifically (takeover_grace=0, the worker task never started, MCPg
reconfigures never fail, no one-node-write-mode, fixed promote-expiry
constants, digests taken only at action boundaries after tasks settle)
the machine's observable semantics reduce to a finite pure function
over this fixed-shape state.  Every branch of that function is mirrored
here as a pure jnp kernel; docs/modelcheck.md walks the encoding.

Engine shape:

* per-action **transition kernels** (peer evaluation incl. the full
  primary/sync duty ladder, view refresh, crash, rejoin, xlog catch-up,
  operator promote/freeze, partition/heal) — pure jnp, int32 in/out;
* vectorized **safety predicates** (generation monotonicity,
  single-writable-primary, sync-only takeover, xlog gate) as a bitmask
  over canon.CATEGORIES;
* a **liveness kernel** mirroring check_liveness: catch-up, the fair
  schedule run to fixpoint with a lax.while_loop, then the convergence
  predicates (dead-primary-replaced, sync-appointed, role/chain
  consistency);
* a **frontier driver**: vmap over a fixed-size chunk, shard_map across
  the device mesh, device-side dedup via sorted semantic-hash keys,
  host-side exact refill from the seen-set (hash collisions therefore
  cannot drop states — the device pass only *reduces* host work).

Deliberate-weakening flags (:class:`Mutations`) mirror the mutation
self-tests of tests/test_model_check.py in both engines, pinning that
vectorization never trades away detection.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import sys
import time
from dataclasses import dataclass

import numpy as np

from manatee_tpu.state import canon
from manatee_tpu.state.modelcheck import (
    CONFIGS,
    FUTURE_EXPIRY,
    PAST_EXPIRY,
    MCConfig,
    MCResult,
    _fast_sleep,
    _replay,
)

# ---------------------------------------------------------------------------
# role / field codes

NONE = -1

# role_note / role_of codes
R_NONE, R_PRIM, R_SYNC, R_ASYNC, R_DEPOSED = 0, 1, 2, 3, 4
_NOTE_STR = {R_NONE: None, R_PRIM: "primary", R_SYNC: "sync",
             R_ASYNC: "async", R_DEPOSED: "deposed"}

# pg-target role codes
T_NONE, T_PRIM, T_SYNC, T_ASYNC = 0, 1, 2, 3
_T_STR = {T_NONE: "none", T_PRIM: "primary", T_SYNC: "sync",
          T_ASYNC: "async"}

# promote-request role codes
PR_SYNC, PR_ASYNC = 0, 1

# the freeze payload the explorer's freeze action writes (modelcheck.py)
FREEZE_DICT = {"date": "2026-01-01T00:00:00Z", "reason": "modelcheck"}

_BIT = canon.CATEGORY_BIT


class EncodingError(Exception):
    """A world outside the fixed-shape encoding's domain — by
    construction unreachable from the explorer's configs; raised loudly
    rather than silently mis-encoded."""


# ---------------------------------------------------------------------------
# layout


class Layout:
    """Offsets of the fixed-shape int32 encoding for P peers.

    state block (SB, one for the durable store + one per-peer view):
      gen, initWal, primary, sync, async[P]+n, deposed[P]+n, frozen,
      promote{has, role, id, asyncIndex, gen, expired}
    globals: kills, rejoins, store actives[P]+n, store SB
    per peer: alive, part, xlog, ver_current, evaled, role_note,
      target{has, role, up, down, deposed}, view actives[P]+n, view SB
    """

    def __init__(self, P: int):
        self.P = P
        # -- state block (relative offsets) --
        self.SB_GEN = 0
        self.SB_IW = 1
        self.SB_PRIM = 2
        self.SB_SYNC = 3
        self.SB_ASY = 4
        self.SB_ASY_N = 4 + P
        self.SB_DEP = 5 + P
        self.SB_DEP_N = 5 + 2 * P
        self.SB_FROZEN = 6 + 2 * P
        self.SB_P_HAS = 7 + 2 * P
        self.SB_P_ROLE = 8 + 2 * P
        self.SB_P_ID = 9 + 2 * P
        self.SB_P_IDX = 10 + 2 * P
        self.SB_P_GEN = 11 + 2 * P
        self.SB_P_EXP = 12 + 2 * P
        self.SB_SIZE = 13 + 2 * P
        # -- globals --
        self.G_KILLS = 0
        self.G_REJOINS = 1
        self.G_ACT = 2
        self.G_ACT_N = 2 + P
        self.G_SB = 3 + P
        self.GLOB = 3 + P + self.SB_SIZE
        # -- per-peer block --
        self.PB_ALIVE = 0
        self.PB_PART = 1
        self.PB_X = 2
        self.PB_VERCUR = 3
        self.PB_EVALED = 4
        self.PB_NOTE = 5
        self.PB_T_HAS = 6
        self.PB_T_ROLE = 7
        self.PB_T_UP = 8
        self.PB_T_DOWN = 9
        self.PB_T_DEP = 10
        self.PB_VACT = 11
        self.PB_VACT_N = 11 + P
        self.PB_VSB = 12 + P
        self.PB_SIZE = 12 + P + self.SB_SIZE
        self.SIZE = self.GLOB + P * self.PB_SIZE

    def pbase(self, i: int) -> int:
        return self.GLOB + i * self.PB_SIZE


# ---------------------------------------------------------------------------
# identity helpers (must match MCPeer exactly)


def _ident(name: str) -> str:
    return "%s:5432:12345" % name


def _info(name: str) -> dict:
    return {
        "id": _ident(name), "zoneId": name, "ip": name,
        "pgUrl": "tcp://postgres@%s:5432/postgres" % name,
        "backupUrl": "http://%s:12345" % name,
    }


def _lsn_int(lsn: str) -> int:
    hi, lo = lsn.strip().split("/")
    if int(hi, 16) != 0:
        raise EncodingError("lsn high word nonzero: %r" % lsn)
    return int(lo, 16)


def _lsn_str(v: int) -> str:
    return "0/%07X" % v


_STATE_KEYS = {"generation", "initWal", "primary", "sync", "async",
               "deposed", "freeze", "promote", "trace", "span", "hlc"}
_PROMOTE_KEYS = {"id", "role", "asyncIndex", "generation", "expireTime"}


# ---------------------------------------------------------------------------
# encode


def _idx_of_info(info, idx_map, what: str) -> int:
    if info is None:
        return NONE
    if not isinstance(info, dict) or "id" not in info:
        raise EncodingError("%s is not a PeerInfo: %r" % (what, info))
    if info["id"] not in idx_map:
        raise EncodingError("%s unknown peer %r" % (what, info["id"]))
    i = idx_map[info["id"]]
    return i


def _check_info(info, names, what: str) -> None:
    """The encoding regenerates PeerInfo dicts from the peer index, so
    any non-canonical info dict would silently decode differently."""
    name = names[_idx_of_info(info, {_ident(n): i for i, n
                                     in enumerate(names)}, what)]
    if info != _info(name):
        raise EncodingError("%s non-canonical PeerInfo: %r" % (what, info))


def _encode_sb(st: dict, names, out, base: int) -> None:
    idx_map = {_ident(n): i for i, n in enumerate(names)}
    P = len(names)
    if st is None:
        raise EncodingError("state block is None (pre-bootstrap world)")
    extra = set(st) - _STATE_KEYS
    if extra:
        raise EncodingError("unsupported state keys: %r" % extra)
    for k in ("generation", "initWal", "primary", "sync", "async",
              "deposed"):
        if k not in st:
            raise EncodingError("state missing %r" % k)
    out[base + 0] = st["generation"]
    out[base + 1] = _lsn_int(st["initWal"])
    _check_info(st["primary"], names, "primary")
    out[base + 2] = idx_map[st["primary"]["id"]]
    if st["sync"] is not None:
        _check_info(st["sync"], names, "sync")
        out[base + 3] = idx_map[st["sync"]["id"]]
    else:
        out[base + 3] = NONE
    L = Layout(P)
    asy = st["async"] or []
    dep = st["deposed"] or []
    if len(asy) > P or len(dep) > P:
        raise EncodingError("async/deposed list longer than P")
    for k, a in enumerate(asy):
        _check_info(a, names, "async[%d]" % k)
        out[base + L.SB_ASY + k] = idx_map[a["id"]]
    for k in range(len(asy), P):
        out[base + L.SB_ASY + k] = NONE
    out[base + L.SB_ASY_N] = len(asy)
    for k, d in enumerate(dep):
        _check_info(d, names, "deposed[%d]" % k)
        out[base + L.SB_DEP + k] = idx_map[d["id"]]
    for k in range(len(dep), P):
        out[base + L.SB_DEP + k] = NONE
    out[base + L.SB_DEP_N] = len(dep)
    if "freeze" in st:
        if st["freeze"] != FREEZE_DICT:
            raise EncodingError("non-canonical freeze: %r" % st["freeze"])
        out[base + L.SB_FROZEN] = 1
    else:
        out[base + L.SB_FROZEN] = 0
    pr = st.get("promote")
    if "promote" in st:
        if pr is None or set(pr) - _PROMOTE_KEYS:
            raise EncodingError("non-canonical promote: %r" % pr)
        out[base + L.SB_P_HAS] = 1
        if pr["role"] == "sync":
            out[base + L.SB_P_ROLE] = PR_SYNC
        elif pr["role"] == "async":
            out[base + L.SB_P_ROLE] = PR_ASYNC
        else:
            raise EncodingError("promote role %r" % pr["role"])
        if pr["id"] not in idx_map:
            raise EncodingError("promote id %r" % pr["id"])
        out[base + L.SB_P_ID] = idx_map[pr["id"]]
        out[base + L.SB_P_IDX] = pr.get("asyncIndex", NONE)
        out[base + L.SB_P_GEN] = pr["generation"]
        if pr["expireTime"] == FUTURE_EXPIRY:
            out[base + L.SB_P_EXP] = 0
        elif pr["expireTime"] == PAST_EXPIRY:
            out[base + L.SB_P_EXP] = 1
        else:
            raise EncodingError("promote expiry %r" % pr["expireTime"])
    else:
        out[base + L.SB_P_HAS] = 0
        out[base + L.SB_P_ROLE] = NONE
        out[base + L.SB_P_ID] = NONE
        out[base + L.SB_P_IDX] = NONE
        out[base + L.SB_P_GEN] = 0
        out[base + L.SB_P_EXP] = 0


def _decode_sb(vec, names, base: int) -> dict:
    P = len(names)
    L = Layout(P)
    st = {
        "generation": int(vec[base + L.SB_GEN]),
        "initWal": _lsn_str(int(vec[base + L.SB_IW])),
        "primary": _info(names[int(vec[base + L.SB_PRIM])]),
        "sync": (None if vec[base + L.SB_SYNC] == NONE
                 else _info(names[int(vec[base + L.SB_SYNC])])),
        "async": [_info(names[int(vec[base + L.SB_ASY + k])])
                  for k in range(int(vec[base + L.SB_ASY_N]))],
        "deposed": [_info(names[int(vec[base + L.SB_DEP + k])])
                    for k in range(int(vec[base + L.SB_DEP_N]))],
    }
    if vec[base + L.SB_FROZEN]:
        st["freeze"] = dict(FREEZE_DICT)
    if vec[base + L.SB_P_HAS]:
        pr = {
            "id": _ident(names[int(vec[base + L.SB_P_ID])]),
            "role": ("sync" if vec[base + L.SB_P_ROLE] == PR_SYNC
                     else "async"),
            "generation": int(vec[base + L.SB_P_GEN]),
            "expireTime": (PAST_EXPIRY if vec[base + L.SB_P_EXP]
                           else FUTURE_EXPIRY),
        }
        if vec[base + L.SB_P_IDX] != NONE:
            pr["asyncIndex"] = int(vec[base + L.SB_P_IDX])
        st["promote"] = pr
    return st


def _encode_cfg(cfg, idx_map, out, pbase: int, L: Layout) -> None:
    """Encode a stripped pg-target dict into the 5 target slots."""
    b = pbase
    if cfg is None:
        out[b + L.PB_T_HAS] = 0
        out[b + L.PB_T_ROLE] = T_NONE
        out[b + L.PB_T_UP] = NONE
        out[b + L.PB_T_DOWN] = NONE
        out[b + L.PB_T_DEP] = 0
        return
    role = cfg.get("role")
    out[b + L.PB_T_HAS] = 1
    if role == "none":
        extra = set(cfg) - {"role", "deposed"}
        if extra:
            raise EncodingError("target extra keys %r" % extra)
        out[b + L.PB_T_ROLE] = T_NONE
        out[b + L.PB_T_UP] = NONE
        out[b + L.PB_T_DOWN] = NONE
        out[b + L.PB_T_DEP] = 1 if cfg.get("deposed") else 0
        if "deposed" in cfg and cfg["deposed"] is not True:
            raise EncodingError("target deposed %r" % cfg["deposed"])
        return
    if role not in ("primary", "sync", "async"):
        raise EncodingError("target role %r" % role)
    extra = set(cfg) - {"role", "upstream", "downstream"}
    if extra:
        raise EncodingError("target extra keys %r" % extra)
    if "upstream" not in cfg or "downstream" not in cfg:
        raise EncodingError("target missing upstream/downstream")
    out[b + L.PB_T_ROLE] = {"primary": T_PRIM, "sync": T_SYNC,
                            "async": T_ASYNC}[role]
    up, down = cfg["upstream"], cfg["downstream"]
    out[b + L.PB_T_UP] = (NONE if up is None else idx_map[up["id"]])
    out[b + L.PB_T_DOWN] = (NONE if down is None else idx_map[down["id"]])
    out[b + L.PB_T_DEP] = 0


def _decode_cfg(vec, names, pbase: int, L: Layout):
    b = pbase
    if not vec[b + L.PB_T_HAS]:
        return None
    role = int(vec[b + L.PB_T_ROLE])
    if role == T_NONE:
        cfg = {"role": "none"}
        if vec[b + L.PB_T_DEP]:
            cfg["deposed"] = True
        return cfg
    up = int(vec[b + L.PB_T_UP])
    down = int(vec[b + L.PB_T_DOWN])
    return {
        "role": _T_STR[role],
        "upstream": None if up == NONE else _info(names[up]),
        "downstream": None if down == NONE else _info(names[down]),
    }


def encode_world(world, config: MCConfig) -> np.ndarray:
    """Encode a (booted, settled) Python checker World.  Raises
    EncodingError on anything outside the encoding's domain — including
    a pg target/applied mismatch, which the settle discipline makes
    impossible at action boundaries (the invariant the single target
    slot relies on)."""
    names = list(config.peers)
    idx_map = {_ident(n): i for i, n in enumerate(names)}
    P = len(names)
    L = Layout(P)
    out = np.zeros(L.SIZE, dtype=np.int32)
    out[L.G_KILLS] = world.kills
    out[L.G_REJOINS] = world.rejoins
    acts = world.store.actives
    if len(acts) > P:
        raise EncodingError("store actives longer than P")
    for k, a in enumerate(acts):
        if a["id"] not in idx_map:
            raise EncodingError("unknown active %r" % a["id"])
        out[L.G_ACT + k] = idx_map[a["id"]]
    for k in range(len(acts), P):
        out[L.G_ACT + k] = NONE
    out[L.G_ACT_N] = len(acts)
    _encode_sb(world.store.state, names, out, L.G_SB)

    if set(world.peers) != set(names):
        raise EncodingError("peer set mismatch")
    for i, name in enumerate(names):
        p = world.peers[name]
        b = L.pbase(i)
        out[b + L.PB_ALIVE] = 1 if p.alive else 0
        out[b + L.PB_PART] = 1 if p.partitioned else 0
        out[b + L.PB_X] = _lsn_int(p.pg.xlog)
        out[b + L.PB_VERCUR] = (
            1 if p.zk.cluster_state_version == world.store.version else 0)
        out[b + L.PB_EVALED] = 1 if p.eval_epoch >= p.view_epoch else 0
        note = p.sm._notified_role
        for code, s in _NOTE_STR.items():
            if s == note:
                out[b + L.PB_NOTE] = code
                break
        else:
            raise EncodingError("role_note %r" % note)
        tgt = p.sm._strip_cfg(p.sm._pg_target)
        app = p.sm._strip_cfg(p.sm._pg_applied)
        if tgt != app:
            raise EncodingError(
                "pg target %r != applied %r on %s" % (tgt, app, name))
        _encode_cfg(tgt, idx_map, out, b, L)
        va = p.zk.active
        if len(va) > P:
            raise EncodingError("view actives longer than P")
        for k, a in enumerate(va):
            if a["id"] not in idx_map:
                raise EncodingError("unknown view active %r" % a["id"])
            out[b + L.PB_VACT + k] = idx_map[a["id"]]
        for k in range(len(va), P):
            out[b + L.PB_VACT + k] = NONE
        out[b + L.PB_VACT_N] = len(va)
        if p.zk.cluster_state is None:
            raise EncodingError("peer %s view is None" % name)
        _encode_sb(p.zk.cluster_state, names, out, b + L.PB_VSB)
    return out


def decode_canon(vec, config: MCConfig) -> dict:
    """Decode a state vector back into the exact canonical dict
    canon.world_canon builds for the equivalent Python world — the
    other half of the bijectivity contract."""
    names = list(config.peers)
    P = len(names)
    L = Layout(P)
    s_act = [_ident(names[int(vec[L.G_ACT + k])])
             for k in range(int(vec[L.G_ACT_N]))]
    peers = {}
    for name in sorted(names):
        i = names.index(name)
        b = L.pbase(i)
        v_act = [_ident(names[int(vec[b + L.PB_VACT + k])])
                 for k in range(int(vec[b + L.PB_VACT_N]))]
        cfg = _decode_cfg(vec, names, b, L)
        peers[name] = {
            "alive": bool(vec[b + L.PB_ALIVE]),
            "part": bool(vec[b + L.PB_PART]),
            "xlog": _lsn_str(int(vec[b + L.PB_X])),
            "ver_current": bool(vec[b + L.PB_VERCUR]),
            "actives_current": v_act == s_act,
            "evaled_current": bool(vec[b + L.PB_EVALED]),
            "view": _decode_sb(vec, names, b + L.PB_VSB),
            "view_actives": v_act,
            "target": cfg,
            "applied": cfg,
            "role_note": _NOTE_STR[int(vec[b + L.PB_NOTE])],
        }
    return {
        "state": _decode_sb(vec, names, L.G_SB),
        "actives": s_act,
        "kills": int(vec[L.G_KILLS]),
        "rejoins": int(vec[L.G_REJOINS]),
        "peers": peers,
    }


def digest_vec(vec, config: MCConfig) -> str:
    return canon.digest_of(decode_canon(vec, config))


# ---------------------------------------------------------------------------
# jnp kernels
#
# All kernels take and return a single (SIZE,) int32 vector; the driver
# vmaps them over the frontier.  Peer/slot indices are Python ints
# (static), so all addressing is static slices; only content-dependent
# gathers (e.g. async[promote.asyncIndex]) are dynamic.  Config budgets
# and mutation flags arrive as one traced knobs array so the compiled
# step is shared across configs of the same peer count.

import jax                                                  # noqa: E402
import jax.numpy as jnp                                     # noqa: E402
from jax import lax                                         # noqa: E402

# knobs array layout (traced scalars)
K_MAX_KILLS, K_MAX_REJOINS, K_PROMOTE, K_FREEZE, K_PARTITION, \
    K_MUT_XLOG, K_MUT_FREEZE, K_MUT_GENBUMP, K_MUT_DEPOSED = range(9)
KNOBS = 9


def make_knobs(config: MCConfig, mutations=None) -> np.ndarray:
    m = mutations or Mutations()
    return np.array([
        config.max_kills, config.max_rejoins,
        int(config.allow_promote), int(config.allow_freeze),
        int(config.allow_partition),
        int(m.disable_xlog_guard), int(m.ignore_freeze),
        int(m.skip_gen_bump), int(m.deposed_keeps_primary),
    ], dtype=np.int32)


@dataclass(frozen=True)
class Mutations:
    """Deliberate rule-weakenings, mirrored in both engines.

    Each flag corresponds to one monkeypatch of the Python machine (see
    mutation_patches) and one traced branch in the kernels, so the
    regression corpus can pin that BOTH engines flag the same seeded
    bug with the same category."""
    disable_xlog_guard: bool = False    # sync takeover skips the lsn gate
    ignore_freeze: bool = False         # duties act on a frozen cluster
    skip_gen_bump: bool = False         # takeover keeps the generation
    deposed_keeps_primary: bool = False  # deposed peer ignores deposition

    def any(self) -> bool:
        return (self.disable_xlog_guard or self.ignore_freeze
                or self.skip_gen_bump or self.deposed_keeps_primary)


def _mask_tail(arr, n):
    P = arr.shape[0]
    return jnp.where(jnp.arange(P) < n, arr, NONE)


def _compact(vals, keep):
    """Stable-compact: kept entries first in original order, tail NONE;
    returns (vals', n)."""
    P = vals.shape[0]
    pos = jnp.arange(P)
    order = jnp.argsort(jnp.where(keep, pos, P + pos))
    n = keep.sum()
    out = jnp.where(pos < n, vals[order], NONE)
    return out, n


def _members(ids, n):
    """(P,) bool: peer j appears in ids[:n]."""
    P = ids.shape[0]
    pos = jnp.arange(P)
    valid = pos < n
    return ((ids[None, :] == pos[:, None]) & valid[None, :]).any(axis=1)


def _index_of(ids, n, j):
    """Position of peer j in ids[:n], or NONE."""
    eq = (ids == j) & (jnp.arange(ids.shape[0]) < n)
    return jnp.where(eq.any(), jnp.argmax(eq), NONE)


def _rd_sb(L, v, base):
    P = L.P
    return {
        "gen": v[base + L.SB_GEN], "iw": v[base + L.SB_IW],
        "prim": v[base + L.SB_PRIM], "sync": v[base + L.SB_SYNC],
        "asy": v[base + L.SB_ASY:base + L.SB_ASY + P],
        "asy_n": v[base + L.SB_ASY_N],
        "dep": v[base + L.SB_DEP:base + L.SB_DEP + P],
        "dep_n": v[base + L.SB_DEP_N],
        "frozen": v[base + L.SB_FROZEN],
        "p_has": v[base + L.SB_P_HAS], "p_role": v[base + L.SB_P_ROLE],
        "p_id": v[base + L.SB_P_ID], "p_idx": v[base + L.SB_P_IDX],
        "p_gen": v[base + L.SB_P_GEN], "p_exp": v[base + L.SB_P_EXP],
    }


def _pack_sb(L, d):
    """Pack a state-block dict, enforcing the canonical encoding
    (NONE-padded tails, zeroed promote fields when absent) so that
    equal semantic states are equal byte-for-byte."""
    has = d["p_has"]
    one = lambda x: jnp.asarray(x, jnp.int32).reshape(1)  # noqa: E731
    return jnp.concatenate([
        one(d["gen"]), one(d["iw"]), one(d["prim"]), one(d["sync"]),
        _mask_tail(d["asy"], d["asy_n"]), one(d["asy_n"]),
        _mask_tail(d["dep"], d["dep_n"]), one(d["dep_n"]),
        one(d["frozen"]),
        one(has), one(jnp.where(has, d["p_role"], NONE)),
        one(jnp.where(has, d["p_id"], NONE)),
        one(jnp.where(has, d["p_idx"], NONE)),
        one(jnp.where(has, d["p_gen"], 0)),
        one(jnp.where(has, d["p_exp"], 0)),
    ]).astype(jnp.int32)


def _wr_sb(L, v, base, d):
    return v.at[base:base + L.SB_SIZE].set(_pack_sb(L, d))


def _sb_no_promote(d):
    d = dict(d)
    d["p_has"] = jnp.int32(0)
    return d


def _peer(L, v, i, off):
    return v[L.pbase(i) + off]


def _set_peer(L, v, i, off, val):
    return v.at[L.pbase(i) + off].set(jnp.asarray(val, jnp.int32))


def _sact(L, v):
    return v[L.G_ACT:L.G_ACT + L.P], v[L.G_ACT_N]


def _vact(L, v, i):
    b = L.pbase(i)
    return v[b + L.PB_VACT:b + L.PB_VACT + L.P], v[b + L.PB_VACT_N]


def _view_sync(L, v, i):
    """view := store, ver_current := 1, view actives := store actives,
    evaled := 0 (MCZk.sync_view / refresh_cluster_state)."""
    b = L.pbase(i)
    v = v.at[b + L.PB_VSB:b + L.PB_VSB + L.SB_SIZE].set(
        v[L.G_SB:L.G_SB + L.SB_SIZE])
    v = v.at[b + L.PB_VACT:b + L.PB_VACT + L.P].set(
        v[L.G_ACT:L.G_ACT + L.P])
    v = _set_peer(L, v, i, L.PB_VACT_N, v[L.G_ACT_N])
    v = _set_peer(L, v, i, L.PB_VERCUR, 1)
    v = _set_peer(L, v, i, L.PB_EVALED, 0)
    return v


def _all_stale(L, v):
    """A store version bump: every peer's cached version goes stale —
    dead peers' frozen caches included (currency is derived live)."""
    for i in range(L.P):
        v = _set_peer(L, v, i, L.PB_VERCUR, 0)
    return v


def _act_remove(L, v, i):
    ids, n = _sact(L, v)
    out, nn = _compact(ids, (jnp.arange(L.P) < n) & (ids != i))
    v = v.at[L.G_ACT:L.G_ACT + L.P].set(out)
    return v.at[L.G_ACT_N].set(nn)


def _act_append(L, v, i):
    ids, n = _sact(L, v)
    v = v.at[L.G_ACT:L.G_ACT + L.P].set(ids.at[n].set(i))
    return v.at[L.G_ACT_N].set(n + 1)


# -- non-eval action kernels ------------------------------------------------


def _k_refresh(L, v, i):
    return _view_sync(L, v, i)


def _k_catchup(L, v, i):
    return _set_peer(L, v, i, L.PB_X, v[L.G_SB + L.SB_IW])


def _k_kill(L, v, i):
    v = _set_peer(L, v, i, L.PB_ALIVE, 0)
    v = v.at[L.G_KILLS].add(1)
    return _act_remove(L, v, i)


def _k_rejoin(L, v, i):
    """Crashed peer returns REBUILT: operator reap of its deposed entry
    (a version-bumping store edit) + fresh machine at the current
    initWal (World._rejoin)."""
    st = _rd_sb(L, v, L.G_SB)
    pos = jnp.arange(L.P)
    in_dep = ((st["dep"] == i) & (pos < st["dep_n"])).any()
    dep2, dep2_n = _compact(st["dep"],
                            (pos < st["dep_n"]) & (st["dep"] != i))
    st2 = dict(st)
    st2["dep"] = jnp.where(in_dep, dep2, st["dep"])
    st2["dep_n"] = jnp.where(in_dep, dep2_n, st["dep_n"])
    v = _wr_sb(L, v, L.G_SB, st2)
    v = jnp.where(in_dep, _all_stale(L, v), v)      # reap bumps version
    v = v.at[L.G_REJOINS].add(1)
    v = _act_append(L, v, i)
    v = _set_peer(L, v, i, L.PB_ALIVE, 1)
    v = _set_peer(L, v, i, L.PB_PART, 0)
    v = _set_peer(L, v, i, L.PB_X, st["iw"])
    v = _set_peer(L, v, i, L.PB_NOTE, R_NONE)
    b = L.pbase(i)
    v = v.at[b + L.PB_T_HAS:b + L.PB_T_DEP + 1].set(
        jnp.array([0, T_NONE, NONE, NONE, 0], jnp.int32))
    return _view_sync(L, v, i)


def _k_partition(L, v, i):
    v = _set_peer(L, v, i, L.PB_PART, 1)
    return _act_remove(L, v, i)                     # session expires


def _k_heal(L, v, i):
    v = _set_peer(L, v, i, L.PB_PART, 0)
    v = _act_append(L, v, i)                        # new session
    return _view_sync(L, v, i)


def _k_promote(L, v, role, idx, expired):
    """Operator promote request (a version-bumping store edit).  role /
    idx / expired are static per slot."""
    st = _rd_sb(L, v, L.G_SB)
    st2 = dict(st)
    st2["p_has"] = jnp.int32(1)
    st2["p_role"] = jnp.int32(role)
    st2["p_id"] = (st["sync"] if role == PR_SYNC
                   else st["asy"][idx])
    st2["p_idx"] = jnp.int32(NONE if role == PR_SYNC else idx)
    st2["p_gen"] = st["gen"]
    st2["p_exp"] = jnp.int32(1 if expired else 0)
    v = _wr_sb(L, v, L.G_SB, st2)
    return _all_stale(L, v)


def _k_freeze(L, v, on):
    st = _rd_sb(L, v, L.G_SB)
    st2 = dict(st)
    st2["frozen"] = jnp.int32(1 if on else 0)
    v = _wr_sb(L, v, L.G_SB, st2)
    return _all_stale(L, v)


# -- slot enumeration -------------------------------------------------------
#
# Slot order REPLICATES World.enabled()'s list order exactly.  That
# matters because the Python explorer memoizes on digest and keeps the
# FIRST-discovered trace's verdict for each state; matching discovery
# order is part of the differential contract, not just cosmetics.


def slot_table(P: int) -> list[tuple]:
    slots: list[tuple] = []
    for i in range(P):
        slots += [("eval", i), ("refresh", i), ("catchup", i)]
    slots += [("kill", i) for i in range(P)]
    slots += [("rejoin", i) for i in range(P)]
    for i in range(P):
        slots += [("partition", i), ("heal", i)]
    slots += [("promote_sync",), ("promote_expired",),
              ("promote_async", 0), ("promote_async", 1),
              ("freeze",), ("unfreeze",)]
    return slots


def enabled_mask(L, v, knobs):
    """(S,) bool in slot order, mirroring World.enabled()."""
    st = _rd_sb(L, v, L.G_SB)
    sact, sact_n = _sact(L, v)
    n_alive = sum(_peer(L, v, i, L.PB_ALIVE) for i in range(L.P))
    bits = []
    for i in range(L.P):
        alive = _peer(L, v, i, L.PB_ALIVE) == 1
        part = _peer(L, v, i, L.PB_PART) == 1
        vact, vact_n = _vact(L, v, i)
        cur = ((_peer(L, v, i, L.PB_VERCUR) == 1)
               & (vact == sact).all() & (vact_n == sact_n))
        bits += [alive,
                 alive & ~part & ~cur,
                 alive & ~part & (_peer(L, v, i, L.PB_X) < st["iw"])]
    for i in range(L.P):
        bits.append((v[L.G_KILLS] < knobs[K_MAX_KILLS]) & (n_alive > 1)
                    & (_peer(L, v, i, L.PB_ALIVE) == 1)
                    & (_peer(L, v, i, L.PB_PART) == 0))
    for i in range(L.P):
        bits.append((v[L.G_REJOINS] < knobs[K_MAX_REJOINS])
                    & (_peer(L, v, i, L.PB_ALIVE) == 0))
    for i in range(L.P):
        alive = _peer(L, v, i, L.PB_ALIVE) == 1
        part = _peer(L, v, i, L.PB_PART) == 1
        allow = knobs[K_PARTITION] == 1
        bits += [allow & alive & ~part, allow & alive & part]
    can_pr = (knobs[K_PROMOTE] == 1) & (st["p_has"] == 0)
    bits += [can_pr & (st["sync"] != NONE), can_pr & (st["sync"] != NONE),
             can_pr & (st["asy_n"] >= 1), can_pr & (st["asy_n"] >= 2)]
    allow_f = knobs[K_FREEZE] == 1
    bits += [allow_f & (st["frozen"] == 0), allow_f & (st["frozen"] == 1)]
    return jnp.stack(bits)


# -- safety predicates (World._check_safety, run after every action) --------


def safety_mask(L, v):
    st = _rd_sb(L, v, L.G_SB)
    viol = jnp.int32(0)
    for j in range(L.P):
        prim_t = ((_peer(L, v, j, L.PB_ALIVE) == 1)
                  & (_peer(L, v, j, L.PB_PART) == 0)
                  & (_peer(L, v, j, L.PB_T_HAS) == 1)
                  & (_peer(L, v, j, L.PB_T_ROLE) == T_PRIM))
        named = st["prim"] == j
        xlog_bad = (prim_t & named
                    & (_peer(L, v, j, L.PB_X) < st["iw"]))
        view_gen = v[L.pbase(j) + L.PB_VSB + L.SB_GEN]
        split = (prim_t & ~named & (view_gen >= st["gen"])
                 & (_peer(L, v, j, L.PB_EVALED) == 1))
        viol = viol | jnp.where(xlog_bad,
                                _BIT["xlog_behind"], 0).astype(jnp.int32)
        viol = viol | jnp.where(split,
                                _BIT["split_brain"], 0).astype(jnp.int32)
    return viol


# -- peer evaluation --------------------------------------------------------


def _member_at(member, x):
    """member[x] for a possibly-NONE peer index."""
    return jnp.where(x >= 0,
                     member[jnp.clip(x, 0, member.shape[0] - 1)], False)


def _at(arr, idx):
    return arr[jnp.clip(idx, 0, arr.shape[0] - 1)]


def _set_target(L, v, i, has, role, up, down, dep):
    b = L.pbase(i)
    return v.at[b + L.PB_T_HAS:b + L.PB_T_DEP + 1].set(
        jnp.stack([has, role, up, down, dep]).astype(jnp.int32))


def eval_kernel(L, v, i, knobs):
    """One PeerStateMachine._evaluate of peer *i* (static), tasks
    settled: role notification, pg-target selection, the primary/sync
    duty ladder, the CAS write with conflict/partition outcomes, and
    the write-legality bits.  Returns (v', violation_bits).

    Mirrors machine.py branch for branch under the checker-harness
    reductions (takeover_grace=0, reconfigures never fail, no ONWM);
    docs/modelcheck.md has the correspondence table."""
    b = L.pbase(i)
    part = _peer(L, v, i, L.PB_PART) == 1
    ver_cur = _peer(L, v, i, L.PB_VERCUR) == 1
    x_i = _peer(L, v, i, L.PB_X)
    vw = _rd_sb(L, v, b + L.PB_VSB)           # the decision snapshot
    vact, vact_n = _vact(L, v, i)
    member = _members(vact, vact_n)           # liveness *by this view*
    pos = jnp.arange(L.P)

    # role_of(view, self) — primary > sync > async > deposed > None
    in_asy = ((vw["asy"] == i) & (pos < vw["asy_n"])).any()
    in_dep = ((vw["dep"] == i) & (pos < vw["dep_n"])).any()
    role = jnp.where(
        vw["prim"] == i, R_PRIM,
        jnp.where(vw["sync"] == i, R_SYNC,
                  jnp.where(in_asy, R_ASYNC,
                            jnp.where(in_dep, R_DEPOSED, R_NONE))))
    is_prim, is_sync = role == R_PRIM, role == R_SYNC

    frozen_eff = (vw["frozen"] == 1) & ~(knobs[K_MUT_FREEZE] == 1)

    # alive asyncs / unassigned actives, both in view order
    alive_of = jax.vmap(lambda x: _member_at(member, x))(vw["asy"])
    aasy, aasy_n = _compact(vw["asy"], (pos < vw["asy_n"]) & alive_of)
    asy_has = jax.vmap(
        lambda j: ((vw["asy"] == j) & (pos < vw["asy_n"])).any()
    )(pos)
    dep_has = jax.vmap(
        lambda j: ((vw["dep"] == j) & (pos < vw["dep_n"])).any()
    )(pos)
    role_none = ~((vw["prim"][None] == pos) | (vw["sync"][None] == pos)
                  | asy_has | dep_has)
    unass_of = jax.vmap(lambda x: _member_at(role_none, x))(vact)
    unass, unass_n = _compact(vact, (pos < vact_n) & unass_of)

    # ---- primary duty ladder (machine._primary_duties) ----
    pr_live = ((vw["p_has"] == 1) & (vw["p_role"] == PR_ASYNC)
               & (vw["p_gen"] == vw["gen"]) & (vw["p_exp"] == 0))
    p_idx = vw["p_idx"]
    ph_valid = (pr_live & (p_idx >= 0) & (p_idx < vw["asy_n"])
                & (_at(vw["asy"], p_idx) == vw["p_id"])
                & _member_at(member, vw["p_id"]))
    ph0_go = ph_valid & (p_idx == 0) & (vw["sync"] != NONE)
    ph_swap = ph_valid & (p_idx > 0)
    ph_act = ph0_go | ph_swap
    sync_bad = ((vw["sync"] == NONE) | ~_member_at(member, vw["sync"]))
    normal = is_prim & ~frozen_eff & ~ph_act
    w_appoint = normal & sync_bad & ((aasy_n > 0) | (unass_n > 0))
    w_prune = normal & ~sync_bad & (aasy_n != vw["asy_n"])
    w_adopt = (normal & ~sync_bad & (aasy_n == vw["asy_n"])
               & (unass_n > 0))
    prim_w = (is_prim & ~frozen_eff & ph_act) | w_appoint | w_prune \
        | w_adopt

    # the candidate sync and each branch's async list
    cand = jnp.where(aasy_n > 0, aasy[0], unass[0])
    app_asy = jnp.where(aasy_n > 0,
                        _mask_tail(jnp.roll(aasy, -1), aasy_n - 1), aasy)
    app_n = jnp.where(aasy_n > 0, aasy_n - 1, aasy_n)
    ph0_asy = _mask_tail(
        jnp.concatenate([vw["sync"].reshape(1), vw["asy"][1:]]),
        vw["asy_n"])
    swp = vw["asy"]
    i1, i2 = jnp.clip(p_idx - 1, 0, L.P - 1), jnp.clip(p_idx, 0, L.P - 1)
    swp = swp.at[i1].set(vw["asy"][i2]).at[i2].set(vw["asy"][i1])
    adopt_asy = jnp.where(pos < vw["asy_n"], vw["asy"],
                          _at(unass, pos - vw["asy_n"]))

    pick = lambda m, a, b_: jnp.where(m, a, b_)  # noqa: E731
    prim_new = dict(vw)
    prim_new["gen"] = vw["gen"] + jnp.where(ph0_go | w_appoint, 1, 0)
    prim_new["iw"] = pick(ph0_go | w_appoint, x_i, vw["iw"])
    prim_new["sync"] = pick(ph0_go, vw["asy"][0],
                            pick(w_appoint, cand, vw["sync"]))
    prim_new["asy"] = pick(ph0_go, ph0_asy,
                           pick(ph_swap, swp,
                                pick(w_appoint, app_asy,
                                     pick(w_prune, aasy,
                                          pick(w_adopt, adopt_asy,
                                               vw["asy"])))))
    prim_new["asy_n"] = pick(w_appoint, app_n,
                             pick(w_prune, aasy_n,
                                  pick(w_adopt,
                                       vw["asy_n"] + unass_n,
                                       vw["asy_n"])))
    prim_new["p_has"] = pick(ph_act, 0, vw["p_has"])

    # ---- sync duty ladder (machine._sync_duties) ----
    primary_alive = _member_at(member, vw["prim"])
    promote_me = ((vw["p_has"] == 1) & (vw["p_role"] == PR_SYNC)
                  & (vw["p_id"] == i) & (vw["p_gen"] == vw["gen"])
                  & (vw["p_exp"] == 0))
    xlog_ok = (x_i >= vw["iw"]) | (knobs[K_MUT_XLOG] == 1)
    w_take = (is_sync & ~frozen_eff & (promote_me | ~primary_alive)
              & xlog_ok)
    new_sync = jnp.where(aasy_n > 0, aasy[0], NONE)
    tasy, tasy_n = _compact(
        vw["asy"], (pos < vw["asy_n"])
        & ((new_sync == NONE) | (vw["asy"] != new_sync)))
    take_new = {
        # the seeded-bug mutation strips the takeover's gen bump
        "gen": vw["gen"] + jnp.where(knobs[K_MUT_GENBUMP] == 1, 0, 1),
        "iw": x_i, "prim": vw["sync"], "sync": new_sync,
        "asy": tasy, "asy_n": tasy_n,
        "dep": vw["dep"].at[jnp.clip(vw["dep_n"], 0, L.P - 1)].set(
            vw["prim"]),
        "dep_n": vw["dep_n"] + 1,
        "frozen": jnp.int32(0),               # a takeover is a fresh dict
        "p_has": jnp.int32(0), "p_role": jnp.int32(NONE),
        "p_id": jnp.int32(NONE), "p_idx": jnp.int32(NONE),
        "p_gen": jnp.int32(0), "p_exp": jnp.int32(0),
    }

    # ---- the CAS write and its outcome ----
    want_write = prim_w | w_take
    succ = want_write & ~part & ver_cur
    conflict = want_write & ~part & ~ver_cur
    new_sb = {k: pick(is_sync, take_new[k], prim_new[k])
              for k in take_new}
    viol = _write_viol(vw, new_sb, succ)

    out = v
    out = out.at[L.G_SB:L.G_SB + L.SB_SIZE].set(
        jnp.where(succ, _pack_sb(L, new_sb),
                  v[L.G_SB:L.G_SB + L.SB_SIZE]))
    for j in range(L.P):
        if j == i:
            continue
        out = out.at[L.pbase(j) + L.PB_VERCUR].set(
            jnp.where(succ, 0, _peer(L, v, j, L.PB_VERCUR)))
    out = out.at[b + L.PB_VERCUR].set(
        jnp.where(succ | conflict, 1, _peer(L, v, i, L.PB_VERCUR)))
    # writer's view: success caches the written state; a conflict does
    # an explicit refresh_cluster_state (view only — NOT the actives,
    # unlike sync_view)
    out = out.at[b + L.PB_VSB:b + L.PB_VSB + L.SB_SIZE].set(
        jnp.where(succ, _pack_sb(L, new_sb),
                  jnp.where(conflict, v[L.G_SB:L.G_SB + L.SB_SIZE],
                            v[b + L.PB_VSB:b + L.PB_VSB + L.SB_SIZE])))
    out = out.at[b + L.PB_EVALED].set(jnp.where(conflict, 0, 1))
    out = out.at[b + L.PB_NOTE].set(role)

    # ---- pg target (machine._react / _pg_config_for) ----
    aidx = _index_of(vw["asy"], vw["asy_n"], i)
    async_up = jnp.where(
        aidx == 0,
        jnp.where(vw["sync"] != NONE, vw["sync"], vw["prim"]),
        _at(vw["asy"], aidx - 1))
    async_down = jnp.where(aidx + 1 < vw["asy_n"],
                           _at(vw["asy"], aidx + 1), NONE)
    take_eff = w_take & ~conflict          # success or partition-abort
    t_role = jnp.where(is_prim, T_PRIM,
                       jnp.where(is_sync,
                                 jnp.where(take_eff, T_PRIM, T_SYNC),
                                 jnp.where(role == R_ASYNC, T_ASYNC,
                                           T_NONE)))
    t_up = jnp.where(is_prim | (is_sync & take_eff), NONE,
                     jnp.where(is_sync, vw["prim"],
                               jnp.where(role == R_ASYNC, async_up,
                                         NONE)))
    t_down = jnp.where(is_prim, vw["sync"],
                       jnp.where(is_sync & take_eff, new_sync,
                                 jnp.where(is_sync,
                                           jnp.where(vw["asy_n"] > 0,
                                                     vw["asy"][0], NONE),
                                           jnp.where(role == R_ASYNC,
                                                     async_down, NONE))))
    t_dep = jnp.where(role == R_DEPOSED, 1, 0)
    out = _set_target(L, out, i, jnp.int32(1), t_role, t_up, t_down,
                      t_dep)

    # the deposed_keeps_primary mutation returns from _evaluate before
    # _react: no notify, no target change, no duties — only the
    # explorer's eval-epoch bookkeeping advances
    mut_dep = (knobs[K_MUT_DEPOSED] == 1) & (role == R_DEPOSED)
    noop = v.at[b + L.PB_EVALED].set(1)
    return (jnp.where(mut_dep, noop, out),
            jnp.where(mut_dep, 0, viol).astype(jnp.int32),
            jnp.where(mut_dep, False, succ))


def _write_viol(old, new, succ):
    """validate_transition + MCStore.apply legality bits for a
    successful CAS write by a peer (operator edits are exempt)."""
    gen_back = new["gen"] < old["gen"]
    iw_back = new["iw"] < old["iw"]
    prim_changed = new["prim"] != old["prim"]
    same_gen = new["gen"] == old["gen"]
    npsg = prim_changed & same_gen
    pnps = prim_changed & ((old["sync"] == NONE)
                           | (new["prim"] != old["sync"]))
    bump_nc = (~prim_changed & (new["gen"] > old["gen"])
               & (old["sync"] != NONE) & (new["sync"] != NONE)
               & (old["sync"] == new["sync"]))
    sync_nb = (~prim_changed & same_gen
               & (((old["sync"] == NONE) != (new["sync"] == NONE))
                  | ((old["sync"] != NONE) & (new["sync"] != NONE)
                     & (old["sync"] != new["sync"]))))
    frozen_w = old["frozen"] == 1
    bits = [(gen_back, "gen_backwards"), (iw_back, "iw_backwards"),
            (npsg, "newprim_samegen"), (pnps, "prim_not_prev_sync"),
            (bump_nc, "bump_nochange"), (sync_nb, "sync_nobump"),
            (frozen_w, "frozen_write")]
    viol = jnp.int32(0)
    for cond, name in bits:
        viol = viol | jnp.where(succ & cond, _BIT[name],
                                0).astype(jnp.int32)
    return viol


# -- liveness (World.check_liveness) ----------------------------------------


def liveness_kernel(L, v, knobs):
    """Catch-up + fair schedule to fixpoint + convergence predicates.
    Returns the liveness violation bits (plus any write-legality bits
    the settle evaluations tripped)."""
    st0 = _rd_sb(L, v, L.G_SB)
    # replication always catches up eventually under a fair schedule:
    # every ALIVE peer (partitioned included) reaches the store initWal
    for i in range(L.P):
        alive = _peer(L, v, i, L.PB_ALIVE) == 1
        x = _peer(L, v, i, L.PB_X)
        v = v.at[L.pbase(i) + L.PB_X].set(
            jnp.where(alive & (x < st0["iw"]), st0["iw"], x))

    def anp(vv, i):
        return ((_peer(L, vv, i, L.PB_ALIVE) == 1)
                & (_peer(L, vv, i, L.PB_PART) == 0))

    def views_current(vv):
        sact, sact_n = _sact(L, vv)
        ok = jnp.bool_(True)
        for i in range(L.P):
            vact, vact_n = _vact(L, vv, i)
            cur = ((_peer(L, vv, i, L.PB_VERCUR) == 1)
                   & (vact == sact).all() & (vact_n == sact_n))
            ok = ok & (~anp(vv, i) | cur)
        return ok

    def round_body(carry):
        vv, viol, r, done = carry
        for i in range(L.P):
            vv = jnp.where(anp(vv, i), _view_sync(L, vv, i), vv)
        wrote_any = jnp.bool_(False)
        for i in range(L.P):
            go = anp(vv, i)
            v2, viol_i, wrote = eval_kernel(L, vv, i, knobs)
            vv = jnp.where(go, v2, vv)
            viol = viol | jnp.where(go, viol_i, 0).astype(jnp.int32)
            wrote_any = wrote_any | (go & wrote)
        return vv, viol, r + 1, ~wrote_any & views_current(vv)

    def cond(carry):
        _, _, r, done = carry
        return (r < 30) & ~done

    v, viol, _, done = lax.while_loop(
        cond, round_body,
        (v, jnp.int32(0), jnp.int32(0), jnp.bool_(False)))
    viol = viol | jnp.where(done, 0,
                            _BIT["no_fixpoint"]).astype(jnp.int32)

    # ---- convergence predicates (only meaningful at a fixpoint) ----
    st = _rd_sb(L, v, L.G_SB)
    pos = jnp.arange(L.P)
    anp_arr = jnp.stack([anp(v, i) for i in range(L.P)])
    in_asy = jax.vmap(
        lambda j: ((st["asy"] == j) & (pos < st["asy_n"])).any())(pos)
    in_dep = jax.vmap(
        lambda j: ((st["dep"] == j) & (pos < st["dep_n"])).any())(pos)
    role_deposed = (in_dep & ~in_asy & (st["prim"] != pos)
                    & (st["sync"] != pos))
    prim_alive = _member_at(anp_arr, st["prim"])
    sync_set = st["sync"] != NONE
    sync_alive = _member_at(anp_arr, st["sync"])
    not_frozen = st["frozen"] == 0
    dead_prim = not_frozen & ~prim_alive & sync_set & sync_alive
    cand_any = (anp_arr & (pos != st["prim"]) & ~role_deposed).any()
    no_sync = (not_frozen & prim_alive & (~sync_set | ~sync_alive)
               & cand_any)

    mism = jnp.bool_(False)
    t_has = jnp.stack([_peer(L, v, j, L.PB_T_HAS) == 1
                       for j in range(L.P)])
    t_role = jnp.stack([_peer(L, v, j, L.PB_T_ROLE)
                        for j in range(L.P)])
    t_up = jnp.stack([_peer(L, v, j, L.PB_T_UP) for j in range(L.P)])
    t_down = jnp.stack([_peer(L, v, j, L.PB_T_DOWN)
                        for j in range(L.P)])
    for j in range(L.P):
        want = jnp.where(
            st["prim"] == j, T_PRIM,
            jnp.where(st["sync"] == j, T_SYNC,
                      jnp.where(in_asy[j], T_ASYNC, T_NONE)))
        mism = mism | (anp_arr[j] & (~t_has[j] | (t_role[j] != want)))

    def up_of(j):
        return jnp.where(_member_at(t_has, j), _at(t_up, j), NONE)

    def down_of(j):
        return jnp.where(_member_at(t_has, j), _at(t_down, j), NONE)

    chain = (prim_alive & sync_set
             & (down_of(st["prim"]) != st["sync"]))
    chain = chain | (sync_set & sync_alive
                     & (up_of(st["sync"]) != st["prim"]))
    for k in range(L.P):
        a_k = st["asy"][k]
        live = (k < st["asy_n"]) & _member_at(anp_arr, a_k)
        want_up = jnp.where(k == 0, st["sync"],
                            st["asy"][max(k - 1, 0)])
        applicable = live & ((k > 0) | sync_set)
        chain = chain | (applicable & (up_of(a_k) != want_up))

    pred = (jnp.where(dead_prim,
                      _BIT["dead_primary_not_replaced"], 0)
            | jnp.where(no_sync, _BIT["no_sync_appointed"], 0)
            | jnp.where(mism, _BIT["role_mismatch"], 0)
            | jnp.where(chain, _BIT["chain"], 0)).astype(jnp.int32)
    return viol | jnp.where(done, pred, 0).astype(jnp.int32)


# -- one frontier step ------------------------------------------------------


def _apply_slot(L, v, slot, knobs):
    kind = slot[0]
    if kind == "eval":
        v2, viol, _ = eval_kernel(L, v, slot[1], knobs)
        return v2, viol
    z = jnp.int32(0)
    if kind == "refresh":
        return _k_refresh(L, v, slot[1]), z
    if kind == "catchup":
        return _k_catchup(L, v, slot[1]), z
    if kind == "kill":
        return _k_kill(L, v, slot[1]), z
    if kind == "rejoin":
        return _k_rejoin(L, v, slot[1]), z
    if kind == "partition":
        return _k_partition(L, v, slot[1]), z
    if kind == "heal":
        return _k_heal(L, v, slot[1]), z
    if kind == "promote_sync":
        return _k_promote(L, v, PR_SYNC, 0, False), z
    if kind == "promote_expired":
        return _k_promote(L, v, PR_SYNC, 0, True), z
    if kind == "promote_async":
        return _k_promote(L, v, PR_ASYNC, slot[1], False), z
    if kind == "freeze":
        return _k_freeze(L, v, True), z
    if kind == "unfreeze":
        return _k_freeze(L, v, False), z
    raise ValueError("unknown slot %r" % (kind,))


def _step_one(L, v, knobs):
    """Expand one state across the whole action alphabet: children in
    slot order (disabled slots return the parent, which dedups away),
    action+safety violation bits, and the enabled mask."""
    en = enabled_mask(L, v, knobs)
    outs, viols = [], []
    for s, slot in enumerate(slot_table(L.P)):
        v2, viol = _apply_slot(L, v, slot, knobs)
        viol = (viol | safety_mask(L, v2)).astype(jnp.int32)
        outs.append(jnp.where(en[s], v2, v))
        viols.append(jnp.where(en[s], viol, 0))
    return (jnp.stack(outs), jnp.stack(viols).astype(jnp.int32), en)


def build_step(P: int):
    """The jitted batched step for a peer count: (B,SIZE) -> children
    (B,S,SIZE), violations (B,S), enabled (B,S).  Config budgets and
    mutation flags are traced, so all same-P configs share one
    compilation."""
    L = Layout(P)

    def step(vs, knobs):
        return jax.vmap(lambda v: _step_one(L, v, knobs))(vs)

    return jax.jit(step)


def build_liveness(P: int):
    L = Layout(P)

    def live(vs, knobs):
        return jax.vmap(lambda v: liveness_kernel(L, v, knobs))(vs)

    return jax.jit(live)


# ---------------------------------------------------------------------------
# mutation patches (Python-side mirror of the knob flags)


@contextlib.contextmanager
def mutation_patches(mutations=None):
    """Apply the deliberate rule-weakenings to the *Python* machine —
    the exact monkeypatches of the mutation self-tests — so the oracle
    and the array engine explore the same weakened semantics and the
    regression corpus can require both to flag the same seeded bug."""
    m = mutations or Mutations()
    from manatee_tpu.state import machine as _machine
    from manatee_tpu.state.types import role_of as _role_of
    saved = {}
    try:
        if m.disable_xlog_guard:
            saved["compare_lsn"] = _machine.compare_lsn
            _machine.compare_lsn = lambda a, b: 0
        if m.ignore_freeze:
            saved["frozen"] = _machine.frozen
            _machine.frozen = lambda st: False
        if m.deposed_keeps_primary:
            orig_eval = _machine.PeerStateMachine._evaluate
            saved["_evaluate"] = orig_eval

            async def bad_evaluate(self):
                st = self.zk.cluster_state
                if (st is not None
                        and _role_of(st, self.self_id) == "deposed"):
                    return    # ignore the deposition; keep old pg config
                return await orig_eval(self)

            _machine.PeerStateMachine._evaluate = bad_evaluate
        if m.skip_gen_bump:
            orig_write = _machine.PeerStateMachine._write_state
            saved["_write_state"] = orig_write

            async def bad_write(self, state, why, ver, **kw):
                if "takeover" in why and state.get("generation", 0) > 0:
                    state = dict(state)
                    state["generation"] -= 1
                return await orig_write(self, state, why, ver, **kw)

            _machine.PeerStateMachine._write_state = bad_write
        yield
    finally:
        if "compare_lsn" in saved:
            _machine.compare_lsn = saved["compare_lsn"]
        if "frozen" in saved:
            _machine.frozen = saved["frozen"]
        if "_evaluate" in saved:
            _machine.PeerStateMachine._evaluate = saved["_evaluate"]
        if "_write_state" in saved:
            _machine.PeerStateMachine._write_state = saved["_write_state"]


# ---------------------------------------------------------------------------
# frontier driver


def _slot_action(config: MCConfig, slot: tuple) -> tuple:
    """Map a slot-table entry back to the Python explorer's action
    tuple (for counterexample traces and the differential replay)."""
    kind = slot[0]
    if kind in ("eval", "refresh", "catchup", "kill", "rejoin",
                "partition", "heal"):
        return (kind, config.peers[slot[1]])
    if kind == "promote_async":
        return (kind, slot[1])
    return (kind,)


def _build_dedup():
    """Device-side dedup over a flattened child batch.

    Rows are reduced to a 32-bit semantic-hash key (the encoding is
    bijective with the canonical digest, so hashing the vector IS
    hashing the semantic state), stably sorted with invalid rows
    (disabled slots, padding) pushed to the back, and neighbor-compared
    on the full vector.  Stability guarantees the *minimum-linear-index*
    occurrence of every distinct state survives, which is what preserves
    the Python explorer's first-discovery order; hash collisions merely
    split a run and leave an extra survivor for the host's exact
    seen-set to absorb — the device pass only reduces host work, it can
    never drop a state."""

    def dedup(flat, valid):
        w = flat.shape[1]
        weights = (jnp.arange(1, w + 1, dtype=jnp.uint32)
                   * jnp.uint32(2654435761)) | jnp.uint32(1)
        key = (flat.astype(jnp.uint32) * weights[None, :]).sum(axis=1)
        o1 = jnp.argsort(key, stable=True)
        o2 = jnp.argsort(~valid[o1], stable=True)   # valid first
        order = o1[o2]
        srt = flat[order]
        dup = jnp.concatenate(
            [jnp.zeros((1,), bool), (srt[1:] == srt[:-1]).all(axis=1)])
        keep = valid[order] & ~dup
        return keep, order

    return jax.jit(dedup)


_ENGINES: dict = {}


def _engine(P: int, chunk: int):
    """Compiled (step, liveness, dedup) for a peer count and chunk
    size.  With more than one device the step and liveness kernels are
    shard_map'd across the host-platform mesh (chunk rows split on the
    ``data`` axis, knobs replicated); dedup runs over the gathered
    batch.  Cached so repeated explorations share compilations."""
    n_dev = len(jax.devices())
    key = (P, chunk, n_dev)
    eng = _ENGINES.get(key)
    if eng is not None:
        return eng
    L = Layout(P)
    if n_dev > 1:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh
        from jax.sharding import PartitionSpec as PSpec
        mesh = Mesh(np.array(jax.devices()), axis_names=("data",))
        dp, rep = PSpec("data"), PSpec()

        def _step(vs, knobs):
            return jax.vmap(lambda v: _step_one(L, v, knobs))(vs)

        def _live(vs, knobs):
            return jax.vmap(lambda v: liveness_kernel(L, v, knobs))(vs)

        # check_rep=False: the liveness fair schedule is a
        # lax.while_loop, which shard_map's replication checker does
        # not support; nothing here relies on replication inference
        # (inputs are either sharded on data or fully replicated)
        step = jax.jit(shard_map(_step, mesh=mesh, in_specs=(dp, rep),
                                 out_specs=(dp, dp, dp),
                                 check_rep=False))
        live = jax.jit(shard_map(_live, mesh=mesh, in_specs=(dp, rep),
                                 out_specs=dp, check_rep=False))
    else:
        step = build_step(P)
        live = build_liveness(P)
    eng = (step, live, _build_dedup())
    _ENGINES[key] = eng
    return eng


def explore_jax(config: MCConfig, depth: int | None = None,
                max_nodes: int = 200_000, progress: bool = False,
                mutations=None, collect=None,
                chunk: int = 256) -> MCResult:
    """Level-synchronized BFS with the whole frontier expanded on
    device.

    Exactly mirrors ``modelcheck.explore``: the slot table enumerates
    actions in ``World.enabled()`` order, chunks are consecutive
    frontier slices, the device dedup keeps minimum-linear-index
    occurrences, and the host seen-set admits candidates in ascending
    linear order — so states are discovered in the Python explorer's
    exact BFS order and first-trace verdicts coincide.  Matched-depth
    runs must agree with the oracle on states, nodes, transitions and
    every verdict (see :func:`differential`).

    *collect*, when given, is called as ``collect(digest, trace,
    categories)`` per discovered state (digests require decoding, so
    only pass it when comparing).  Violation records carry category
    names (canon.CATEGORIES) as their problems."""
    depth = config.depth if depth is None else depth
    m = mutations or Mutations()
    P = len(config.peers)
    L = Layout(P)
    table = slot_table(P)
    S = len(table)
    n_dev = len(jax.devices())
    chunk = max(1, chunk // n_dev) * n_dev
    res = MCResult(config=config.name, engine="jax")
    t0 = time.monotonic()
    last_report = t0
    logging.getLogger("manatee.state").setLevel(logging.CRITICAL)

    # boot through the real machine (under the same mutations): the
    # root state and its boot-time violations come from the oracle
    from manatee_tpu.state import machine as _machine
    patched, _machine._sleep = _machine._sleep, _fast_sleep
    try:
        loop = asyncio.new_event_loop()
        try:
            with mutation_patches(m):
                root_w = loop.run_until_complete(_replay(config, ()))
        finally:
            loop.close()
    finally:
        _machine._sleep = patched
    root_vec = np.asarray(encode_world(root_w, config), np.int32)
    boot_bad = canon.classify_all(root_w.violations
                                  + root_w.store.violations)

    knobs = jnp.asarray(make_knobs(config, m))
    step, live, dedup = _engine(P, chunk)

    vecs: list[np.ndarray] = [root_vec]
    index: dict[bytes, int] = {root_vec.tobytes(): 0}
    parents: list[int] = [-1]
    pslots: list[int] = [-1]

    def lv_bits(arr: np.ndarray) -> np.ndarray:
        out = []
        for off in range(0, len(arr), chunk):
            part = arr[off:off + chunk]
            if len(part) < chunk:
                part = np.concatenate(
                    [part, np.repeat(part[:1], chunk - len(part), 0)])
            out.append(np.asarray(live(jnp.asarray(part), knobs)))
        return np.concatenate(out)[:len(arr)]

    def trace_of(i: int) -> list:
        rev = []
        while parents[i] >= 0:
            rev.append(pslots[i])
            i = parents[i]
        return [_slot_action(config, table[s]) for s in reversed(rev)]

    root_cats = boot_bad | canon.mask_to_categories(
        int(lv_bits(root_vec[None, :])[0]))
    if collect is not None:
        collect(digest_vec(root_vec, config), (), root_cats)
    frontier: list[int] = []
    if root_cats:
        res.violations.append({"trace": [],
                               "problems": sorted(root_cats)})
    elif depth > 0:
        frontier.append(0)

    level = 0
    truncated = False
    while frontier and level < depth and not truncated:
        level += 1
        budget = max_nodes - res.nodes
        if budget <= 0:
            truncated = True
            break
        expand = frontier
        if len(expand) > budget:
            expand = expand[:budget]
            truncated = True
        new_ids: list[int] = []
        new_avi: list[int] = []
        for off in range(0, len(expand), chunk):
            part = expand[off:off + chunk]
            n_real = len(part)
            vs = np.stack([vecs[i] for i in part])
            if n_real < chunk:
                vs = np.concatenate(
                    [vs, np.repeat(vs[:1], chunk - n_real, 0)])
            ch, vi, en = step(jnp.asarray(vs), knobs)
            en = np.asarray(en)
            vi = np.asarray(vi)
            flat = np.asarray(ch).reshape(chunk * S, L.SIZE)
            valid = np.zeros(chunk * S, bool)
            valid[:n_real * S] = en[:n_real].reshape(-1)
            keep, order = dedup(jnp.asarray(flat), jnp.asarray(valid))
            kept = np.sort(np.asarray(order)[np.asarray(keep)])
            for lin in kept:                # ascending == BFS order
                b, s = divmod(int(lin), S)
                vb = flat[lin].tobytes()
                if vb in index:
                    continue
                nid = len(vecs)
                index[vb] = nid
                vecs.append(flat[lin].copy())
                parents.append(part[b])
                pslots.append(s)
                new_ids.append(nid)
                new_avi.append(int(vi[b, s]))
            res.nodes += n_real
            res.transitions += int(en[:n_real].sum())
            if progress and time.monotonic() - last_report >= 2.0:
                last_report = time.monotonic()
                print("[modelcheck %s/jax] states=%d frontier=%d "
                      "depth<=%d %.0f states/s"
                      % (config.name, len(vecs), len(frontier),
                         res.depth_reached,
                         len(vecs) / (last_report - t0)),
                      file=sys.stderr, flush=True)
        if not new_ids:
            frontier = []
            break
        res.depth_reached = level
        lv = lv_bits(np.stack([vecs[i] for i in new_ids]))
        nxt: list[int] = []
        for nid, avi, lbits in zip(new_ids, new_avi, lv):
            cats = canon.mask_to_categories(avi | int(lbits))
            if collect is not None:
                collect(digest_vec(vecs[nid], config),
                        tuple(trace_of(nid)), cats)
            if cats:
                res.violations.append({"trace": trace_of(nid),
                                       "problems": sorted(cats)})
            else:
                nxt.append(nid)
        frontier = nxt
    if truncated:
        res.complete = False
    res.states = len(vecs)
    res.seconds = time.monotonic() - t0
    return res


# ---------------------------------------------------------------------------
# differential oracle


class DifferentialError(AssertionError):
    """The engines disagreed — always a bug, never tolerable noise."""

    def __init__(self, msg: str, trace=None):
        super().__init__(msg)
        self.trace = trace


def _replay_report(config: MCConfig, mutations, trace) -> str:
    """Replay the offending action sequence through the Python world,
    reporting the verdict after every prefix — the minimized trace a
    divergence report ships."""
    from manatee_tpu.state import machine as _machine
    from manatee_tpu.state.modelcheck import _check_world
    lines = []
    patched, _machine._sleep = _machine._sleep, _fast_sleep
    try:
        loop = asyncio.new_event_loop()
        try:
            with mutation_patches(mutations):
                for k in range(len(trace) + 1):
                    w = loop.run_until_complete(
                        _replay(config, tuple(trace[:k])))
                    bad = _check_world(loop, w)
                    cats = sorted(canon.classify_all(bad))
                    lines.append("  after %-60r %s"
                                 % (list(trace[:k]), cats or "clean"))
        finally:
            loop.close()
    finally:
        _machine._sleep = patched
    return "\n".join(lines)


def differential(config: MCConfig, depth: int | None = None,
                 max_nodes: int = 200_000, mutations=None):
    """Run both engines at matched depth and require exact agreement on
    the reachable semantic-state set and every violation verdict.

    Divergence is a hard failure (:class:`DifferentialError`): the
    offending action sequence is replayed through the Python world and
    the per-prefix verdicts attached as a minimized trace.  Returns
    ``(python_result, jax_result)`` on agreement."""
    from manatee_tpu.state.modelcheck import explore
    m = mutations or Mutations()
    py: dict = {}
    jx: dict = {}

    def py_collect(d, seq, bad):
        if d not in py:
            py[d] = (seq, canon.classify_all(bad))

    def jx_collect(d, seq, cats):
        if d not in jx:
            jx[d] = (seq, cats)

    with mutation_patches(m):
        pres = explore(config, depth=depth, max_nodes=max_nodes,
                       collect=py_collect)
    jres = explore_jax(config, depth=depth, max_nodes=max_nodes,
                       mutations=m, collect=jx_collect)

    def fail(msg, trace):
        raise DifferentialError(
            "%s [config=%s depth=%r mutations=%r]\nminimized trace:\n%s"
            % (msg, config.name, depth, m,
               _replay_report(config, m, trace)), trace=trace)

    for d in sorted(jx.keys() - py.keys()):
        fail("state %s reached only by the jax engine" % d, jx[d][0])
    for d in sorted(py.keys() - jx.keys()):
        fail("state %s reached only by the python engine" % d,
             py[d][0])
    for d in sorted(py):
        if py[d][1] != jx[d][1]:
            fail("verdict mismatch on %s: python=%s jax=%s"
                 % (d, sorted(py[d][1]), sorted(jx[d][1])), jx[d][0])
    if pres.complete and jres.complete:
        pc = (pres.states, pres.nodes, pres.transitions,
              pres.depth_reached)
        jc = (jres.states, jres.nodes, jres.transitions,
              jres.depth_reached)
        if pc != jc:
            raise DifferentialError(
                "counter mismatch on %s: python"
                "(states,nodes,transitions,depth)=%r jax=%r"
                % (config.name, pc, jc))
    return pres, jres


# ---------------------------------------------------------------------------
# throughput probe (the bench.py modelcheck_throughput leg)


def main(argv=None) -> int:
    """One warm-measured jax sweep, JSON on stdout.

    Runs in a subprocess per device count (XLA_FLAGS must be set before
    jax initializes, so the caller — bench.py — sets the env and execs
    this module).  A short cold run pays the jit compile first; the
    timed runs therefore measure steady-state states/sec, which is the
    number that matters for sweep planning."""
    import argparse
    import json as _json
    import os

    ap = argparse.ArgumentParser(
        description="jax model-check engine throughput probe")
    ap.add_argument("--config", default="promote",
                    choices=sorted(CONFIGS))
    ap.add_argument("--depth", type=int, default=5)
    ap.add_argument("--deeper", type=int, default=0,
                    help="extra plies for a second, deeper timed sweep")
    ap.add_argument("--chunk", type=int, default=1024)
    ap.add_argument("--max-nodes", type=int, default=500_000)
    args = ap.parse_args(argv)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the image's pinned accelerator plugin ignores the env var;
        # jax.config is the mechanism it honors (tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    cfg = CONFIGS[args.config]
    explore_jax(cfg, depth=min(2, args.depth), chunk=args.chunk)
    res = explore_jax(cfg, depth=args.depth, chunk=args.chunk,
                      max_nodes=args.max_nodes)
    out = {
        "engine": "jax", "config": args.config,
        "n_devices": len(jax.devices()),
        "depth": args.depth, "states": res.states,
        "nodes": res.nodes, "ok": res.ok, "complete": res.complete,
        "seconds": round(res.seconds, 3),
        "states_per_sec": round(res.states_per_sec, 1),
    }
    if args.deeper > 0:
        d2 = explore_jax(cfg, depth=args.depth + args.deeper,
                         chunk=args.chunk, max_nodes=args.max_nodes)
        out["deeper"] = {
            "depth": args.depth + args.deeper, "states": d2.states,
            "ok": d2.ok, "complete": d2.complete,
            "seconds": round(d2.seconds, 3),
            "states_per_sec": round(d2.states_per_sec, 1),
        }
    print(_json.dumps(out))
    return 0 if res.ok else 1


if __name__ == "__main__":
    sys.exit(main())
