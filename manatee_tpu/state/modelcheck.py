"""Explicit-state model checker for the cluster state machine.

SURVEY.md §7 ranks the state machine's safety invariants as the hardest
part of the rebuild and names property-style exploration over event
interleavings as the biggest quality lever over the reference (which
outsources the logic to the `manatee-state-machine` dependency and tests
it only through whole-cluster integration runs).  tests/test_soak.py
samples random interleavings; this module goes further and enumerates
them exhaustively up to a bounded depth.

It drives the REAL ``PeerStateMachine`` (manatee_tpu/state/machine.py) —
not a re-implementation — through deterministic checker-owned stand-ins
for the consensus manager and the PG manager:

* ``MCStore`` is the durable coordination state (the `state` znode plus
  election membership) with ZooKeeper CAS semantics: a write succeeds
  only when the writer's expected version matches
  (lib/zookeeperMgr.js:605-630).
* ``MCZk`` is one peer's *view* of the store.  Views go stale and are
  refreshed only by an explicit explorer action, which models watch
  delivery lag more adversarially than production (where the watch and
  the cache update arrive together).
* ``MCPg`` records reconfigure targets and serves a settable xlog
  position, like the unit suite's SimPg.

The explorer then runs a breadth-first search over action sequences —
peer evaluations, view refreshes, crashes, rebuilt rejoins, xlog
catch-up, operator promote/freeze writes, and network partitions — with
memoization on a canonical hash of the full system state.  At every
reached state it checks:

safety (checked on every store write, at every node):
  * every transition satisfies the generation discipline encoded by the
    reference's history annotator (validate_transition,
    lib/adm.js:2296-2416);
  * the durable generation never decreases;
  * at most one live peer is configured writable-primary AND named
    primary by the durable state;
  * a takeover only ever installs the previous sync as primary, and
    never while the taker's xlog is behind the generation's initWal
    (docs/xlog-diverge.md);
  * no evaluation raises an unexpected exception.

liveness (checked by running a fair schedule from every reached state):
  * the fair schedule reaches a fixpoint (no livelock/wedge);
  * a dead primary with a live, caught-up sync is always replaced;
  * a live primary with no sync appoints one whenever a candidate is
    alive;
  * every live peer's PG target matches its durable role, and the
    upstream/downstream replication chain is exactly the daisy chain the
    state describes (primary -> sync -> async[0] -> async[1] ...).

Run deep explorations from the CLI::

    python3 -m manatee_tpu.state.modelcheck --config all --depth 7

The pytest wrapper (tests/test_model_check.py) runs bounded
configurations on every `make test`.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import sys
import time
from collections import deque
from dataclasses import dataclass, field

from manatee_tpu.coord.api import (
    BadVersionError,
    ConnectionLossError,
    NodeExistsError,
)
from manatee_tpu.state import canon
from manatee_tpu.state.machine import PeerStateMachine
from manatee_tpu.state.types import (
    INITIAL_WAL,
    compare_lsn,
    frozen,
    role_of,
    validate_transition,
)

FUTURE_EXPIRY = "2099-01-01T00:00:00.000Z"
PAST_EXPIRY = "2000-01-01T00:00:00.000Z"

_ORIG_SLEEP = asyncio.sleep


async def _fast_sleep(delay, result=None):
    """Injected as machine._sleep during exploration: keep the yield
    point (tasks must still get scheduled) but drop the wall-clock wait
    so the machine's retry/backoff paths run at full speed."""
    return await _ORIG_SLEEP(0)


# ---------------------------------------------------------------------------
# deterministic stand-ins


class MCStore:
    """Durable coordination state with ZooKeeper CAS semantics."""

    def __init__(self):
        self.state: dict | None = None
        self.version: int | None = None
        self.actives: list[dict] = []     # election order = seq order
        self.seq = 0
        self.writes = 0
        self.violations: list[str] = []

    def join(self, info: dict) -> None:
        self.seq += 1
        rec = dict(info)
        rec["seq"] = self.seq
        self.actives.append(rec)

    def leave(self, peer_id: str) -> None:
        self.actives = [a for a in self.actives if a["id"] != peer_id]

    def apply(self, state: dict, new_version: int, who: str) -> None:
        for p in validate_transition(self.state, state):
            self.violations.append("%s wrote illegal transition: %s"
                                   % (who, p))
        if (self.state is not None and frozen(self.state)
                and not who.startswith("operator")):
            # frozen clusters make no automatic transitions
            # (docs/user-guide.md freeze semantics); only operator
            # writes (unfreeze, reap, promote requests) may land
            self.violations.append(
                "%s wrote state while the cluster was frozen" % who)
        if (self.state is not None
                and state.get("generation", 0)
                < self.state.get("generation", 0)):
            self.violations.append("%s: generation went backwards" % who)
        self.state = state
        self.version = new_version
        self.writes += 1

    def operator_edit(self, mutate, who: str) -> None:
        """An operator read-modify-CAS (freeze, promote, reap...)."""
        if self.state is None:
            return
        st = json.loads(json.dumps(self.state))
        mutate(st)
        self.apply(st, self.version + 1, who)


class MCZk:
    """One peer's (possibly stale) view of the store, presenting the
    narrow interface PeerStateMachine consumes (the zkinterface of
    lib/shard.js:59-71)."""

    def __init__(self, store: MCStore, peer):
        self._store = store
        self._peer = peer
        self.cluster_state: dict | None = None
        self.cluster_state_version: int | None = None
        self.active: list[dict] = []

    def on(self, event, cb):              # events are explorer-driven
        pass

    def view_current(self) -> bool:
        return (self.cluster_state_version == self._store.version
                and [a["id"] for a in self.active]
                == [a["id"] for a in self._store.actives])

    def sync_view(self) -> None:
        if self._peer.partitioned:
            return
        self.cluster_state = (None if self._store.state is None
                              else json.loads(json.dumps(self._store.state)))
        self.cluster_state_version = self._store.version
        self.active = [dict(a) for a in self._store.actives]
        self._peer.view_epoch += 1

    async def put_cluster_state(self, state: dict, *,
                                expected_version: int | None = None) -> None:
        if self._peer.partitioned:
            raise ConnectionLossError("partitioned from coordination")
        version = (expected_version if expected_version is not None
                   else self.cluster_state_version)
        if version is None:
            if self._store.state is not None:
                raise NodeExistsError("state already exists")
            new_version = 0
        else:
            if self._store.version != version:
                raise BadVersionError(
                    "expected v%s, have v%s" % (version, self._store.version))
            new_version = version + 1
        self._store.apply(json.loads(json.dumps(state)), new_version,
                          self._peer.name)
        # a successful write updates the writer's own cache
        # (coord/manager.py put_cluster_state)
        self.cluster_state = json.loads(json.dumps(state))
        self.cluster_state_version = new_version

    async def refresh_cluster_state(self) -> None:
        if self._peer.partitioned:
            raise ConnectionLossError("partitioned from coordination")
        self.cluster_state = (None if self._store.state is None
                              else json.loads(json.dumps(self._store.state)))
        self.cluster_state_version = self._store.version
        self._peer.view_epoch += 1


class MCPg:
    """PG manager stand-in: records the applied reconfigure target and
    serves a settable xlog position."""

    def __init__(self, xlog: str):
        self.cfg: dict | None = None
        self.xlog = xlog

    async def reconfigure(self, cfg: dict) -> None:
        self.cfg = cfg

    async def stop(self) -> None:
        self.cfg = {"role": "none"}

    async def get_xlog_location(self) -> str:
        return self.xlog


class MCPeer:
    def __init__(self, store: MCStore, name: str, xlog: str,
                 singleton: bool = False):
        self.name = name
        self.ident = "%s:5432:12345" % name
        self.info = {
            "id": self.ident, "zoneId": name, "ip": name,
            "pgUrl": "tcp://postgres@%s:5432/postgres" % name,
            "backupUrl": "http://%s:12345" % name,
        }
        self.alive = True
        self.partitioned = False
        # has this peer EVALUATED since it last learned new state?  the
        # split-brain check may only fire once it has: between seeing a
        # takeover and acting on it, a stale-primary window is the same
        # unavoidable transient the reference has
        self.view_epoch = 0
        self.eval_epoch = -1
        self.zk = MCZk(store, self)
        self.pg = MCPg(xlog)
        self.sm = PeerStateMachine(zk=self.zk, pg=self.pg,
                                   self_info=self.info,
                                   singleton=singleton,
                                   takeover_grace=0.0)


# ---------------------------------------------------------------------------
# configurations


@dataclass
class MCConfig:
    name: str
    peers: tuple = ("A", "B", "C")
    # xlog the first joiner (the bootstrap primary) starts at; appointing
    # a new sync stamps initWal with this, arming the takeover guard
    primary_xlog: str = "0/0001000"
    standby_xlog: str = "0/0001000"
    max_kills: int = 2
    max_rejoins: int = 0
    allow_promote: bool = False
    allow_freeze: bool = False
    allow_partition: bool = False
    # peers killed (then fair-settled) during boot, before exploration:
    # lets a config start from a later generation, e.g. with a nonzero
    # initWal arming the takeover guard.  Not counted against max_kills.
    boot_kills: tuple = ()
    depth: int = 5
    description: str = ""


CONFIGS = {
    c.name: c for c in [
        MCConfig(
            name="deaths3",
            description="3 peers; every interleaving of up to two "
                        "crashes with stale views and CAS races"),
        MCConfig(
            name="behind",
            peers=("A", "B", "C", "D"),
            standby_xlog="0/0000500", boot_kills=("B",), max_kills=1,
            description="boots past a sync re-appointment so initWal is "
                        "ahead of the standbys: the xlog takeover guard "
                        "must hold until an explicit catch-up event"),
        MCConfig(
            name="rejoin",
            max_kills=2, max_rejoins=2,
            description="crashed peers rejoin REBUILT (operator reap + "
                        "restore-to-initWal) in every order"),
        MCConfig(
            name="promote",
            peers=("A", "B", "C", "D"), max_kills=1, allow_promote=True,
            description="operator promote requests (sync, async swap, "
                        "already-expired) racing a crash"),
        MCConfig(
            name="freeze",
            max_kills=2, allow_freeze=True,
            description="freeze/unfreeze racing crashes: frozen clusters "
                        "must make no automatic transitions"),
        MCConfig(
            name="partition",
            max_kills=1, allow_partition=True,
            description="a partitioned (alive but unreachable) peer: "
                        "stale writes must lose CAS, the healed peer "
                        "must adopt the durable topology"),
    ]
}


# ---------------------------------------------------------------------------
# the world


class World:
    def __init__(self, config: MCConfig):
        self.config = config
        self.store = MCStore()
        self.peers: dict[str, MCPeer] = {}
        self.kills = 0
        self.rejoins = 0
        self.violations: list[str] = []

    # -- construction --

    async def boot(self) -> None:
        for name in self.config.peers:
            xlog = (self.config.primary_xlog if name == self.config.peers[0]
                    else self.config.standby_xlog)
            await self._add_peer(name, xlog)
        await self.fair_settle()
        if self.store.state is None:
            self.violations.append("bootstrap never declared a cluster")
        for name in self.config.boot_kills:
            p = self.peers[name]
            p.alive = False
            self.store.leave(p.ident)
            await self.fair_settle()

    async def _add_peer(self, name: str, xlog: str) -> MCPeer:
        p = MCPeer(self.store, name, xlog)
        self.peers[name] = p
        self.store.join(p.info)
        p.zk.sync_view()
        p.sm._on_zk_init({"active": p.zk.active})
        p.sm.pg_init()
        return p

    # -- actions --

    def enabled(self) -> list[tuple]:
        acts: list[tuple] = []
        alive = [p for p in self.peers.values() if p.alive]
        st = self.store.state
        for p in alive:
            acts.append(("eval", p.name))
            if not p.partitioned and not p.zk.view_current():
                acts.append(("refresh", p.name))
            if st is not None and not p.partitioned and \
                    compare_lsn(p.pg.xlog, st.get("initWal", INITIAL_WAL)) < 0:
                acts.append(("catchup", p.name))
        if self.kills < self.config.max_kills and len(alive) > 1:
            for p in alive:
                if not p.partitioned:
                    acts.append(("kill", p.name))
        if self.rejoins < self.config.max_rejoins:
            for name, p in self.peers.items():
                if not p.alive:
                    acts.append(("rejoin", name))
        if self.config.allow_partition:
            for p in alive:
                if not p.partitioned:
                    acts.append(("partition", p.name))
                else:
                    acts.append(("heal", p.name))
        if st is not None and "promote" not in st and self.config.allow_promote:
            if st.get("sync"):
                acts.append(("promote_sync",))
                acts.append(("promote_expired",))
            if st.get("async"):
                acts.append(("promote_async", 0))
                if len(st["async"]) > 1:
                    acts.append(("promote_async", 1))
        if self.config.allow_freeze and st is not None:
            acts.append(("unfreeze",) if frozen(st) else ("freeze",))
        return acts

    async def do(self, action: tuple) -> None:
        kind = action[0]
        if kind == "eval":
            await self._eval(self.peers[action[1]])
        elif kind == "refresh":
            p = self.peers[action[1]]
            p.zk.sync_view()
            p.sm._witness(p.zk.active)
        elif kind == "catchup":
            st = self.store.state
            if st is not None:
                self.peers[action[1]].pg.xlog = st.get("initWal", INITIAL_WAL)
        elif kind == "kill":
            p = self.peers[action[1]]
            p.alive = False
            self.kills += 1
            self.store.leave(p.ident)
        elif kind == "rejoin":
            await self._rejoin(action[1])
        elif kind == "partition":
            p = self.peers[action[1]]
            p.partitioned = True
            self.store.leave(p.ident)     # session expires
        elif kind == "heal":
            p = self.peers[action[1]]
            p.partitioned = False
            self.store.join(p.info)       # new session
            p.zk.sync_view()
            p.sm._on_session_rebuilt({"active": p.zk.active})
        elif kind == "promote_sync":
            def mut(st):
                st["promote"] = {"id": st["sync"]["id"], "role": "sync",
                                 "generation": st["generation"],
                                 "expireTime": FUTURE_EXPIRY}
            self.store.operator_edit(mut, "operator")
        elif kind == "promote_expired":
            def mut(st):
                st["promote"] = {"id": st["sync"]["id"], "role": "sync",
                                 "generation": st["generation"],
                                 "expireTime": PAST_EXPIRY}
            self.store.operator_edit(mut, "operator")
        elif kind == "promote_async":
            idx = action[1]

            def mut(st):
                asyncs = st.get("async") or []
                if idx < len(asyncs):
                    st["promote"] = {"id": asyncs[idx]["id"], "role": "async",
                                     "asyncIndex": idx,
                                     "generation": st["generation"],
                                     "expireTime": FUTURE_EXPIRY}
            self.store.operator_edit(mut, "operator")
        elif kind == "freeze":
            self.store.operator_edit(
                lambda st: st.__setitem__(
                    "freeze", {"date": "2026-01-01T00:00:00Z",
                               "reason": "modelcheck"}), "operator")
        elif kind == "unfreeze":
            self.store.operator_edit(
                lambda st: st.pop("freeze", None), "operator")
        else:
            raise ValueError("unknown action %r" % (action,))
        self._check_safety()

    async def _rejoin(self, name: str) -> None:
        """A crashed peer returns REBUILT: the operator reaped its
        deposed entry and the restore brought its xlog to the current
        initWal (what manatee-adm rebuild leaves behind,
        lib/adm.js:1533-1539)."""
        self.rejoins += 1
        st = self.store.state
        iw = (st or {}).get("initWal", INITIAL_WAL)
        ident = "%s:5432:12345" % name
        if st is not None and any(
                d["id"] == ident for d in st.get("deposed") or []):
            self.store.operator_edit(
                lambda s: s.__setitem__(
                    "deposed", [d for d in s.get("deposed") or []
                                if d["id"] != ident]), "operator-reap")
        await self._add_peer(name, iw)

    async def _eval(self, p: MCPeer) -> None:
        # the epoch this evaluation actually reasons about is the one at
        # entry: a CAS loss refreshes the view MID-eval (bumping
        # view_epoch), and the decision already taken used the old view —
        # only the next evaluation acts on the refreshed one
        epoch = p.view_epoch
        try:
            await p.sm._evaluate()
        except asyncio.CancelledError:
            raise
        except ConnectionLossError:
            pass                          # partitioned: expected
        except Exception as exc:          # noqa: BLE001 - report, don't die
            self.violations.append(
                "%s evaluation crashed: %r" % (p.name, exc))
        await self._settle_tasks()
        p.eval_epoch = max(p.eval_epoch, epoch)

    async def _settle_tasks(self) -> None:
        for _ in range(20):
            pending = [p.sm._pg_task for p in self.peers.values()
                       if p.sm._pg_task is not None
                       and not p.sm._pg_task.done()]
            if not pending:
                return
            await _ORIG_SLEEP(0)
        self.violations.append("pg task failed to settle")

    # -- invariants --

    def _check_safety(self) -> None:
        st = self.store.state
        if st is None:
            return
        prims = [p for p in self.peers.values()
                 if p.alive and not p.partitioned
                 and p.sm._pg_target
                 and p.sm._pg_target.get("role") == "primary"]
        for p in prims:
            named = bool(st.get("primary")
                         and st["primary"]["id"] == p.ident)
            if named:
                # the named primary's xlog must satisfy the generation's
                # initWal
                if compare_lsn(p.pg.xlog,
                               st.get("initWal", INITIAL_WAL)) < 0:
                    self.violations.append(
                        "%s is primary with xlog %s behind initWal %s"
                        % (p.name, p.pg.xlog, st.get("initWal")))
                continue
            # an UN-named peer still configured writable-primary is the
            # split-brain transient: tolerable while its view predates
            # the durable state, or while it has seen the takeover but
            # not yet evaluated (the reference tolerates the same
            # window, bounded by synchronous commit refusing to ack).
            # A peer that EVALUATED a current-or-newer view must have
            # stepped down.
            view_gen = (p.zk.cluster_state or {}).get("generation", -1)
            if (view_gen >= st.get("generation", 0)
                    and p.eval_epoch >= p.view_epoch):
                self.violations.append(
                    "%s configured primary with a current view (gen %s) "
                    "but the durable primary is %s"
                    % (p.name, view_gen,
                       (st.get("primary") or {}).get("id")))

    # -- fair schedule / liveness --

    async def fair_settle(self, rounds: int = 30) -> bool:
        """Deliver everything and evaluate everyone until fixpoint."""
        for _ in range(rounds):
            for p in self.peers.values():
                if p.alive and not p.partitioned:
                    p.zk.sync_view()
                    p.sm._witness(p.zk.active)
            writes = self.store.writes
            for p in self.peers.values():
                if p.alive and not p.partitioned:
                    await self._eval(p)
            if self.store.writes == writes and all(
                    p.zk.view_current() for p in self.peers.values()
                    if p.alive and not p.partitioned):
                return True
        return False

    def _expected_pg_role(self, st: dict, ident: str) -> str:
        role = role_of(st, ident)
        if st.get("oneNodeWriteMode") and role != "primary":
            return "none"
        if role in ("primary", "sync", "async"):
            return role
        return "none"

    async def check_liveness(self) -> None:
        """Run the fair schedule to fixpoint, then assert convergence."""
        # replication always catches up eventually under a fair schedule
        st = self.store.state
        if st is not None:
            iw = st.get("initWal", INITIAL_WAL)
            for p in self.peers.values():
                if p.alive and compare_lsn(p.pg.xlog, iw) < 0:
                    p.pg.xlog = iw
        if not await self.fair_settle():
            self.violations.append("fair schedule never reached fixpoint")
            return
        st = self.store.state
        alive = {p.ident: p for p in self.peers.values()
                 if p.alive and not p.partitioned}
        if st is None:
            if len(alive) >= 2:
                self.violations.append("no cluster despite %d live peers"
                                       % len(alive))
            return
        if not frozen(st):
            primary_alive = st.get("primary") and \
                st["primary"]["id"] in alive
            sync = st.get("sync")
            if not primary_alive and sync and sync["id"] in alive:
                self.violations.append(
                    "dead primary %s not replaced by live sync %s"
                    % (st["primary"]["id"], sync["id"]))
            if primary_alive and (sync is None or sync["id"] not in alive):
                candidates = [i for i in alive
                              if i != st["primary"]["id"]
                              and role_of(st, i) != "deposed"]
                if candidates:
                    self.violations.append(
                        "primary alive with no live sync despite "
                        "candidates %s" % candidates)
        # role consistency + replication chain
        for ident, p in alive.items():
            want = self._expected_pg_role(st, ident)
            got = (p.sm._pg_target or {}).get("role")
            if got != want:
                self.violations.append(
                    "%s pg target %r but durable role %r"
                    % (p.name, got, want))
        self._check_chain(st, alive)

    def _check_chain(self, st: dict, alive: dict) -> None:
        """The applied upstream/downstream links must spell the daisy
        chain primary -> sync -> async[0] -> async[1] -> ...
        (docs/user-guide.md:69-90)."""
        def target(ident):
            p = alive.get(ident)
            return (p.sm._pg_target or {}) if p else {}

        prim, sync = st.get("primary"), st.get("sync")
        asyncs = st.get("async") or []
        if prim and prim["id"] in alive and sync:
            down = target(prim["id"]).get("downstream")
            if (down or {}).get("id") != sync["id"]:
                self.violations.append(
                    "primary downstream %r != sync %s" % (down, sync["id"]))
        if sync and sync["id"] in alive and prim:
            up = target(sync["id"]).get("upstream")
            if (up or {}).get("id") != prim["id"]:
                self.violations.append(
                    "sync upstream %r != primary %s" % (up, prim["id"]))
        for i, a in enumerate(asyncs):
            if a["id"] not in alive:
                continue
            want_up = sync if i == 0 else asyncs[i - 1]
            up = target(a["id"]).get("upstream")
            if want_up and (up or {}).get("id") != want_up["id"]:
                self.violations.append(
                    "async[%d] upstream %r != %s"
                    % (i, up, want_up["id"]))

    # -- canonical hash --

    # the semantic-state quotient lives in canon.py, shared with the
    # JAX array engine (mc_array.py) so the two engines cannot silently
    # disagree on what "same state" means
    _OBS_KEYS = canon.OBS_KEYS

    @staticmethod
    def _sem(state):
        """Semantic projection of a cluster state for hashing (see
        canon.sem_state; kept as a method for back-compat)."""
        return canon.sem_state(state)

    def canon(self) -> dict:
        return canon.world_canon(self)

    def digest(self) -> str:
        return canon.digest_of(canon.world_canon(self))


# ---------------------------------------------------------------------------
# the explorer


@dataclass
class MCResult:
    config: str
    nodes: int = 0            # states EXPANDED (popped from the queue)
    transitions: int = 0
    depth_reached: int = 0
    seconds: float = 0.0
    complete: bool = True     # False when max_nodes truncated the search
    violations: list = field(default_factory=list)
    states: int = 0           # distinct semantic states DISCOVERED
    engine: str = "python"

    @property
    def ok(self) -> bool:
        return not self.violations

    @property
    def states_per_sec(self) -> float:
        return self.states / self.seconds if self.seconds > 0 else 0.0


async def _replay(config: MCConfig, seq: tuple) -> World:
    w = World(config)
    await w.boot()
    for action in seq:
        await w.do(action)
    return w


def _check_world(loop, w: World) -> list[str]:
    """Safety violations accumulated along the trace plus the liveness
    verdict from this state.  Mutates *w* (the fair schedule runs), so
    callers needing the pre-check world must replay again."""
    bad = list(w.violations + w.store.violations)
    loop.run_until_complete(w.check_liveness())
    bad += [v for v in w.violations + w.store.violations if v not in bad]
    return bad


def explore(config: MCConfig, depth: int | None = None,
            max_nodes: int = 200_000, collect=None,
            progress: bool = False) -> MCResult:
    """BFS over action interleavings with memoization on the canonical
    world digest.  Worlds are rebuilt by replaying the action sequence
    (the machine is deterministic), so counterexamples come out as
    minimal-length traces.  Each discovered state is checked exactly
    once, at discovery; the pop replays it only to expand children.

    *collect*, when given, is called as ``collect(digest, seq, bad)``
    for every discovered semantic state (root included) — the hook the
    differential oracle uses to compare reachable-state sets and
    violation verdicts against the JAX array engine.  *progress* emits
    periodic states/sec + frontier-size lines to stderr."""
    depth = config.depth if depth is None else depth
    res = MCResult(config=config.name)
    t0 = time.monotonic()
    last_report = t0
    logging.getLogger("manatee.state").setLevel(logging.CRITICAL)
    from manatee_tpu.state import machine as _machine
    patched, _machine._sleep = _machine._sleep, _fast_sleep
    try:
        loop = asyncio.new_event_loop()
        try:
            seen: set[str] = set()
            # each queue entry carries the action set captured at
            # discovery (before the liveness fair schedule mutated the
            # world), so a pop never needs to re-replay its own node
            queue: deque[tuple] = deque()
            root = loop.run_until_complete(_replay(config, ()))
            root_digest = root.digest()
            seen.add(root_digest)
            root_actions = root.enabled()
            root_bad = _check_world(loop, root)
            if collect is not None:
                collect(root_digest, (), root_bad)
            if _record(res, (), root_bad) and depth > 0:
                queue.append(((), root_actions))
            while queue:
                if res.nodes >= max_nodes:
                    res.complete = False
                    break
                seq, actions = queue.popleft()
                res.nodes += 1
                if progress and time.monotonic() - last_report >= 2.0:
                    last_report = time.monotonic()
                    el = last_report - t0
                    print("[modelcheck %s/python] states=%d frontier=%d "
                          "depth<=%d %.0f states/s"
                          % (config.name, len(seen), len(queue),
                             res.depth_reached, len(seen) / el),
                          file=sys.stderr, flush=True)
                for action in actions:
                    res.transitions += 1
                    child_seq = seq + (action,)
                    child = loop.run_until_complete(
                        _replay(config, child_seq))
                    d = child.digest()
                    if d in seen:
                        continue
                    seen.add(d)
                    res.depth_reached = max(res.depth_reached,
                                            len(child_seq))
                    child_actions = child.enabled()
                    bad = _check_world(loop, child)
                    if collect is not None:
                        collect(d, child_seq, bad)
                    ok = _record(res, child_seq, bad)
                    if ok and len(child_seq) < depth:
                        queue.append((child_seq, child_actions))
            res.states = len(seen)
        finally:
            loop.close()
    finally:
        _machine._sleep = patched
    res.seconds = time.monotonic() - t0
    return res


def _record(res: MCResult, seq: tuple, bad: list[str]) -> bool:
    """Record violations for a trace; returns True when clean."""
    if bad:
        res.violations.append({"trace": list(seq), "problems": bad})
        return False
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="exhaustively model-check the cluster state machine")
    ap.add_argument("--config", default="all",
                    choices=[*sorted(CONFIGS), "all"],
                    help="configuration name or 'all'")
    ap.add_argument("--depth", type=int, default=None,
                    help="override the per-config interleaving depth")
    ap.add_argument("--max-nodes", type=int, default=200_000)
    ap.add_argument("--engine", default="python",
                    choices=("python", "jax"),
                    help="python: replay-based BFS (the oracle); jax: "
                         "vectorized frontier exploration on the device "
                         "mesh (docs/modelcheck.md)")
    ap.add_argument("--progress", action="store_true",
                    help="periodic states/sec + frontier-size lines on "
                         "stderr")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON result line per config (the "
                         "CI artifact format)")
    args = ap.parse_args(argv)

    names = sorted(CONFIGS) if args.config == "all" else [args.config]
    rc = 0
    for name in names:
        cfg = CONFIGS[name]
        if args.engine == "jax":
            from manatee_tpu.state import mc_array
            res = mc_array.explore_jax(cfg, depth=args.depth,
                                       max_nodes=args.max_nodes,
                                       progress=args.progress)
        else:
            res = explore(cfg, depth=args.depth,
                          max_nodes=args.max_nodes,
                          progress=args.progress)
        status = "ok" if res.ok else "VIOLATIONS"
        if not res.complete:
            # an incomplete sweep must not read as a pass: the whole
            # point of the tool is exhaustiveness within the bound
            status += "/TRUNCATED"
            rc = 1
        if args.as_json:
            print(json.dumps({
                "config": name, "engine": res.engine, "ok": res.ok,
                "complete": res.complete, "nodes": res.nodes,
                "states": res.states, "transitions": res.transitions,
                "depth": res.depth_reached,
                "seconds": round(res.seconds, 3),
                "states_per_sec": round(res.states_per_sec, 1),
                "violations": len(res.violations),
            }))
        else:
            print("%-10s %-10s nodes=%-6d transitions=%-7d depth=%d  "
                  "%.1fs  (%s)"
                  % (name, status, res.nodes, res.transitions,
                     res.depth_reached, res.seconds, cfg.description))
        for v in res.violations[:5]:
            rc = 1
            print("  trace: %s" % (v["trace"],))
            for p in v["problems"]:
                print("    - %s" % p)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
