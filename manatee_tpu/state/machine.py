"""PeerStateMachine — the topology decision engine.

The reference outsources this to the `manatee-state-machine` dependency
(consumed at lib/shard.js:59-71); its behavior is re-derived here from the
observable schema, the history annotations (lib/adm.js:2296-2416), the
man-page promote semantics (docs/man/manatee-adm.md:346-419), the user
guide (docs/user-guide.md:69-90, 330-400), and the integration scenarios
(test/integ.test.js).

Inputs: the consensus manager's events ('init', 'activeChange',
'clusterStateChange' — lib/zookeeperMgr.js:44-52) and the PG manager's
'init' event (lib/postgresMgr.js:401-421).  Outputs:
``zk.put_cluster_state()`` and ``pg.reconfigure()/stop()``.

Decision rules:

* BOOTSTRAP — no cluster state yet:
  - singleton (ONWM): the configured peer writes gen-0 state with itself
    as primary, no sync, and an auto-freeze (moving ONWM->HA requires an
    explicit unfreeze, docs/user-guide.md:367-387);
  - normal: the peer with the LOWEST election sequence declares the
    cluster once >= 2 peers are present: primary = itself, sync = next
    in election order, rest = asyncs; generation 0, initWal '0/0000000'
    (the same initial shape state-backfill writes, lib/adm.js:1266-1276).

* PRIMARY duties (docs/user-guide.md:86-90 "the primary manages
  topology"): appoint a replacement sync from the asyncs when the sync
  dies (generation bump, initWal = its current xlog); add newly-joined
  peers as asyncs and remove dead asyncs (no bump); act on promote
  requests for asyncs.

* SYNC duties: take over when the primary dies (generation bump, old
  primary -> deposed, first async -> new sync), but ONLY if its own xlog
  has reached state.initWal (it actually replicated from this
  generation); act on a promote request naming itself (deposes a live
  primary).

* FROZEN clusters make no automatic transitions (docs/user-guide.md
  freeze section).

* A peer that finds itself deposed stops PostgreSQL and waits for the
  operator (docs/user-guide.md:337-365).  In ONWM, a peer that is not
  the primary shuts down (docs/user-guide.md:369-372).
"""

from __future__ import annotations

import asyncio
import datetime
import logging
import time
from typing import Callable

from manatee_tpu import faults
from manatee_tpu.coord.api import (
    BadVersionError,
    NodeExistsError,
)
from manatee_tpu.obs import (
    bind_parent,
    bind_trace,
    get_journal,
    get_registry,
    get_span_store,
    hlc_now,
    merge_remote,
    new_trace_id,
    span,
)
from manatee_tpu.state.types import (
    INITIAL_WAL,
    ClusterState,
    compare_lsn,
    frozen,
    peer_info_from_active,
    role_of,
)

log = logging.getLogger("manatee.state")

RETRY_DELAY = 1.0

_REG = get_registry()
# durable state writes by this peer (was the status server's ad-hoc
# listener counter; same exported name, now registry-owned)
_TRANSITIONS = _REG.counter(
    "state_transitions_total", "durable state writes made by this peer")
_TRANSITION_DUR = _REG.histogram(
    "transition_write_duration_seconds",
    "latency of the durable cluster-state CAS write")
# THE headline SLI: primary-loss-detection -> new-primary-writable,
# observed by the taking-over sync (detection stamped in _sync_duties,
# completion on the PG manager's 'writable' event)
# Buckets resized for the sub-second regime the bench now lives in
# (~0.5-0.8s end to end; the in-shard portion is tens of ms): the
# original grid was cut for the 30s reference budget and lumped every
# modern failover into its first two buckets.  Name and unit are
# unchanged, so no deprecated alias is owed under the PR 1 naming
# contract; the tail keeps the old coarse steps so a restore-bound
# failover still lands in a finite bucket.
_FAILOVER_DUR = _REG.histogram(
    "failover_duration_seconds",
    "primary loss detected by the sync until the new primary re-enabled "
    "writes",
    buckets=(0.05, 0.1, 0.15, 0.25, 0.4, 0.6, 0.8, 1.0, 1.5, 2.5, 5.0,
             10.0, 30.0, 60.0, 120.0, 300.0))


from manatee_tpu.utils import iso_ms as _now_iso  # noqa: E402

# Injection point for the model checker: explore() swaps this for a
# zero-delay sleep so retry/backoff paths run at full speed WITHOUT
# monkeypatching the process-global asyncio.sleep (which would silently
# strip delays from unrelated asyncio code in the same process).
_sleep = asyncio.sleep


def _retry_backoff(op: str):
    """A jittered-backoff helper whose sleeps route through the
    swappable :data:`_sleep`, so the model checker's zero-delay
    exploration still covers every retry path at full speed."""
    from manatee_tpu.utils.retry import Backoff
    return Backoff(op, base=RETRY_DELAY, cap=5 * RETRY_DELAY,
                   sleep_fn=lambda d: _sleep(d))


def _iso_to_ts(s: str) -> float:
    try:
        return datetime.datetime.fromisoformat(
            s.replace("Z", "+00:00")).timestamp()
    except ValueError:
        return 0.0


class PeerStateMachine:
    def __init__(self, *, zk, pg, self_info: dict,
                 singleton: bool = False,
                 takeover_grace: float = 0.0):
        """*zk* is a ConsensusMgr-shaped object (on/active/cluster_state/
        put_cluster_state); *pg* provides async reconfigure(cfg), stop(),
        get_xlog_location() (the pginterface of lib/shard.js:59-71);
        *self_info* is this peer's PeerInfo dict.

        *takeover_grace*: seconds after our own coordination init during
        which the sync will NOT treat the primary's absence as death.
        On a cold start the primary may simply not have joined yet —
        absence observed for less than a session timeout is not evidence
        of failure.  Wire it to the session timeout."""
        self.zk = zk
        self.pg = pg
        self.self_info = self_info
        self.self_id = self_info["id"]
        self.singleton = singleton
        self.takeover_grace = takeover_grace
        self._boot_time: float | None = None
        # peer ids seen alive in membership since our own init: a
        # disappearance we *witnessed* is death evidence (the failure
        # detector expired it while we watched), so the cold-start
        # absence-isn't-death grace does not apply to it
        self._witnessed: set[str] = set()

        self._zk_ready = False
        self._pg_ready = False
        self._closed = False
        self._notified_role: str | None = None
        self._kick = asyncio.Event()
        self._worker_task: asyncio.Task | None = None
        self._pg_task: asyncio.Task | None = None
        self._pg_target: dict | None = None
        self._pg_applied: dict | None = None
        # jittered retry schedules (reset on success): consecutive
        # failures back off instead of hammering a struggling database
        # or coordination service at a fixed cadence
        self._eval_retry = _retry_backoff("state.evaluate")
        self._pg_retry = _retry_backoff("pg.reconfigure")
        self._listeners: dict[str, list[Callable]] = {}
        # failover SLI bookkeeping: monotonic stamp of the moment this
        # peer (as sync) detected the primary's loss, and the trace id
        # of the takeover, cleared when the new primary is writable
        self._failover_t0: float | None = None
        self._failover_trace: str | None = None
        # the ROOT span of the failover tree: opened at loss detection,
        # closed when writes re-enable (the same window the SLI
        # histogram observes) — `manatee-adm trace` hangs the whole
        # cross-peer takeover under it
        self._failover_span = None
        # last foreign transition span we reacted to, so exactly one
        # state.evaluate span is recorded per observed transition (not
        # one per worker kick)
        self._reacted_span: str | None = None
        # the write-enable gate of an in-flight overlapped takeover:
        # created at promote start, opened when the CAS write lands,
        # reused across takeover retries so the running reconfigure is
        # not restarted per attempt
        self._takeover_gate: asyncio.Event | None = None

        zk.on("init", self._on_zk_init)
        zk.on("activeChange", self._on_active_change)
        zk.on("clusterStateChange", self._on_cluster_state)
        zk.on("sessionRebuilt", self._on_session_rebuilt)
        # 'writable' fires when the PG manager re-enables writes after
        # the downstream catches up — the end of the failover SLI.
        # getattr-guarded: unit-test fakes implement only the pg calls
        # the decision procedure needs.
        pg_on = getattr(pg, "on", None)
        if callable(pg_on):
            pg_on("writable", self._on_pg_writable)

    # ---- events out (role changes, shutdown requests) ----

    def on(self, event: str, cb: Callable) -> None:
        self._listeners.setdefault(event, []).append(cb)

    def _emit(self, event: str, payload=None) -> None:
        for cb in self._listeners.get(event, []):
            try:
                cb(payload)
            except Exception:
                log.exception("listener for %s failed", event)

    # ---- events in ----

    # Events only kick the worker; the evaluation reads state+version+
    # actives from the consensus manager in one event-loop step so the
    # CAS version always matches the snapshot the decision was computed
    # from.

    def _witness(self, actives: list[dict] | None) -> None:
        self._witnessed.update(a["id"] for a in actives or [])

    def _on_zk_init(self, payload: dict) -> None:
        self._zk_ready = True
        if self._boot_time is None:
            self._boot_time = asyncio.get_event_loop().time()
        self._witness((payload or {}).get("active"))
        self.kick()

    def _on_session_rebuilt(self, payload: dict) -> None:
        # after a session expiry/rebuild the absence-isn't-death grace
        # must re-arm: everyone just re-registered from scratch, so
        # prior sightings are void — but the rebuilt membership snapshot
        # counts as a fresh sighting (like the init payload), or a
        # primary that re-registered and later dies would wrongly get
        # the cold-start grace
        self._boot_time = asyncio.get_event_loop().time()
        self._witnessed.clear()
        self._witness((payload or {}).get("active"))
        # the failover clock rests on witnessed-death evidence, which a
        # rebuilt session voids along with the sightings themselves
        self._abort_failover_span("session rebuilt")
        self._failover_t0 = None
        self._failover_trace = None
        self.kick()

    def _on_active_change(self, actives: list[dict]) -> None:
        self._witness(actives)
        self.kick()

    def _on_cluster_state(self, _state: ClusterState) -> None:
        self.kick()

    @property
    def _state(self) -> ClusterState | None:
        return self.zk.cluster_state

    @property
    def _actives(self) -> list[dict]:
        return self.zk.active

    def pg_init(self) -> None:
        """Called once the PG manager is constructed and has reported its
        initial status (the 'init' event, lib/postgresMgr.js:401-421)."""
        self._pg_ready = True
        self.kick()

    # ---- lifecycle ----

    def start(self) -> None:
        if self._worker_task is None:
            self._worker_task = asyncio.create_task(self._worker())

    async def close(self) -> None:
        self._closed = True
        self._abort_failover_span("shutdown")
        self._kick.set()
        for t in (self._worker_task, self._pg_task):
            if t:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass       # the cancel we just requested
                except Exception:
                    pass       # a dying worker's last error is moot

    def kick(self) -> None:
        self._kick.set()

    def debug_state(self) -> dict:
        """Introspection for the status server (lib/shard.js:74-76)."""
        return {
            "id": self.self_id,
            "singleton": self.singleton,
            "role": role_of(self._state, self.self_id),
            "zkReady": self._zk_ready,
            "pgReady": self._pg_ready,
            "active": self._actives,
            "clusterState": self._state,
            "pgTarget": self._strip_cfg(self._pg_target),
            "pgApplied": self._strip_cfg(self._pg_applied),
        }

    async def _worker(self) -> None:
        while not self._closed:
            await self._kick.wait()
            self._kick.clear()
            try:
                await self._evaluate()
                self._eval_retry.reset()
            except asyncio.CancelledError:
                return
            except BadVersionError:
                # lost a CAS race; the watch will deliver the winning
                # state and re-kick us
                log.info("cluster-state CAS conflict; deferring")
            except Exception:
                log.exception("state machine evaluation failed")
                await self._eval_retry.sleep()
                self._kick.set()

    # ---- the decision procedure ----

    async def _evaluate(self) -> None:
        if not (self._zk_ready and self._pg_ready):
            return
        # consistent snapshot: state, its CAS version, and membership read
        # in the same event-loop step
        st = self.zk.cluster_state
        ver = self.zk.cluster_state_version
        actives = self.zk.active

        if st is None:
            await self._bootstrap(actives)
            return

        my_role = role_of(st, self.self_id)
        # react under the trace AND parent span of the transition that
        # produced this state: the pg reconfigure (and its logs/journal
        # events/spans) on EVERY peer then correlates with — and nests
        # under — the initiating write.  New transitions we decide
        # below mint their own fresh ids in _write_state.
        # fold the writer's HLC stamp before reacting: every record the
        # reaction produces then causally follows the state write, even
        # when our wall clock lags the writer's (degrades to wall-clock
        # ordering on merge failure, never blocks the evaluation)
        await merge_remote(st.get("hlc"))
        with bind_trace(st.get("trace")), bind_parent(st.get("span")):
            fresh = (st.get("span") is not None
                     and st.get("span") != self._reacted_span)
            if fresh:
                # exactly one evaluate span per observed transition per
                # peer (the worker re-kicks far more often than the
                # state changes); everything the reaction spawns —
                # the pg reconfigure task included — parents under it
                self._reacted_span = st.get("span")
                with span("state.evaluate", role=my_role or "none",
                          generation=st.get("generation")):
                    await self._react(st, ver, actives, my_role)
            else:
                await self._react(st, ver, actives, my_role)

    async def _react(self, st: ClusterState, ver: int | None,
                     actives: list[dict], my_role: str | None) -> None:
        self._notify_role(my_role, st)

        if st.get("oneNodeWriteMode") and my_role != "primary":
            # ONWM: foreign peers shut down
            # (docs/user-guide.md:369-372)
            log.warning("cluster is in one-node-write mode and we "
                        "are not the primary; shutting down")
            await self._apply_pg({"role": "none"})
            return

        if my_role == "primary":
            await self._apply_pg(self._pg_config_for(st, "primary"))
            await self._primary_duties(st, ver, actives)
        elif my_role == "sync":
            acted = await self._sync_duties(st, ver, actives)
            if not acted:
                await self._apply_pg(self._pg_config_for(st, "sync"))
        elif my_role == "async":
            await self._apply_pg(self._pg_config_for(st, "async"))
        elif my_role == "deposed":
            await self._apply_pg({"role": "none", "deposed": True})
        else:
            # unassigned: wait for the primary to adopt us
            await self._apply_pg({"role": "none"})

    def _notify_role(self, my_role: str | None, st: ClusterState) -> None:
        """Emit role-transition events ONCE per transition."""
        key = my_role
        if st.get("oneNodeWriteMode") and my_role != "primary":
            key = "onwm-foreign"
        if key == self._notified_role:
            return
        self._notified_role = key
        if key not in ("sync", "primary") and \
                self._failover_t0 is not None:
            # demoted (async/deposed/none) while a failover clock was
            # running: this peer can no longer complete the takeover it
            # detected, and a 'writable' event in some far-future
            # primary life must not observe a bogus duration
            get_journal().record("failover.aborted",
                                 trace_id=self._failover_trace,
                                 why="role became %s" % (key or "none"))
            self._abort_failover_span("role became %s" % (key or "none"))
            self._failover_t0 = None
            self._failover_trace = None
        get_journal().record("role.change", role=key or "none",
                             generation=st.get("generation"))
        if key == "deposed":
            log.warning("we are deposed; stopping postgres and waiting "
                        "for operator rebuild")
            self._emit("deposed", None)
        elif key == "onwm-foreign":
            self._emit("shutdown", "onwm-foreign-peer")
        self._emit("roleChange", key)

    # -- bootstrap --

    async def _bootstrap(self, actives: list[dict]) -> None:
        ids = [a["id"] for a in actives]
        if self.self_id not in ids:
            return
        if self.singleton:
            state = {
                "generation": 0,
                "initWal": INITIAL_WAL,
                "primary": self.self_info,
                "sync": None,
                "async": [],
                "deposed": [],
                "oneNodeWriteMode": True,
                "freeze": {"date": _now_iso(),
                           "reason": "one-node-write mode setup"},
            }
            await self._write_state(state, "singleton setup", None)
            return
        # normal mode: lowest election sequence declares, needs a sync
        by_seq = sorted(actives, key=lambda a: a.get("seq", 1 << 30))
        if len(by_seq) < 2 or by_seq[0]["id"] != self.self_id:
            return
        state = {
            "generation": 0,
            "initWal": INITIAL_WAL,
            "primary": peer_info_from_active(by_seq[0]),
            "sync": peer_info_from_active(by_seq[1]),
            "async": [peer_info_from_active(a) for a in by_seq[2:]],
            "deposed": [],
        }
        await self._write_state(state, "cluster setup", None)

    # -- primary --

    async def _primary_duties(self, st: ClusterState, ver: int | None,
                              actives: list[dict]) -> None:
        if frozen(st):
            return
        alive = {a["id"] for a in actives}

        if await self._handle_promote_as_primary(st, ver, alive):
            return

        if st.get("oneNodeWriteMode"):
            return

        asyncs = list(st.get("async") or [])
        alive_asyncs = [a for a in asyncs if a["id"] in alive]
        unassigned = [a for a in actives
                      if role_of(st, a["id"]) is None]

        sync = st.get("sync")
        if sync is None or sync["id"] not in alive:
            # need a replacement sync: prefer an alive async, else an
            # unassigned joiner ("sync added", lib/adm.js:2349-2358)
            if alive_asyncs:
                cand = alive_asyncs[0]
                rest = [a for a in asyncs if a["id"] != cand["id"]]
            elif unassigned:
                cand = peer_info_from_active(unassigned[0])
                rest = asyncs
            else:
                return  # nothing to appoint; wait for a joiner
            new = dict(st)
            new["generation"] = st["generation"] + 1
            new["initWal"] = await self.pg.get_xlog_location()
            new["sync"] = cand
            new["async"] = [a for a in rest if a["id"] in alive]
            await self._write_state(
                new, "appointed new sync %s" % cand["id"], ver)
            return

        # prune dead asyncs (no generation bump)
        if len(alive_asyncs) != len(asyncs):
            new = dict(st)
            new["async"] = alive_asyncs
            await self._write_state(new, "removed dead asyncs", ver)
            return

        # adopt unassigned joiners as asyncs (no generation bump)
        if unassigned:
            new = dict(st)
            new["async"] = asyncs + [peer_info_from_active(a)
                                     for a in unassigned]
            await self._write_state(
                new, "adopted asyncs %s"
                % [a["id"] for a in unassigned], ver)
            return

    async def _handle_promote_as_primary(self, st: ClusterState,
                                         ver: int | None,
                                         alive: set) -> bool:
        pr = st.get("promote")
        if not pr or pr.get("role") != "async":
            return False
        if pr.get("generation") != st.get("generation"):
            return False
        if _iso_to_ts(pr.get("expireTime", "")) < \
                datetime.datetime.now(datetime.timezone.utc).timestamp():
            return False
        asyncs = list(st.get("async") or [])
        idx = pr.get("asyncIndex", 0)
        if idx >= len(asyncs) or asyncs[idx]["id"] != pr.get("id"):
            return False  # topology moved; ignore the request
        if asyncs[idx]["id"] not in alive:
            return False
        new = dict(st)
        new.pop("promote", None)
        if idx == 0:
            # first async -> sync; old sync -> first async (gen bump:
            # sync changed, docs/man/manatee-adm.md:363-365)
            old_sync = st.get("sync")
            if old_sync is None:
                return False
            new["generation"] = st["generation"] + 1
            new["initWal"] = await self.pg.get_xlog_location()
            new["sync"] = asyncs[0]
            new["async"] = [old_sync] + asyncs[1:]
        else:
            # move up one position in the async chain (no data-path
            # impact, docs/man/manatee-adm.md:366)
            asyncs[idx - 1], asyncs[idx] = asyncs[idx], asyncs[idx - 1]
            new["async"] = asyncs
        await self._write_state(new, "acted on promote request", ver)
        return True

    # -- sync --

    async def _sync_duties(self, st: ClusterState, ver: int | None,
                           actives: list[dict]) -> bool:
        """Returns True if a takeover happened (state write succeeded)."""
        if frozen(st):
            return False
        alive = {a["id"] for a in actives}
        primary_alive = st["primary"]["id"] in alive

        pr = st.get("promote")
        promote_me = (
            pr is not None
            and pr.get("role") == "sync"
            and pr.get("id") == self.self_id
            and pr.get("generation") == st.get("generation")
            and _iso_to_ts(pr.get("expireTime", "")) >
            datetime.datetime.now(datetime.timezone.utc).timestamp())

        if primary_alive and not promote_me:
            if self._failover_t0 is not None:
                # the primary flapped back before we took over: the
                # detection was not a failover after all
                get_journal().record("failover.aborted",
                                     trace_id=self._failover_trace,
                                     primary=st["primary"]["id"])
                self._abort_failover_span("primary flapped back")
                self._failover_t0 = None
                self._failover_trace = None
            return False

        if not primary_alive and self._failover_t0 is None \
                and st["primary"]["id"] in self._witnessed:
            # SLI clock starts: we watched this primary die (witnessed
            # membership expiry), and it stops when the new primary
            # re-enables writes (_on_pg_writable)
            self._failover_t0 = time.monotonic()
            self._failover_trace = new_trace_id()
            # the ROOT of the cross-peer failover tree: everything the
            # takeover causes — the durable write, every peer's
            # reconfigure, the catchup wait — nests under this span,
            # and its duration IS the SLI window
            self._failover_span = get_span_store().start(
                "failover", trace_id=self._failover_trace, root=True,
                old_primary=st["primary"]["id"],
                generation=st.get("generation"))
            get_journal().record("failover.detected",
                                 trace_id=self._failover_trace,
                                 primary=st["primary"]["id"],
                                 generation=st.get("generation"))

        if not primary_alive and not promote_me and self._boot_time \
                and st["primary"]["id"] not in self._witnessed:
            # cold-start grace: shortly after boot, the primary's absence
            # may mean it has not re-joined yet, not that it died.  Only
            # for primaries we never saw alive — a disappearance we
            # witnessed (present in membership, then expired) is death.
            elapsed = asyncio.get_event_loop().time() - self._boot_time
            if elapsed < self.takeover_grace:
                delay = self.takeover_grace - elapsed + 0.05
                log.info("primary absent %0.1fs after boot; deferring "
                         "takeover %0.1fs (cold-start grace)",
                         elapsed, delay)
                loop = asyncio.get_event_loop()
                loop.call_later(delay, self.kick)
                return False

        # safety: never take over unless our xlog reached this
        # generation's initWal — otherwise we never replicated from this
        # primary and our database may predate it (docs/xlog-diverge.md)
        my_xlog = await self.pg.get_xlog_location()
        try:
            if compare_lsn(my_xlog, st.get("initWal", INITIAL_WAL)) < 0:
                log.warning(
                    "declining takeover: xlog %s behind initWal %s",
                    my_xlog, st.get("initWal"))
                return False
        except ValueError:
            log.warning("declining takeover: bad xlog %r", my_xlog)
            return False

        asyncs = list(st.get("async") or [])
        alive_asyncs = [a for a in asyncs if a["id"] in alive]
        new_sync = alive_asyncs[0] if alive_asyncs else None
        new = {
            "generation": st["generation"] + 1,
            "initWal": my_xlog,
            "primary": st["sync"],
            "sync": new_sync,
            "async": [a for a in asyncs
                      if new_sync is None or a["id"] != new_sync["id"]],
            "deposed": (st.get("deposed") or []) + [st["primary"]],
        }
        why = ("promote request" if promote_me else "primary death")
        # the takeover rides the trace minted at loss detection, so the
        # detection, the durable write, and the pg promotion all carry
        # one id across the journal and the logs — and parent under the
        # failover root span, so `manatee-adm trace` shows one tree.
        # No failover root (promote request; unwitnessed death): the
        # transition must root its own trace, or the ambient evaluate
        # span — which belongs to the PREVIOUS transition's trace —
        # leaks in as a cross-trace parent and the tree looks orphaned.
        tid = self._failover_trace or new_trace_id()
        parent = (self._failover_span.span_id
                  if self._failover_span is not None else None)
        with bind_trace(tid), bind_parent(parent):
            get_journal().record("takeover.begin", why=why,
                                 old_primary=st["primary"]["id"],
                                 new_generation=new["generation"])
            # OVERLAPPED TAKEOVER: the pg promotion starts while the
            # durable CAS write is still in flight — the two stages
            # are independent until write-enable.  Write authority is
            # NOT weakened: the promoted database stays read-only
            # until the commit gate opens, and the gate opens only
            # after the CAS write lands (the catchup watcher awaits it
            # even when the downstream is already caught up).  A
            # retried takeover (CAS fault, conflict re-drive) reuses
            # the SAME gate object so the in-flight reconfigure is
            # neither restarted nor orphaned.
            gate = self._takeover_gate
            if gate is None or gate.is_set():
                gate = self._takeover_gate = asyncio.Event()
            cfg = self._pg_config_for(new, "primary")
            cfg["commitGate"] = gate
            await self._apply_pg(cfg)
            if not await self._write_state(new, "takeover (%s)" % why,
                                           ver, trace_id=tid,
                                           root=parent is None):
                # lost the race (e.g. an operator freeze landed first):
                # withdraw the optimistic reconfigure — the gate never
                # opens, so no write was ever enabled.  The retract
                # cannot UNDO a pg_promote that already executed: if
                # the winner's state still names us sync, the promoted
                # (non-recovery, still read-only) database cannot
                # re-enter recovery and ends up on the restore path —
                # the deliberate cost of overlapping promote with the
                # CAS write, paid only in the rare lost-race window
                # and never as a write-authority violation.
                self._retract_pg(cfg)
                self._takeover_gate = None
                return False
            # the takeover is durable; we are the primary now — open
            # the write-enable gate
            gate.set()
            self._takeover_gate = None
        return True

    # -- shared helpers --

    async def _write_state(self, state: ClusterState, why: str,
                           expected_version: int | None, *,
                           trace_id: str | None = None,
                           root: bool | None = None) -> bool:
        """CAS-write; returns False when the write lost a race.

        Every durable transition mints a trace id (or rides the one the
        caller minted, e.g. at failover detection) and embeds it in the
        state object — along with the transition SPAN's id — so peers
        reacting to the watch (and the coordd that stored it) log,
        journal, and span under the same identity, parented to this
        write."""
        # the decided-transition seam: error/delay/stall here models a
        # peer that decides a topology change but cannot commit it (the
        # worker's jittered-backoff retry re-drives the evaluation)
        await faults.point("state.write")
        tid = trace_id or new_trace_id()
        state = dict(state)
        state["trace"] = tid
        journal = get_journal()
        with bind_trace(tid):
            log.info("writing cluster state gen=%s (%s)",
                     state.get("generation"), why)
            # root when WE minted the trace (callers with a same-trace
            # parent — the takeover under its failover root — pass
            # root=False explicitly): the ambient span here is the
            # evaluate span reacting to the PREVIOUS state, and a
            # cross-trace parent link would make this trace's own tree
            # look orphaned.
            with span("state.transition",
                      root=(trace_id is None if root is None
                            else root),
                      why=why,
                      generation=state.get("generation")) as tsp:
                # the embedded span id is what makes a transition's
                # effects on OTHER peers children of this write
                state["span"] = tsp.span_id
                # the written state object is an HLC piggyback
                # boundary: peers reacting to the watch merge this
                # stamp, so their reaction records sort after the
                # write at any wall-clock skew
                state["hlc"] = hlc_now()
                journal.record("transition.begin", why=why,
                               generation=state.get("generation"))
                try:
                    with span("state.cas_write"), \
                            _TRANSITION_DUR.time():
                        await self.zk.put_cluster_state(
                            state, expected_version=expected_version)
                except (BadVersionError, NodeExistsError):
                    log.info("state write lost a race (%s); deferring",
                             why)
                    journal.record("transition.conflict", why=why)
                    tsp.end(status="conflict")
                    # refresh the cached state explicitly: if our watch
                    # was lost, waiting for it would spin on the same
                    # stale snapshot
                    refresh = getattr(self.zk, "refresh_cluster_state",
                                      None)
                    if refresh is not None:
                        try:
                            await refresh()
                        except asyncio.CancelledError:
                            raise
                        except Exception:
                            pass
                    await _sleep(0.05)
                    self.kick()
                    return False
                _TRANSITIONS.inc()
                journal.record("transition.committed", why=why,
                               generation=state.get("generation"))
                self._emit("stateWritten", state)
        self.kick()
        return True

    def _on_pg_writable(self, _standby_id) -> None:
        """PG manager re-enabled writes.  If a failover clock is
        running, this peer just completed a takeover end-to-end: observe
        the headline SLI and close the root span — both cover the same
        detection→writable window, so `manatee-adm trace`'s critical
        path total and the histogram sample agree."""
        if self._failover_t0 is None:
            return
        dur = time.monotonic() - self._failover_t0
        _FAILOVER_DUR.observe(dur)
        get_journal().record("failover.complete",
                             trace_id=self._failover_trace,
                             duration_s=round(dur, 3))
        if self._failover_span is not None:
            self._failover_span.end(duration_s=round(dur, 3))
            self._failover_span = None
        self._failover_t0 = None
        self._failover_trace = None

    def _abort_failover_span(self, why: str) -> None:
        """A failover clock that will never complete must not leave its
        root span open (the leak the chaos suite asserts against)."""
        if self._failover_span is not None:
            self._failover_span.end(status="aborted", why=why)
            self._failover_span = None

    def _pg_config_for(self, st: ClusterState, role: str) -> dict:
        """The reconfigure contract {role, upstream, downstream}
        (lib/postgresMgr.js:758-816)."""
        asyncs = st.get("async") or []
        if role == "primary":
            return {"role": "primary", "upstream": None,
                    "downstream": st.get("sync")}
        if role == "sync":
            return {"role": "sync", "upstream": st.get("primary"),
                    "downstream": asyncs[0] if asyncs else None}
        idx = next(i for i, a in enumerate(asyncs)
                   if a["id"] == self.self_id)
        # the preceding peer in the daisy chain.  A takeover written
        # while every standby candidate was dead leaves sync=None with
        # asyncs listed (the crash sweep's state.write scenario hits
        # exactly this window); the chain then collapses to
        # primary <- async0, and an upstream of None here would boot
        # the async as a NON-recovery database that never streams —
        # a silent permanent wedge
        upstream = (st.get("sync") or st.get("primary")) if idx == 0 \
            else asyncs[idx - 1]
        downstream = asyncs[idx + 1] if idx + 1 < len(asyncs) else None
        return {"role": "async", "upstream": upstream,
                "downstream": downstream}

    @staticmethod
    def _strip_cfg(cfg: dict | None) -> dict | None:
        """The reconfigure contract minus the overlapped-takeover gate:
        equality checks (and debug output) must see the same target
        whether or not a commit gate rides along, or a committed
        takeover's follow-up evaluation would cancel its own in-flight
        promote just to restart it gateless."""
        if cfg is None or "commitGate" not in cfg:
            return cfg
        return {k: v for k, v in cfg.items() if k != "commitGate"}

    async def _apply_pg(self, cfg: dict) -> None:
        if self._strip_cfg(cfg) == self._strip_cfg(self._pg_target):
            if self._pg_target is not None \
                    and "commitGate" in self._pg_target \
                    and "commitGate" not in (cfg or {}):
                # an UNGATED request for the same config can only come
                # from reacting to the durable state itself — exactly
                # the authority the gate guards.  Open any still-closed
                # gate rather than leaving a gated catchup waiting on a
                # takeover that concluded through another write (e.g. a
                # lost CAS race whose winner still names us primary).
                self._pg_target["commitGate"].set()
            return
        self._pg_target = cfg
        if self._pg_task and not self._pg_task.done():
            # cancel the in-flight transition (a restore can take hours
            # and must not wedge the next topology change,
            # lib/postgresMgr.js:1263-1275)
            self._pg_task.cancel()
        self._pg_task = asyncio.create_task(self._run_pg(cfg))

    def _retract_pg(self, cfg: dict) -> None:
        """Withdraw an optimistic reconfigure whose durable write lost
        its race: cancel the in-flight task (if it is still ours) and
        clear the target so the winner's state re-drives pg.  Compared
        by CONTENT (gate stripped): a retried takeover's cfg is a
        fresh dict while the target still holds the first attempt's —
        identity would no-op exactly when the retract matters most."""
        if self._pg_target is None or \
                self._strip_cfg(self._pg_target) != self._strip_cfg(cfg):
            return               # something else took over the target
        self._pg_target = None
        if self._pg_task and not self._pg_task.done():
            self._pg_task.cancel()
        self.kick()

    async def _run_pg(self, cfg: dict) -> None:
        try:
            await self.pg.reconfigure(cfg)
            self._pg_applied = cfg
            self._pg_retry.reset()
            self._emit("pgApplied", cfg)
        except asyncio.CancelledError:
            raise
        except Exception:
            log.exception("pg reconfigure to %s failed; will retry",
                          cfg.get("role"))
            self._pg_target = None
            await self._pg_retry.sleep()
            self.kick()
