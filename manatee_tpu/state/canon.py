"""Shared semantic-state canonicalization for the model-checker engines.

Both explorers — the Python BFS in ``modelcheck.py`` and the JAX array
engine in ``mc_array.py`` — memoize on "the semantic state of the whole
checker world".  If the two engines computed that quotient separately
they could silently disagree about what "same state" means, and the
differential oracle would be comparing apples to oranges.  This module
is the single definition:

* :func:`sem_state` — the semantic projection of a cluster-state dict
  (the per-transition ``trace``/``span`` obs ids are quotiented out;
  hashing either would make every logically-identical state look fresh
  and defeat memoization, the PR 3 fix);
* :func:`world_canon` — the full canonical dict of a checker ``World``
  (durable state, election order, kill/rejoin budgets, and every peer's
  liveness/partition/xlog/view-staleness/pg-target/role-note);
* :func:`digest_of` — the canonical hash over that dict;
* :data:`CATEGORIES` / :func:`classify` — the stable violation-verdict
  vocabulary the differential comparison matches on (the Python engine
  produces prose, the array engine produces bitmasks; both map here).
"""

from __future__ import annotations

import hashlib
import json

# obs metadata embedded in durable states by _write_state: unique per
# write, semantically irrelevant
OBS_KEYS = frozenset(("trace", "span", "hlc"))


def sem_state(state):
    """Semantic projection of a cluster state for hashing."""
    if not isinstance(state, dict) or not (OBS_KEYS & state.keys()):
        return state
    return {k: v for k, v in state.items() if k not in OBS_KEYS}


def world_canon(world) -> dict:
    """The canonical (JSON-able) semantic state of a checker World.

    Everything the explorer's behavior can depend on is here; anything
    quotiented out (absolute CAS versions beyond the currency bit,
    trace/span ids, election seq numbers beyond their order, commit-gate
    identities) is provably irrelevant to future transitions."""
    peers = {}
    for name in sorted(world.peers):
        p = world.peers[name]
        sm = p.sm
        peers[name] = {
            "alive": p.alive,
            "part": p.partitioned,
            "xlog": p.pg.xlog,
            # version staleness and actives staleness diverge (a kill
            # changes actives without bumping the state version), and
            # CAS outcomes depend on the version bit alone — hash them
            # separately
            "ver_current": (p.zk.cluster_state_version
                            == world.store.version),
            "actives_current": ([a["id"] for a in p.zk.active]
                                == [a["id"] for a in
                                    world.store.actives]),
            "evaled_current": p.eval_epoch >= p.view_epoch,
            "view": sem_state(p.zk.cluster_state),
            "view_actives": [a["id"] for a in p.zk.active],
            # strip the overlapped-takeover commit gate: an Event is
            # not JSON, and its identity is fresh per attempt
            "target": sm._strip_cfg(sm._pg_target),
            "applied": sm._strip_cfg(sm._pg_applied),
            "role_note": sm._notified_role,
        }
    return {
        "state": sem_state(world.store.state),
        "actives": [a["id"] for a in world.store.actives],
        "kills": world.kills,
        "rejoins": world.rejoins,
        "peers": peers,
    }


def digest_of(canon: dict) -> str:
    return hashlib.md5(
        json.dumps(canon, sort_keys=True).encode()).hexdigest()


# ---------------------------------------------------------------------------
# violation-verdict vocabulary

# Stable category names; the array engine's violation bitmask indexes
# into this tuple, and classify() maps the Python engine's prose onto
# the same names, so verdicts can be compared exactly.
CATEGORIES = (
    "gen_backwards",            # generation decreased (validate + store)
    "iw_backwards",             # initWal decreased (data-loss signature)
    "singleton_transition",     # multi-peer -> ONWM
    "newprim_samegen",          # primary changed without a gen bump
    "prim_not_prev_sync",       # takeover installed a non-sync
    "bump_nochange",            # gen bumped, primary+sync unchanged
    "sync_nobump",              # sync changed without a gen bump
    "frozen_write",             # automatic write on a frozen cluster
    "xlog_behind",              # named primary behind the gen's initWal
    "split_brain",              # un-named peer writable with current view
    "no_fixpoint",              # fair schedule never converged
    "no_cluster",               # no durable state despite live peers
    "dead_primary_not_replaced",
    "no_sync_appointed",
    "role_mismatch",            # pg target != durable role at fixpoint
    "chain",                    # replication daisy chain broken
    "eval_crash",               # evaluation raised unexpectedly
    "settle",                   # pg task failed to settle
    "no_bootstrap",             # bootstrap never declared a cluster
)

CATEGORY_BIT = {name: 1 << i for i, name in enumerate(CATEGORIES)}

# ordered (substring, category) — first match wins
_RULES = (
    ("generation went backwards", "gen_backwards"),
    ("initWal went backwards", "iw_backwards"),
    ("unparseable initWal", "iw_backwards"),
    ("singleton transition is unsupported", "singleton_transition"),
    ("new primary but same generation", "newprim_samegen"),
    ("new primary was not previous sync", "prim_not_prev_sync"),
    ("generation bumped but primary and sync", "bump_nochange"),
    ("sync changed without generation bump", "sync_nobump"),
    ("while the cluster was frozen", "frozen_write"),
    ("behind initWal", "xlog_behind"),
    ("configured primary with a current view", "split_brain"),
    ("fair schedule never reached fixpoint", "no_fixpoint"),
    ("no cluster despite", "no_cluster"),
    ("not replaced by live sync", "dead_primary_not_replaced"),
    ("no live sync despite", "no_sync_appointed"),
    ("pg target", "role_mismatch"),
    ("downstream", "chain"),
    ("upstream", "chain"),
    ("evaluation crashed", "eval_crash"),
    ("failed to settle", "settle"),
    ("bootstrap never declared", "no_bootstrap"),
)


def classify(problem: str) -> str:
    """Map a Python-engine violation string to its stable category."""
    for needle, cat in _RULES:
        if needle in problem:
            return cat
    return "other:" + problem[:60]


def classify_all(problems) -> frozenset:
    return frozenset(classify(p) for p in problems)


def mask_to_categories(mask: int) -> frozenset:
    return frozenset(name for name, bit in CATEGORY_BIT.items()
                     if mask & bit)
