"""Cluster-state schema and helpers.

The cluster state is a JSON-shaped dict, exactly the schema the reference
maintains in its versioned ZooKeeper `state` znode (observable at
lib/adm.js:788-819 and lib/adm.js:1915-1928):

    {
      "generation":       int,        # bumps EXACTLY on primary/sync change
      "initWal":          "X/XXXXXXX" # xlog position at generation start
      "primary":          PeerInfo,
      "sync":             PeerInfo | None,
      "async":            [PeerInfo, ...],
      "deposed":          [PeerInfo, ...],
      "oneNodeWriteMode": bool,               # optional
      "freeze":           {"date", "reason"}  # optional / None
      "promote":          {"id", "role", "asyncIndex"?, "generation",
                           "expireTime"}      # optional / None
    }

PeerInfo = {"id": "ip:pgPort:backupPort", "zoneId", "ip", "pgUrl",
"backupUrl"} (built at lib/shard.js:39-54).

Invariants encoded by the reference's history annotator
(lib/adm.js:2296-2416):
  * generation never decreases;
  * a new primary must have been the previous sync, and bumps generation;
  * a generation bump without a primary change means the primary selected
    a new sync;
  * a sync change without a generation bump is an error;
  * multi-peer mode -> singleton mode is an unsupported transition.
"""

from __future__ import annotations


ClusterState = dict   # JSON-shaped; helpers below
PeerInfo = dict

INITIAL_WAL = "0/0000000"


def peer_info_from_active(active: dict) -> PeerInfo:
    """Build the PeerInfo stored in cluster state from an election-member
    record ({id, ...data} as emitted by ConsensusMgr.active)."""
    return {
        "id": active["id"],
        "zoneId": active.get("zoneId", active["id"]),
        "ip": active.get("ip"),
        "pgUrl": active.get("pgUrl"),
        "backupUrl": active.get("backupUrl"),
    }


def role_of(state: ClusterState | None, peer_id: str) -> str | None:
    """'primary' | 'sync' | 'async' | 'deposed' | None."""
    if not state:
        return None
    if state.get("primary") and state["primary"]["id"] == peer_id:
        return "primary"
    if state.get("sync") and state["sync"]["id"] == peer_id:
        return "sync"
    for a in state.get("async") or []:
        if a["id"] == peer_id:
            return "async"
    for d in state.get("deposed") or []:
        if d["id"] == peer_id:
            return "deposed"
    return None


def async_index_of(state: ClusterState, peer_id: str) -> int | None:
    for i, a in enumerate(state.get("async") or []):
        if a["id"] == peer_id:
            return i
    return None


def parse_lsn(lsn: str) -> int:
    """'16/B374D848' -> 64-bit int (pg-lsn parity, used at
    lib/postgresMgr.js:2390-2555 for catch-up checks)."""
    try:
        hi, lo = lsn.strip().split("/")
        return (int(hi, 16) << 32) | int(lo, 16)
    except (ValueError, AttributeError):
        raise ValueError("bad lsn: %r" % (lsn,)) from None


def compare_lsn(a: str, b: str) -> int:
    """-1, 0, 1 as a <, ==, > b."""
    ia, ib = parse_lsn(a), parse_lsn(b)
    return (ia > ib) - (ia < ib)


def frozen(state: ClusterState) -> bool:
    return bool(state.get("freeze"))


def validate_transition(old: ClusterState | None,
                        new: ClusterState) -> list[str]:
    """Check the annotator-encoded invariants; returns a list of violation
    strings (empty = legal).  Used by tests and debug assertions."""
    problems: list[str] = []
    if old is None:
        return problems
    og, ng = old.get("generation", 0), new.get("generation", 0)
    if ng < og:
        problems.append("generation went backwards (%d -> %d)" % (og, ng))
    try:
        if compare_lsn(new.get("initWal", INITIAL_WAL),
                       old.get("initWal", INITIAL_WAL)) < 0:
            # initWal is the WAL position at generation start; a takeover
            # stamps the taker's xlog, which the xlog-diverge guard keeps
            # at/above the previous generation's mark — going backwards
            # means a peer that never replicated this generation seized
            # the primary role (docs/xlog-diverge.md)
            problems.append("initWal went backwards (%s -> %s)"
                            % (old.get("initWal"), new.get("initWal")))
    except ValueError:
        problems.append("unparseable initWal (%r -> %r)"
                        % (old.get("initWal"), new.get("initWal")))
    if not old.get("oneNodeWriteMode") and new.get("oneNodeWriteMode"):
        problems.append("multi-peer -> singleton transition is unsupported")
    op, np_ = old.get("primary"), new.get("primary")
    osync, nsync = old.get("sync"), new.get("sync")
    if op and np_ and op["id"] != np_["id"]:
        if ng == og:
            problems.append("new primary but same generation")
        if osync is None or np_["id"] != osync["id"]:
            problems.append("new primary was not previous sync")
    elif ng > og and not old.get("oneNodeWriteMode"):
        same_sync = (osync is not None and nsync is not None
                     and osync["id"] == nsync["id"])
        if same_sync:
            problems.append("generation bumped but primary and sync "
                            "unchanged")
    elif ng == og:
        sync_changed = ((osync is None) != (nsync is None)
                        or (osync is not None and nsync is not None
                            and osync["id"] != nsync["id"]))
        if sync_changed:
            problems.append("sync changed without generation bump")
    return problems
