"""Admin library — everything behind the manatee-adm CLI.

Reference parity: lib/adm.js (2541 lines).  Implements:

- cluster-details loading: coordination-state read plus per-peer
  PostgreSQL status/lag via direct queries with a 1 s timeout
  (:348-427, :2196-2227), honoring the MANATEE_ADM_TEST_STATE env hook
  that substitutes a canned cluster-details JSON (:662-745);
- the ClusterDetails object (pgs_* fields, :577-985) with error/warning
  derivation including replication-chain verification (loadErrors /
  loadReplErrors, :860-985);
- operations: freeze/unfreeze (:1048-1098), reap (:1108-1146),
  set-onwm (:1148-1209), state-backfill (:1231-1312), promote /
  clear-promote with the 30 s expiry (:1693-2040), rebuild (:1319-1684),
  check-lock (:2049-2086), annotated history (:2088-2162, :2296-2416);
- lag computation helpers (:2504-2541).
"""

from __future__ import annotations

import asyncio
import datetime
import json
import os
from dataclasses import dataclass

from manatee_tpu.coord.api import BadVersionError, CoordClient, \
    NoNodeError, cluster_state_txn
from manatee_tpu.coord.client import mux_handle
from manatee_tpu.obs.causal import hlc_now, hlc_sort_key, \
    merge_remote_sync, observe_peer_clock
from manatee_tpu.pg.engine import PgError, parse_pg_url
from manatee_tpu.state.types import role_of
from manatee_tpu.utils import iso_ms as _now_iso

PG_QUERY_TIMEOUT = 1.0     # lib/adm.js:2203-2205
# failure-prediction score at/above this raises an informational notice
from manatee_tpu.health.telemetry import \
    WARN_THRESHOLD as HEALTH_WARN_THRESHOLD  # noqa: E402
PROMOTE_EXPIRY_S = 30.0    # lib/adm.js:1925-1926
DEFAULT_LAG_TO_IGNORE = 5.0


class AdmError(Exception):
    pass


def load_test_state(value: str) -> "ClusterDetails":
    """MANATEE_ADM_TEST_STATE hook: the env value is either a path to, or
    the inline text of, a canned cluster-details JSON
    (lib/adm.js:662-745)."""
    return ClusterDetails.from_json(
        open(value).read() if os.path.exists(value) else value)


def pg_duration(lag_seconds: float | None) -> str:
    """Human duration like '87m12s' (pgDuration, bin/manatee-adm)."""
    if lag_seconds is None:
        return "-"
    try:
        secs = int(lag_seconds)
    except (TypeError, ValueError):
        return "?"
    if secs < 0:
        return "?"
    out = ""
    days, secs = divmod(secs, 86400)
    hours, secs = divmod(secs, 3600)
    mins, secs = divmod(secs, 60)
    if days:
        out += "%dd" % days
    if hours or days:
        out += "%dh" % hours
    if mins or hours or days:
        out += "%dm" % mins
    out += "%ds" % secs
    return out


@dataclass
class PeerStatus:
    """pgp_* parity (lib/adm.js loadPeer)."""
    ident: dict                       # PeerInfo
    label: str = ""                   # first 8 chars of zoneId
    pgerr: str | None = None          # error string or None
    repl: dict | None = None          # downstream pg_stat_replication row
    lag: float | None = None          # replay lag seconds (standbys)
    online: bool = False
    health_score: float | None = None  # failure-prediction score [0,1]

    def __post_init__(self):
        if not self.label:
            self.label = str(self.ident.get("zoneId", "?"))[:8]

    def to_dict(self) -> dict:
        return {"ident": self.ident, "label": self.label,
                "pgerr": self.pgerr, "repl": self.repl, "lag": self.lag,
                "online": self.online, "health_score": self.health_score}

    @classmethod
    def from_dict(cls, d: dict) -> "PeerStatus":
        return cls(ident=d["ident"], label=d.get("label", ""),
                   pgerr=d.get("pgerr"), repl=d.get("repl"),
                   lag=d.get("lag"), online=d.get("online", False),
                   health_score=d.get("health_score"))


class ClusterDetails:
    """pgs_* parity (ManateeClusterDetails, lib/adm.js:577-985)."""

    def __init__(self, shard: str, state: dict,
                 peer_status: dict[str, PeerStatus]):
        self.shard = shard
        self.state = state
        self.peers: dict[str, PeerStatus] = peer_status
        self.primary = state["primary"]["id"]
        self.sync = state["sync"]["id"] if state.get("sync") else None
        self.asyncs = [a["id"] for a in state.get("async") or []]
        self.deposed = [d["id"] for d in state.get("deposed") or []]
        self.generation = state.get("generation")
        self.initwal = state.get("initWal")
        self.singleton = bool(state.get("oneNodeWriteMode"))
        fr = state.get("freeze")
        self.frozen = bool(fr)
        self.freeze_time = (fr or {}).get("date", "unknown") \
            if self.frozen else None
        self.freeze_reason = (fr or {}).get("reason", "unknown") \
            if self.frozen else None
        self.errors: list[str] = []
        self.warnings: list[str] = []
        # informational only: failure-prediction notices never gate
        # promote nor flip verify's exit code — a probabilistic score
        # must not block the operator who is promoting AWAY from a
        # degrading peer, nor page monitoring on a transient
        self.notices: list[str] = []
        self._load_errors()

    # -- serialization (MANATEE_ADM_TEST_STATE hook) --

    def to_json(self) -> str:
        return json.dumps({
            "shard": self.shard,
            "state": self.state,
            "peers": {k: v.to_dict() for k, v in self.peers.items()},
        }, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ClusterDetails":
        d = json.loads(text)
        return cls(d["shard"], d["state"],
                   {k: PeerStatus.from_dict(v)
                    for k, v in d["peers"].items()})

    # -- error derivation (loadErrors, lib/adm.js:875-927) --

    def _load_errors(self) -> None:
        # failure-prediction early warnings apply in every topology
        # (incl. singleton) — before any early return below
        for ps in self.peers.values():
            if ps.health_score is not None and \
                    ps.health_score >= HEALTH_WARN_THRESHOLD:
                self.notices.append(
                    "peer \"%s\" failure-prediction score %.2f "
                    "(degrading before hard health timeout)"
                    % (ps.label, ps.health_score))

        p = self.peers[self.primary]
        if p.pgerr:
            self.errors.append(
                "cannot query postgres on primary: peer \"%s\": %s"
                % (p.label, p.pgerr))

        if self.singleton:
            if len(self.peers) > 1:
                self.warnings.append(
                    "found %d peers in singleton mode" % len(self.peers))
            return

        if self.sync is None:
            self.errors.append("cluster has no sync peer")
            return
        s = self.peers[self.sync]
        if s.pgerr:
            self.errors.append(
                "cannot query postgres on sync: peer \"%s\": %s"
                % (s.label, s.pgerr))

        if self.deposed:
            self.warnings.append("cluster has a deposed peer")
        if not self.asyncs:
            self.warnings.append("cluster has no async peers")

        if s.pgerr:
            return  # if the sync is down, that's all we can check

        self._repl_errors(p, self.sync, "sync", self.errors)
        self._repl_errors(s, self.asyncs[0] if self.asyncs else None,
                          "async", self.warnings)
        for i, a in enumerate(self.asyncs):
            nxt = self.asyncs[i + 1] if i + 1 < len(self.asyncs) else None
            self._repl_errors(self.peers[a], nxt, "async", self.warnings)

    def _repl_errors(self, peer: PeerStatus, ds_id: str | None,
                     kind: str, errors: list[str]) -> None:
        """(loadReplErrors, lib/adm.js:930-985)"""
        if ds_id is None:
            return
        before = len(errors)
        dspeer = self.peers[ds_id]
        if peer.repl is None:
            errors.append('peer "%s": downstream replication peer not '
                          "connected" % peer.label)
            return
        expected = dspeer.ident["id"]
        found = peer.repl.get("application_name") \
            or peer.repl.get("client_addr")
        if found != expected and found != dspeer.ident.get("ip"):
            errors.append('peer "%s": expected downstream peer to be '
                          '"%s", but found "%s"'
                          % (peer.label, dspeer.label, found))
        if peer.repl.get("state") != "streaming":
            errors.append('peer "%s": downstream replication not yet '
                          'established (expected state "streaming", '
                          'found "%s")'
                          % (peer.label, peer.repl.get("state")))
        if len(errors) > before:
            return
        if peer.repl.get("sync_state") != kind:
            errors.append('peer "%s": expected downstream replication '
                          'to be "%s", but found "%s"'
                          % (peer.label, kind,
                             peer.repl.get("sync_state")))

    def role_of(self, peer_id: str) -> str | None:
        return role_of(self.state, peer_id)


# ---------------------------------------------------------------------------


def history_annotation(state: dict, last: dict | None) -> str:
    """Semantic annotation for one history transition
    (annotateHistoryNode, lib/adm.js:2296-2416)."""
    def zid(p):
        return str(p.get("zoneId", p.get("id", "?")))[:8]

    if last is None:
        if state.get("oneNodeWriteMode"):
            return "cluster setup for singleton (one-node-write) mode"
        return "cluster setup for normal (multi-peer) mode"
    nst, lst = state, last
    if nst.get("generation", 0) < lst.get("generation", 0):
        return "error: gen number went backwards"
    if not lst.get("oneNodeWriteMode") and nst.get("oneNodeWriteMode"):
        return ("error: unsupported transition from multi-peer mode to "
                "singleton (one-node-write) mode")
    if lst.get("oneNodeWriteMode") and not nst.get("oneNodeWriteMode"):
        return ("cluster transitioned from singleton (one-node-write) "
                "mode to multi-peer mode")
    if nst["primary"]["id"] != lst["primary"]["id"]:
        if nst.get("generation") == lst.get("generation"):
            return "error: new primary, but same gen number"
        if lst.get("sync") is None or \
                nst["primary"]["id"] != lst["sync"]["id"]:
            return "error: new primary was not previous sync"
        return "sync (%s) took over as primary (from %s)" % (
            zid(nst["primary"]), zid(lst["primary"]))
    if nst.get("generation", 0) > lst.get("generation", 0):
        if lst.get("sync") is None and not lst.get("oneNodeWriteMode"):
            return 'sync "%s" added' % zid(nst["sync"])
        if nst.get("sync") and lst.get("sync") and \
                nst["sync"]["id"] == lst["sync"]["id"]:
            return ("error: gen number changed, but primary and sync "
                    "did not")
        return "primary (%s) selected new sync (was %s, now %s)" % (
            zid(nst["primary"]), zid(lst["sync"]), zid(nst["sync"]))
    nsync, lsync = nst.get("sync"), lst.get("sync")
    if (nsync is None) != (lsync is None) or \
            (nsync and lsync and nsync["id"] != lsync["id"]):
        return "error: sync changed, but gen number did not"

    changes = []
    if nst.get("freeze") and not lst.get("freeze"):
        changes.append("cluster frozen: %s"
                       % nst["freeze"].get("reason"))
    elif not nst.get("freeze") and lst.get("freeze"):
        changes.append("cluster unfrozen")
    nas = {a["zoneId"]: 1 for a in nst.get("async") or []}
    las = {a["zoneId"]: 1 for a in lst.get("async") or []}
    for z in nas:
        if z not in las:
            changes.append('async "%s" added' % z[:8])
    for z in las:
        if z not in nas:
            changes.append('async "%s" removed' % z[:8])
    nd = {d["zoneId"]: 1 for d in nst.get("deposed") or []}
    ld = {d["zoneId"]: 1 for d in lst.get("deposed") or []}
    for z in nd:
        if z not in ld:
            changes.append('"%s" deposed' % z[:8])
    for z in ld:
        if z not in nd:
            changes.append('"%s" no longer deposed' % z[:8])
    return ", ".join(changes)


# ---------------------------------------------------------------------------


def merge_events(events: list[dict]) -> list[dict]:
    """Merge per-peer journal/span rings into one shard timeline:
    hybrid-logical-clock stamp first (obs/causal.py — every record the
    fleet emits carries one, and the stamps piggyback on every
    boundary the trace id crosses, so cause sorts before effect
    regardless of wall-clock skew), then wall clock, then (peer, seq)
    as the tiebreak.  Records from old peers carry no stamp and fall
    back to their wall time — `hlc_sort_key` slots them in at
    ``(ts*1000, -1)`` so a mixed fleet still merges into one
    deterministic timeline (skew CAN misorder those records, which is
    why the doctor warns when measured skew exceeds
    ``MERGE_SKEW_BOUND_S``).

    The tiebreak matters: two peers' clocks quantize to the same
    millisecond constantly during a failover (the reacting peers all
    journal within the same watch-delivery tick), and without a total
    order the interleaving would depend on fan-out completion order —
    two runs of `manatee-adm events` over the same rings would render
    different timelines.  Within one peer, seq preserves the ring's
    own causality regardless of any clock step between its records."""
    return sorted(events, key=hlc_sort_key)


class AdmClient:
    """Operator-side client: talks to the coordination service and each
    peer's database directly (lib/adm.js:81-209, 2166-2227)."""

    def __init__(self, coord_addr: str, *, base_path: str = "/manatee"):
        """*coord_addr*: 'host:port' or an ensemble connection string
        'h1:p1,h2:p2' (zkCfg.connStr parity)."""
        self.coord_addr = coord_addr
        self.base_path = base_path
        self._client: CoordClient | None = None

    async def __aenter__(self):
        await self.connect()
        return self

    async def __aexit__(self, *exc):
        await self.close()

    async def connect(self) -> None:
        # the process-wide mux pool: concurrent AdmClients in one
        # process (topology fan-outs, harness probes) share one
        # connection and one session.  NOTE the pool keys on (connstr,
        # session params), so an embedding process only shares ITS
        # connection with adm when its session_timeout is also 30 —
        # otherwise adm dials its own, exactly as requested.
        self._client = await asyncio.wait_for(
            mux_handle(self.coord_addr, session_timeout=30), 10)

    async def close(self) -> None:
        if self._client:
            await self._client.close()

    def _shard_path(self, shard: str) -> str:
        return "%s/%s" % (self.base_path, shard)

    # -- reads --

    async def list_shards(self) -> list[str]:
        try:
            return await self._client.get_children(self.base_path)
        except NoNodeError:
            return []

    async def get_state(self, shard: str) -> tuple[dict | None, int]:
        try:
            data, ver = await self._client.get(
                self._shard_path(shard) + "/state")
            return json.loads(data.decode()), ver
        except NoNodeError:
            return None, -1

    async def get_active(self, shard: str) -> list[dict]:
        from manatee_tpu.coord.manager import parse_and_unique_actives
        path = self._shard_path(shard) + "/election"
        try:
            names = await self._client.get_children(path)
        except NoNodeError:
            return []
        actives = parse_and_unique_actives(names)
        for ent in actives:
            try:
                data, _ = await self._client.get(path + "/" + ent["name"])
                ent["data"] = json.loads(data.decode())
            except (NoNodeError, ValueError):
                ent["data"] = {}
        return actives

    async def get_history(self, shard: str) -> list[dict]:
        """[{time, generation, state, annotation}] ordered by sequence
        (lib/adm.js:2088-2162)."""
        path = self._shard_path(shard) + "/history"
        try:
            names = await self._client.get_children(path)
        except NoNodeError:
            return []
        names.sort(key=lambda n: int(n.rsplit("-", 1)[1]))
        out = []
        last_state = None
        for n in names:
            try:
                data, _v, ctime = await self._client.get_full(
                    path + "/" + n)
                state = json.loads(data.decode())
            except (NoNodeError, ValueError):
                continue
            out.append({
                "node": n,
                "zkSeq": int(n.rsplit("-", 1)[1]),
                "time": _now_iso(ctime) if ctime else "?",
                "generation": state.get("generation"),
                "state": state,
                "annotation": history_annotation(state, last_state),
            })
            last_state = state
        return out

    # -- cluster details --

    async def _election_topology(self, shard: str) -> tuple:
        """v1 semantics (lib/adm.js:226-337): the election-node order
        IS the daisy chain — first member primary, second sync, the
        rest asyncs.  Shared by `status -l` and `state-backfill` (the
        latter applies the _rearrangeState shift on top)."""
        actives = await self.get_active(shard)
        if not actives:
            raise AdmError("no active peers in shard %s" % shard)
        actives.sort(key=lambda a: a["seq"])

        def info(a):
            d = {"id": a["id"]}
            d.update(a.get("data") or {})
            d.setdefault("zoneId", a["id"])
            return d

        return (info(actives[0]),
                info(actives[1]) if len(actives) > 1 else None,
                [info(a) for a in actives[2:]])

    async def legacy_state(self, shard: str) -> dict:
        """Topology under v1 semantics, instead of the persistent
        cluster state.  The `status -l` view for diagnosing a cluster
        whose state object is missing or disputed."""
        primary, sync, asyncs = await self._election_topology(shard)
        return {
            "generation": None,
            "primary": primary,
            "sync": sync,
            "async": asyncs,
            "deposed": [],
        }

    async def load_cluster_details(self, shard: str, *,
                                   legacy_order_mode: bool = False
                                   ) -> ClusterDetails:
        canned = os.environ.get("MANATEE_ADM_TEST_STATE")
        if canned:
            # the hook may name a file on disk: read it off-loop like
            # every other file the async client touches
            return await asyncio.to_thread(load_test_state, canned)
        if legacy_order_mode:
            state = await self.legacy_state(shard)
        else:
            state, _v = await self.get_state(shard)
        if state is None:
            raise AdmError("no cluster state for shard %r" % shard)
        peer_status: dict[str, PeerStatus] = {}
        peers = [state["primary"]]
        if state.get("sync"):
            peers.append(state["sync"])
        peers.extend(state.get("async") or [])
        peers.extend(state.get("deposed") or [])
        import aiohttp
        timeout = aiohttp.ClientTimeout(total=PG_QUERY_TIMEOUT)
        async with aiohttp.ClientSession(timeout=timeout) as http:
            await asyncio.gather(*[
                self._add_pg_status(p, peer_status, state, http)
                for p in peers])
        return ClusterDetails(shard, state, peer_status)

    async def _add_pg_status(self, peer: dict,
                             out: dict[str, PeerStatus],
                             state: dict, http) -> None:
        """(lib/adm.js:348-427: pg_stat_replication + replay lag with a
        1 s timeout).  The database query and the sitter's health-score
        fetch run concurrently; both are bounded by PG_QUERY_TIMEOUT."""
        ps = PeerStatus(ident=peer)
        out[peer["id"]] = ps
        engine = self._engine_for(peer)
        if engine is None:
            ps.pgerr = "unsupported pgUrl %r" % peer.get("pgUrl")
            return
        st, ps.health_score = await asyncio.gather(
            self._query_status(engine, peer),
            self._fetch_health_score(peer, http))
        if isinstance(st, str):
            ps.pgerr = st
            return
        ps.online = True
        ps.lag = st.get("replay_lag_seconds")
        # the row describing this peer's DOWNSTREAM (first repl row)
        repl = st.get("replication") or []
        ps.repl = repl[0] if repl else None

    @staticmethod
    async def _query_status(engine, peer: dict) -> dict | str:
        try:
            return await engine.query_url(peer["pgUrl"], {"op": "status"},
                                          PG_QUERY_TIMEOUT)
        except (PgError, asyncio.TimeoutError, OSError) as e:
            return str(e)

    @staticmethod
    async def _fetch_health_score(peer: dict, http) -> float | None:
        """The failure-prediction score lives in the sitter, not the
        database: read it from the peer's status server (pgPort+1),
        best-effort — an old/absent sitter simply shows no score."""
        try:
            _s, host, pg_port = parse_pg_url(peer.get("pgUrl") or "")
        except PgError:
            return None
        try:
            async with http.get("http://%s:%d/state"
                                % (host, pg_port + 1)) as resp:
                if resp.status != 200:
                    return None
                body = await resp.json()
            score = body.get("healthScore")
            return float(score) if score is not None else None
        except asyncio.CancelledError:
            raise
        except Exception:
            return None

    @staticmethod
    def _engine_for(peer: dict):
        try:
            scheme, _h, _p = parse_pg_url(peer.get("pgUrl") or "")
        except PgError:
            return None
        if scheme == "sim":
            from manatee_tpu.pg.engine import SimPgEngine
            return SimPgEngine()
        if scheme == "tcp":
            from manatee_tpu.pg.postgres import PostgresEngine
            # psql from $MANATEE_PG_BIN_DIR when set (dev images keep
            # the binaries out of PATH), else PATH; status queries
            # never need sudo
            return PostgresEngine(
                pg_bin_dir=os.environ.get("MANATEE_PG_BIN_DIR", ""),
                use_sudo=False,
                # ad-hoc engines answer ONE query then evaporate: a
                # pooled coprocess would only leak until process exit
                session_pool=False)
        return None

    # -- state mutations (operator actions) --

    async def _update_state(self, shard: str, mutate, *,
                            retries: int = 3) -> dict:
        """Read-modify-CAS loop for operator writes.  *mutate(state)*
        returns the new state dict (or raises AdmError)."""
        from manatee_tpu.obs import bind_trace, new_trace_id
        for _ in range(retries):
            state, ver = await self.get_state(shard)
            if state is None:
                raise AdmError("no cluster state for shard %r" % shard)
            new = mutate(json.loads(json.dumps(state)))
            # operator transitions mint trace ids like the state
            # machine's do, so freeze/promote/reap actions correlate
            # with every peer's reaction in `manatee-adm events`.
            # The copied-through SPAN id must go: it names the PREVIOUS
            # transition's write, and peers would wrongly parent their
            # reaction spans under it (this CLI process's own spans die
            # with it, so there is no id worth embedding instead)
            tid = new_trace_id()
            new["trace"] = tid
            new.pop("span", None)
            # the written state object is an HLC piggyback boundary:
            # peers reacting to the watch merge the writer's stamp, so
            # their reaction records sort after this write at any skew
            new["hlc"] = hlc_now()
            try:
                with bind_trace(tid):
                    await self._client.multi(cluster_state_txn(
                        self._shard_path(shard) + "/history",
                        self._shard_path(shard) + "/state", new, ver))
                return new
            except BadVersionError:
                continue
        raise AdmError("lost the update race %d times; try again"
                       % retries)

    async def freeze(self, shard: str, reason: str) -> dict:
        """(lib/adm.js:1048-1075)"""
        def mutate(st):
            if st.get("freeze"):
                raise AdmError("cluster is already frozen")
            st["freeze"] = {"date": _now_iso(), "reason": reason}
            return st
        return await self._update_state(shard, mutate)

    async def unfreeze(self, shard: str) -> dict:
        """(lib/adm.js:1077-1098)"""
        def mutate(st):
            if not st.get("freeze"):
                raise AdmError("cluster is not frozen")
            st.pop("freeze", None)
            return st
        return await self._update_state(shard, mutate)

    async def reap(self, shard: str, zonename: str | None = None,
                   ip: str | None = None) -> dict:
        """Remove deposed entries that are gone (or the one named by
        zonename or IP).  (lib/adm.js:1108-1146; safety per
        docs/man/manatee-adm.md:306-329 — never reap a peer that is
        still registered)"""
        active_ids = {a["id"] for a in await self.get_active(shard)}

        def mutate(st):
            deposed = st.get("deposed") or []
            if zonename is not None or ip is not None:
                keep, dropped = [], []
                for d in deposed:
                    if (zonename is not None
                            and (d.get("zoneId") == zonename
                                 or d.get("id") == zonename)) \
                            or (ip is not None and d.get("ip") == ip):
                        dropped.append(d)
                    else:
                        keep.append(d)
                if not dropped:
                    raise AdmError("%s not in deposed list"
                                   % (zonename or ip))
            else:
                keep = [d for d in deposed if d["id"] in active_ids]
                dropped = [d for d in deposed
                           if d["id"] not in active_ids]
            for d in dropped:
                if d["id"] in active_ids:
                    raise AdmError(
                        "peer %s is still registered; will not reap"
                        % d["id"])
            if not dropped:
                raise AdmError("nothing to reap")
            st["deposed"] = keep
            return st
        return await self._update_state(shard, mutate)

    async def set_onwm(self, shard: str, mode: str) -> dict:
        """(lib/adm.js:1148-1209)"""
        if mode not in ("on", "off"):
            raise AdmError("mode must be 'on' or 'off'")

        def mutate(st):
            current = bool(st.get("oneNodeWriteMode"))
            if mode == "on":
                if current:
                    raise AdmError("already in one-node-write mode")
                if st.get("sync") or st.get("async"):
                    raise AdmError("cannot enable one-node-write mode "
                                   "with standbys in the topology")
                st["oneNodeWriteMode"] = True
            else:
                if not current:
                    raise AdmError("not in one-node-write mode")
                st.pop("oneNodeWriteMode", None)
            return st
        return await self._update_state(shard, mutate)

    async def state_backfill(self, shard: str, *,
                             dry_run: bool = False,
                             precomputed: dict | None = None) -> dict:
        """Create an initial (frozen) state from the current election
        order when none exists — the v1→v2 migration analogue
        (lib/adm.js:1231-1312).  *dry_run* computes and returns the
        state without writing it (the CLI's confirmation preview);
        *precomputed* writes EXACTLY the object the operator confirmed
        instead of recomputing from an election that may have shifted
        since the prompt (the reference previews and writes the same
        object, lib/adm.js:1278-1296)."""
        state, _ = await self.get_state(shard)
        if state is not None:
            raise AdmError("state already exists for shard %s" % shard)
        if precomputed is not None:
            new = precomputed
        else:
            primary, sync, asyncs = await self._election_topology(shard)
            # _rearrangeState parity (lib/adm.js:1251-1259): v1
            # election order named the daisy chain head-first, but the
            # backfilled v2 sync is the LAST async, with the old sync
            # appended to the async list
            if sync is not None and asyncs:
                new_sync = asyncs.pop()
                asyncs.append(sync)
                sync = new_sync

            new = {
                "generation": 0,
                "initWal": "0/0000000",
                "primary": primary,
                "sync": sync,
                "async": asyncs,
                "deposed": [],
                "freeze": {"date": _now_iso(),
                           "reason": "manatee-adm state-backfill"},
            }
        if dry_run:
            return new
        from manatee_tpu.obs import bind_trace, new_trace_id
        new = dict(new)
        new["trace"] = new_trace_id()
        await self._client.mkdirp(self._shard_path(shard) + "/history")
        with bind_trace(new["trace"]):
            await self._client.multi(cluster_state_txn(
                self._shard_path(shard) + "/history",
                self._shard_path(shard) + "/state", new, None))
        return new

    # -- promote --

    async def promote(self, shard: str, *, role: str, zonename: str,
                      async_index: int | None = None,
                      lag_to_ignore: float = DEFAULT_LAG_TO_IGNORE,
                      ignore_warnings: bool = False,
                      wait: bool = True,
                      wait_timeout: float = PROMOTE_EXPIRY_S + 10) -> dict:
        """(lib/adm.js:1693-2040, docs/man/manatee-adm.md:346-419)"""
        details = await self.load_cluster_details(shard)
        if details.errors:
            raise AdmError("cluster has errors; not promoting: %s"
                           % "; ".join(details.errors))
        lags = [p.lag for p in details.peers.values()
                if p.lag is not None]
        if not ignore_warnings:
            if details.warnings:
                raise AdmError("cluster has warnings; use -y to "
                               "override: %s"
                               % "; ".join(details.warnings))
            if any(lag > lag_to_ignore for lag in lags):
                raise AdmError("replication lag exceeds %ss; use -y to "
                               "override" % lag_to_ignore)

        st = details.state
        if role == "sync":
            target = st.get("sync")
            if target is None or target.get("zoneId") != zonename:
                raise AdmError("the sync is not %r (topology changed?)"
                               % zonename)
            promote = {"id": target["id"], "role": "sync"}
        elif role == "async":
            asyncs = st.get("async") or []
            if async_index is None:
                if len(asyncs) != 1:
                    raise AdmError("--asyncIndex required with %d asyncs"
                                   % len(asyncs))
                async_index = 0
            if async_index < 0:
                raise AdmError("asyncIndex must be >= 0")
            if async_index >= len(asyncs) or \
                    asyncs[async_index].get("zoneId") != zonename:
                raise AdmError(
                    "async[%d] is not %r (topology changed?)"
                    % (async_index, zonename))
            promote = {"id": asyncs[async_index]["id"], "role": "async",
                       "asyncIndex": async_index}
        else:
            raise AdmError("role must be 'sync' or 'async'")

        promote["generation"] = st["generation"]
        promote["expireTime"] = _now_iso(
            datetime.datetime.now(datetime.timezone.utc)
            + datetime.timedelta(seconds=PROMOTE_EXPIRY_S))

        def mutate(s):
            if s.get("generation") != promote["generation"]:
                raise AdmError("topology changed while composing the "
                               "promotion request")
            s["promote"] = promote
            return s
        await self._update_state(shard, mutate)

        if not wait:
            return promote
        # watch until the request is acted on (promote object removed)
        deadline = asyncio.get_event_loop().time() + wait_timeout
        while asyncio.get_event_loop().time() < deadline:
            s, _ = await self.get_state(shard)
            if s is not None and "promote" not in s:
                return promote
            await asyncio.sleep(1.0)
        raise AdmError("promotion request was not acted on (it may "
                       "have been ignored; see clear-promote)")

    async def clear_promote(self, shard: str) -> dict:
        """(lib/adm.js:2004-2040)"""
        def mutate(st):
            if "promote" not in st:
                raise AdmError("no promotion request present")
            st.pop("promote", None)
            return st
        return await self._update_state(shard, mutate)

    # -- check-lock --

    async def check_lock(self, path: str) -> bool:
        """True if the lock node EXISTS (lib/adm.js:2049-2086)."""
        stat = await self._client.exists(path)
        return stat is not None

    # -- shard-wide event timeline / span tree --

    async def _shard_peers(self, shard: str) -> dict[str, dict]:
        """PeerInfo by id: the durable topology's peers plus any
        election member not yet adopted — the fan-out set for /events
        and /spans."""
        state, _v = await self.get_state(shard)
        peers: dict[str, dict] = {}
        if state is not None:
            for p in ([state.get("primary"), state.get("sync")]
                      + list(state.get("async") or [])
                      + list(state.get("deposed") or [])):
                if p and p.get("id"):
                    peers[p["id"]] = p
        for a in await self.get_active(shard):
            ent = {"id": a["id"]}
            ent.update(a.get("data") or {})
            peers.setdefault(a["id"], ent)
        return peers

    @staticmethod
    def peer_http_targets(peers: dict[str, dict], *,
                          include_backup: bool = False
                          ) -> tuple[list[tuple[str, str]],
                                     dict[str, str]]:
        """THE peer→HTTP mapping, shared by every fan-out (/events,
        /spans, /faults): (label, base URL) of each peer's status
        server (pgPort+1) — plus its backupserver (label 'id/backup')
        when *include_backup* — and an errors map for peers that could
        not be mapped (unsupported pgUrl), so no fan-out can silently
        skip a peer."""
        targets: list[tuple[str, str]] = []
        errors: dict[str, str] = {}
        for p in peers.values():
            try:
                _s, host, pg_port = parse_pg_url(p.get("pgUrl") or "")
            except PgError:
                errors[p["id"]] = ("unsupported pgUrl %r"
                                   % p.get("pgUrl"))
                continue
            targets.append((p["id"],
                            "http://%s:%d" % (host, pg_port + 1)))
            if include_backup:
                # a separate daemon (the backup sender's spans and
                # stream faults live there, not in the sitter); a peer
                # record WITHOUT a backupUrl is reported, not silently
                # skipped — its backupserver could still hold armed
                # rules a shard-wide clear must not miss
                if p.get("backupUrl"):
                    targets.append((p["id"] + "/backup",
                                    p["backupUrl"].rstrip("/")))
                else:
                    errors[p["id"] + "/backup"] = \
                        "peer record has no backupUrl"
        return targets, errors

    async def _fan_out(self, peers: dict[str, dict], path: str,
                       keys: tuple[str, ...], *, timeout: float,
                       query: str = "",
                       include_backup: bool = False,
                       skew: dict[str, float] | None = None
                       ) -> tuple[dict[str, list], dict[str, str]]:
        """GET *path* from every peer's status server (and, when
        *include_backup*, its backup server too), collecting the dicts
        under each of *keys*; per-peer failures land in the errors
        map.  *query* may be a callable(label) so a poll-tail can send
        each peer its own ``since`` cursor.  Every reply body carries
        the server's wall clock and HLC stamp: the stamp is merged
        into this process's clock (so anything we journal afterward
        sorts after everything we saw), and when *skew* is given the
        measured per-peer clock offset lands there (doctor's
        skew-vs-merge-bound check, the incident report's skew
        table)."""
        import aiohttp
        import time as _time

        out: dict[str, list] = {k: [] for k in keys}
        targets, errors = self.peer_http_targets(
            peers, include_backup=include_backup)
        by_label = {p["id"]: p for p in peers.values()}

        async def fetch(peer: dict, url: str, err_key: str,
                        http) -> None:
            t0 = _time.time()
            try:
                async with http.get(url) as resp:
                    if resp.status != 200:
                        errors[err_key] = "HTTP %d" % resp.status
                        return
                    body = await resp.json()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                errors[err_key] = str(e) or type(e).__name__
                return
            merge_remote_sync(body.get("hlc"))
            if skew is not None and body.get("now") is not None:
                off = observe_peer_clock(err_key, body.get("now"),
                                         t0, _time.time())
                if off is not None:
                    skew[err_key] = round(off, 6)
            for key in keys:
                for ent in body.get(key) or []:
                    if not isinstance(ent, dict):
                        continue
                    # an old daemon (or a ring predating set_peer) may
                    # report peer missing/None; the fan-out knows who
                    # it asked
                    if ent.get("peer") is None:
                        ent["peer"] = peer["id"]
                    out[key].append(ent)

        http_timeout = aiohttp.ClientTimeout(total=timeout)
        async with aiohttp.ClientSession(timeout=http_timeout) as http:
            await asyncio.gather(*(
                fetch(by_label[label.split("/", 1)[0]],
                      base + path
                      + (query(label) if callable(query) else query),
                      label, http)
                for label, base in targets))
        return out, errors

    async def shard_events(self, shard: str, *,
                           limit: int | None = None,
                           since: dict[str, int] | None = None,
                           timeout: float = 5.0) -> dict:
        """Fan out ``GET /events`` to every peer's status server, merge
        the rings by wall-clock timestamp (peer/seq as the tiebreak),
        and return::

            {"events": [...merged, oldest first...],
             "errors": {peer_id: "why the fetch failed", ...}}

        The merged list is what one grep of per-peer bunyan logs could
        never give the reference's operators: a single trace-correlated
        takeover timeline.  *since* maps peer id -> last seq already
        seen, so a follow loop (``manatee-adm events --follow``) ships
        only each ring's new tail instead of the whole ring per poll."""
        peers = await self._shard_peers(shard)

        def q(label: str) -> str:
            parts = []
            cursor = (since or {}).get(label)
            if cursor:
                parts.append("since=%d" % cursor)
            if limit is not None:
                parts.append("limit=%d" % limit)
            return ("?" + "&".join(parts)) if parts else ""

        skew: dict[str, float] = {}
        got, errors = await self._fan_out(
            peers, "/events", ("events",), timeout=timeout, query=q,
            skew=skew)
        return {"events": merge_events(got["events"]), "errors": errors,
                "skew": skew}

    @staticmethod
    async def _gather_raw(targets, path: str, errors: dict, *,
                          timeout: float, as_json: bool = False
                          ) -> dict:
        """GET *path* from each (label, base URL) target, returning
        the whole body per label (text, or parsed JSON with
        *as_json*); per-target failures land in *errors*.  The
        NON-merging fan-out under shard_metrics / shard_profile /
        shard_tasks — those endpoints are per-process snapshots, not
        rings to merge."""
        import aiohttp

        out: dict = {}

        async def fetch(label: str, base: str, http) -> None:
            try:
                async with http.get(base + path) as resp:
                    if resp.status != 200:
                        errors[label] = "HTTP %d" % resp.status
                        return
                    out[label] = (await resp.json() if as_json
                                  else await resp.text())
            except asyncio.CancelledError:
                raise
            except Exception as e:
                errors[label] = str(e) or type(e).__name__

        http_timeout = aiohttp.ClientTimeout(total=timeout)
        async with aiohttp.ClientSession(timeout=http_timeout) as http:
            await asyncio.gather(*(fetch(label, base, http)
                                   for label, base in targets))
        return out

    async def shard_metrics(self, shard: str, *, timeout: float = 5.0
                            ) -> tuple[dict[str, str], dict[str, str]]:
        """Raw Prometheus exposition text per peer status server — the
        `manatee-adm top` fan-out (process self-metrics, replication
        lag, health score all ride the one scrape every sitter already
        serves)."""
        peers = await self._shard_peers(shard)
        targets, errors = self.peer_http_targets(peers)
        out = await self._gather_raw(targets, "/metrics", errors,
                                     timeout=timeout)
        return out, errors

    async def shard_profile(self, shard: str, *,
                            seconds: float = 30.0,
                            timeout: float = 15.0
                            ) -> tuple[dict[str, str], dict[str, str]]:
        """Folded-stack profile text per peer status server
        (``GET /profile?seconds=N``) — the `manatee-adm profile`
        fan-out.  Each body is already flamegraph food; the CLI
        prefixes a ``peer:<id>`` root frame when merging peers."""
        peers = await self._shard_peers(shard)
        targets, errors = self.peer_http_targets(peers)
        out = await self._gather_raw(
            targets, "/profile?seconds=%g" % seconds, errors,
            timeout=timeout)
        return out, errors

    async def shard_tasks(self, shard: str, *, timeout: float = 5.0
                          ) -> tuple[dict[str, dict], dict[str, str]]:
        """Live asyncio task census per peer (``GET /tasks``) — the
        `manatee-adm tasks` fan-out and the post-failover leak check's
        data source."""
        peers = await self._shard_peers(shard)
        targets, errors = self.peer_http_targets(peers)
        out = await self._gather_raw(targets, "/tasks", errors,
                                     timeout=timeout, as_json=True)
        return out, errors

    @staticmethod
    async def http_json(url: str, *, timeout: float = 5.0
                        ) -> tuple[int, dict]:
        """One JSON GET — how the CLI talks to a prober's /alerts and
        /slis (the prober fronts the fleet; it is not a shard peer, so
        the peer fan-out machinery does not apply)."""
        import aiohttp

        http_timeout = aiohttp.ClientTimeout(total=timeout)
        async with aiohttp.ClientSession(timeout=http_timeout) as http:
            async with http.get(url) as resp:
                return resp.status, await resp.json()

    async def shard_spans(self, shard: str, *,
                          trace: str | None = None,
                          limit: int | None = None,
                          timeout: float = 5.0) -> dict:
        """Fan out ``GET /spans`` to every peer's status server AND
        backup server, returning ``{"spans": [...merged...],
        "open": [...], "errors": {...}}``.  *trace* filters server-side
        so a busy shard's rings are not shipped whole."""
        peers = await self._shard_peers(shard)
        q = []
        if trace is not None:
            q.append("trace=%s" % trace)
        if limit is not None:
            q.append("limit=%d" % limit)
        skew: dict[str, float] = {}
        got, errors = await self._fan_out(
            peers, "/spans", ("spans", "open"), timeout=timeout,
            query=("?" + "&".join(q)) if q else "",
            include_backup=True, skew=skew)
        opens = got["open"]
        if trace is not None:
            # the trace query filters completed spans server-side;
            # open spans come back whole (they are the leak signal)
            opens = [o for o in opens if o.get("trace") == trace]
        return {"spans": merge_events(got["spans"]), "open": opens,
                "errors": errors, "skew": skew}

    # -- live fault injection (manatee-adm fault set|list|clear) --

    async def fault_targets(self, shard: str, *,
                            zonename: str | None = None,
                            backup: bool = False
                            ) -> tuple[list[tuple[str, str]],
                                       dict[str, str]]:
        """(label, base URL) of every targeted peer's status server —
        plus its backupserver when *backup* — resolved from the durable
        topology + election via the same mapping the /events and /spans
        fan-outs use.  *zonename* (a zoneId or full peer id) narrows to
        one peer.  Unmappable peers come back in the errors map: a
        shard-wide `fault clear` must never silently skip a peer that
        could still be armed."""
        peers = await self._shard_peers(shard)
        if zonename is not None:
            peers = {pid: p for pid, p in peers.items()
                     if zonename in (p.get("zoneId"), p["id"])}
            if not peers:
                raise AdmError("no peer matches %r" % zonename)
        return self.peer_http_targets(peers, include_backup=backup)

    @staticmethod
    async def fault_request(targets: list[tuple[str, str]],
                            method: str, *, payload: dict | None = None,
                            query: str = "",
                            timeout: float = 5.0) -> dict[str, dict]:
        """Issue one /faults request per (label, base URL); returns
        {label: body-or-{"error": ...}}."""
        import aiohttp

        out: dict[str, dict] = {}

        async def one(label: str, base: str, http) -> None:
            try:
                async with http.request(
                        method, base + "/faults" + query,
                        json=payload) as resp:
                    body = await resp.json()
                    if resp.status != 200:
                        body = {"error": body.get("error")
                                or ("HTTP %d" % resp.status)}
            except asyncio.CancelledError:
                raise
            except Exception as e:
                body = {"error": str(e) or type(e).__name__}
            out[label] = body

        http_timeout = aiohttp.ClientTimeout(total=timeout)
        async with aiohttp.ClientSession(timeout=http_timeout) as http:
            await asyncio.gather(*(one(label, base, http)
                                   for label, base in targets))
        return out

    async def last_failover_trace(self, shard: str, *,
                                  timeout: float = 5.0) -> str:
        """The trace id of the most recent failover visible in the
        shard's journals (completed if one exists, else the freshest
        detection) — what `manatee-adm trace --last-failover`
        resolves."""
        out = await self.shard_events(shard, timeout=timeout)
        best: tuple | None = None
        for ev in out["events"]:
            name = str(ev.get("event") or "")
            if name not in ("failover.complete", "failover.detected"):
                continue
            tid = ev.get("trace")
            if not tid:
                continue
            rank = (1 if name == "failover.complete" else 0,
                    ev.get("ts") or 0.0)
            if best is None or rank > best[0]:
                best = (rank, tid)
        if best is None:
            raise AdmError(
                "no failover found in any peer's journal window "
                "(rings are in-memory; a restarted peer's history "
                "died with it)")
        return best[1]
