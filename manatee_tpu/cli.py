"""manatee-adm — the operator CLI.

Reference parity: bin/manatee-adm (cmdln subcommands, 1536 lines) with
the same command set, column registry/aliases/defaults (:1151-1232),
tabular output (:1330-1419), cluster-issue printing and exit-code
contracts (verify exits non-zero on ANY issue, :466-477), plus the man
page semantics (docs/man/manatee-adm.md).

Environment: SHARD, COORD_ADDR (the ZK_IPS analogue),
MANATEE_SITTER_CONFIG, MANATEE_ADM_TEST_STATE
(docs/man/manatee-adm.md:502-515).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import re
import sys
import time

from manatee_tpu import __version__
from manatee_tpu.adm import (
    AdmClient,
    AdmError,
    ClusterDetails,
    DEFAULT_LAG_TO_IGNORE,
    pg_duration,
)

# rebuild gives a repeatedly-failing restore this many attempts before
# aborting with a diagnosis (RESTORE_RETRIES, lib/adm.js:71)
RESTORE_RETRIES = 5

# ---- column registry (bin/manatee-adm:1151-1232) ----

ALL_COLUMNS = {
    "peername": {"label": "PEERNAME", "width": 36},
    "peerabbr": {"label": "PEER", "width": 8},
    "role":     {"label": "ROLE", "width": 8},
    "ip":       {"label": "IP", "width": 16},
    "pg-online": {"label": "PG", "width": 4},
    "pg-repl":  {"label": "REPL", "width": 5},
    "pg-sent":  {"label": "SENT", "width": 13},
    "pg-write": {"label": "WRITE", "width": 13},
    "pg-flush": {"label": "FLUSH", "width": 13},
    "pg-replay": {"label": "REPLAY", "width": 13},
    "pg-lag":   {"label": "LAG", "width": 6},
    # failure-prediction score from each sitter's telemetry window
    # (manatee_tpu/health); "-" when the peer predates the model or the
    # window has not filled yet
    "pg-pred":  {"label": "PRED", "width": 5},
}
COLUMN_ALIASES = {"zonename": "peername", "zoneabbr": "peerabbr"}
PEERS_DFL = ["role", "peername", "ip"]
PGSTATUS_DFL = ["role", "peerabbr", "pg-online", "pg-repl", "pg-sent",
                "pg-flush", "pg-replay", "pg-lag"]
PGSTATUS_WIDE_DFL = ["role", "peername", "pg-online", "pg-repl",
                     "pg-sent", "pg-flush", "pg-replay", "pg-lag"]


def extract_columns(names: list[str]) -> list[dict]:
    out = []
    for n in names:
        n = COLUMN_ALIASES.get(n, n)
        if n not in ALL_COLUMNS:
            raise AdmError("unknown column: %r" % n)
        col = dict(ALL_COLUMNS[n])
        col["name"] = n
        out.append(col)
    return out


def row_for_peer(role: str, peer) -> dict:
    """(rowForPeer, bin/manatee-adm:1377-1419)"""
    rv = {
        "role": role,
        "peerabbr": peer.label,
        "peername": str(peer.ident.get("zoneId", "?")),
        "ip": str(peer.ident.get("ip", "-")),
    }
    score = getattr(peer, "health_score", None)
    rv["pg-pred"] = "-" if score is None else "%.2f" % score
    if peer.pgerr is not None:
        rv.update({"pg-online": "fail", "pg-repl": "-", "pg-sent": "-",
                   "pg-write": "-", "pg-flush": "-", "pg-replay": "-",
                   "pg-lag": "-"})
        return rv
    rv["pg-online"] = "ok"
    rv["pg-lag"] = pg_duration(peer.lag)
    repl = peer.repl
    if repl is None or not repl.get("sync_state"):
        rv.update({"pg-repl": "-", "pg-sent": "-", "pg-write": "-",
                   "pg-flush": "-", "pg-replay": "-"})
        return rv
    rv["pg-repl"] = repl["sync_state"]
    rv["pg-sent"] = repl.get("sent_lsn") or "-"
    rv["pg-write"] = repl.get("write_lsn") or "-"
    rv["pg-flush"] = repl.get("flush_lsn") or "-"
    rv["pg-replay"] = repl.get("replay_lsn") or "-"
    return rv


def emit_table(columns: list[dict], rows: list[dict], *,
               omit_header: bool = False, out=None) -> None:
    out = out or sys.stdout
    if not omit_header:
        parts = [c["label"].ljust(c["width"]) for c in columns]
        out.write(" ".join(parts).rstrip() + "\n")
    for row in rows:
        parts = [str(row.get(c["name"], "-")).ljust(c["width"])
                 for c in columns]
        out.write(" ".join(parts).rstrip() + "\n")


def print_cluster_table(details: ClusterDetails, columns: list[dict], *,
                        role_filter: str | None = None,
                        omit_header: bool = False, out=None) -> None:
    rows = []
    if role_filter in (None, "primary"):
        rows.append(row_for_peer("primary",
                                 details.peers[details.primary]))
    if role_filter in (None, "sync") and details.sync is not None:
        rows.append(row_for_peer("sync", details.peers[details.sync]))
    if role_filter in (None, "async"):
        for a in details.asyncs:
            rows.append(row_for_peer("async", details.peers[a]))
    if role_filter in (None, "deposed"):
        for d in details.deposed:
            rows.append(row_for_peer("deposed", details.peers[d]))
    emit_table(columns, rows, omit_header=omit_header, out=out)


def print_cluster_issues(details: ClusterDetails, stream, *,
                         leading_nl: bool) -> None:
    notices = getattr(details, "notices", [])
    if leading_nl and (details.errors or details.warnings or notices):
        stream.write("\n")
    for e in details.errors:
        stream.write("error: %s\n" % e.split("\n")[0])
    for w in details.warnings:
        stream.write("warning: %s\n" % w.split("\n")[0])
    # informational (failure prediction): shown, but never affects the
    # verify exit contract or the promote warning gate
    for n in notices:
        stream.write("notice: %s\n" % n.split("\n")[0])


# ---- command implementations ----

def _coord(args) -> str:
    addr = args.coord or os.environ.get("COORD_ADDR") \
        or os.environ.get("ZK_IPS")
    if not addr:
        die("coordination address required (-z or COORD_ADDR)")
    return addr


def _shard(args) -> str:
    shard = getattr(args, "shard", None) or os.environ.get("SHARD")
    if not shard:
        die("shard name required (-s or SHARD)")
    return shard


def die(msg: str, code: int = 2) -> None:
    sys.stderr.write("manatee-adm: %s\n" % msg)
    sys.exit(code)


def confirm_or_die(prompt: str = "") -> None:
    """Read a yes/no answer; anything else — including EOF from a
    scripted run without -y — is a clean 'aborted', not a traceback."""
    try:
        answer = input(prompt)
    except EOFError:
        answer = ""
    if answer.strip().lower() not in ("y", "yes"):
        die("aborted")


async def _load_details(args) -> ClusterDetails:
    canned = os.environ.get("MANATEE_ADM_TEST_STATE")
    if canned:
        from manatee_tpu.adm import load_test_state
        return await asyncio.to_thread(load_test_state, canned)
    async with AdmClient(_coord(args)) as adm:
        return await adm.load_cluster_details(_shard(args))


def cmd_version(_args) -> int:
    print(__version__)
    return 0


def cmd_show(args) -> int:
    async def go():
        details = await _load_details(args)
        print("coordination: %s" % (args.coord or
                                    os.environ.get("COORD_ADDR", "-")))
        print("cluster:     %s" % details.shard)
        print("generation:  %s (%s)" % (details.generation,
                                        details.initwal))
        print("mode:        %s" % ("singleton (one-node-write)"
                                   if details.singleton else "normal"))
        if details.frozen:
            print("freeze:      frozen since %s" % details.freeze_time)
            print("freeze info: %s" % details.freeze_reason)
        else:
            print("freeze:      not frozen")
        print("")
        if args.verbose:
            print_cluster_table(details, extract_columns(PEERS_DFL))
            print("")
        print_cluster_table(details, extract_columns(PGSTATUS_DFL))
        print_cluster_issues(details, sys.stdout, leading_nl=True)
        return 0
    return asyncio.run(go())


def cmd_peers(args) -> int:
    async def go():
        details = await _load_details(args)
        cols = extract_columns(args.columns.split(",")
                               if args.columns else PEERS_DFL)
        print_cluster_table(details, cols, role_filter=args.role,
                            omit_header=args.omit_header)
        return 0
    return asyncio.run(go())


def cmd_pg_status(args) -> int:
    async def go():
        dfl = PGSTATUS_WIDE_DFL if args.wide else PGSTATUS_DFL
        cols = extract_columns(args.columns.split(",")
                               if args.columns else dfl)
        count = args.count if args.count is not None else \
            (0 if args.period else 1)
        i = 0
        while True:
            details = await _load_details(args)
            print_cluster_table(details, cols, role_filter=args.role,
                                omit_header=args.omit_header)
            print_cluster_issues(details, sys.stdout, leading_nl=True)
            i += 1
            if count and i >= count:
                break
            await asyncio.sleep(args.period or 1)
        return 0
    return asyncio.run(go())


def cmd_verify(args) -> int:
    async def go():
        try:
            details = await _load_details(args)
        except asyncio.CancelledError:
            raise
        except Exception:
            print("error: failed to fetch cluster state")
            return 1
        print_cluster_issues(details, sys.stdout, leading_nl=False)
        if details.errors or details.warnings:
            return 1
        if args.verbose:
            print("all checks passed")
        return 0
    return asyncio.run(go())


def cmd_status(args) -> int:
    """Deprecated JSON status across shards (bin/manatee-adm:203).
    -l/--legacyOrderMode derives topology from election order (v1
    semantics, bin/manatee-adm:223-230) instead of cluster state."""
    print('note: "status" is deprecated. See "pg-status".',
          file=sys.stderr)

    async def go():
        async with AdmClient(_coord(args)) as adm:
            shards = [args.shard] if args.shard else \
                await adm.list_shards()
            out = {}
            for sh in shards:
                try:
                    d = await adm.load_cluster_details(
                        sh, legacy_order_mode=args.legacy_order_mode)
                except AdmError:
                    continue
                entry = {}

                def peerjson(pid):
                    p = d.peers[pid]
                    return {
                        "zoneId": p.ident.get("zoneId"),
                        "ip": p.ident.get("ip"),
                        "pgUrl": p.ident.get("pgUrl"),
                        "backupUrl": p.ident.get("backupUrl"),
                        "online": p.online,
                        "repl": p.repl or {},
                        "lag": p.lag,
                    }
                entry["primary"] = peerjson(d.primary)
                if d.sync:
                    entry["sync"] = peerjson(d.sync)
                for i, a in enumerate(d.asyncs):
                    entry["async" + ("" if i == 0 else str(i))] = \
                        peerjson(a)
                for i, dep in enumerate(d.deposed):
                    entry["deposed" + ("" if i == 0 else str(i))] = \
                        peerjson(dep)
                out[sh] = entry
            print(json.dumps(out, indent=4))
        return 0
    return asyncio.run(go())


def cmd_coord_status(args) -> int:
    """Probe every coordination member in the connstr: role, seq,
    leader hint — the ensemble-aware analogue of the reference's
    zkConnTest smoke tool."""
    async def go():
        from manatee_tpu.coord.client import parse_connstr, sync_status
        addrs = parse_connstr(_coord(args))
        stats = await asyncio.gather(
            *[sync_status(host, port, 2.0) for host, port in addrs])
        rows = []
        for (host, port), st in zip(addrs, stats):
            rows.append({
                "address": "%s:%d" % (host, port),
                "state": "ok" if st else "unreachable",
                "role": (st or {}).get("role", "-"),
                "seq": str((st or {}).get("seq", "-")),
                "leader": (st or {}).get("leader") or "-",
            })
        cols = [{"name": "address", "label": "ADDRESS", "width": 22},
                {"name": "state", "label": "STATE", "width": 12},
                {"name": "role", "label": "ROLE", "width": 9},
                {"name": "seq", "label": "SEQ", "width": 8},
                {"name": "leader", "label": "LEADER", "width": 22}]
        emit_table(cols, rows, omit_header=args.omit_header)
        # exit nonzero when no member is serving sessions
        return 0 if any(r["role"] == "leader" for r in rows) else 1
    return asyncio.run(go())


def cmd_zk_state(args) -> int:
    async def go():
        async with AdmClient(_coord(args)) as adm:
            state, _ = await adm.get_state(_shard(args))
            if state is None:
                sys.stderr.write("manatee-adm: no cluster state for "
                                 "shard %r\n" % _shard(args))
                return 1
            print(json.dumps(state, indent=4))
        return 0
    return asyncio.run(go())


def cmd_zk_active(args) -> int:
    async def go():
        async with AdmClient(_coord(args)) as adm:
            active = await adm.get_active(_shard(args))
            print(json.dumps(active, indent=4))
        return 0
    return asyncio.run(go())


def cmd_freeze(args) -> int:
    async def go():
        async with AdmClient(_coord(args)) as adm:
            await adm.freeze(_shard(args), args.reason)
            print("Frozen.")
        return 0
    return asyncio.run(go())


def cmd_unfreeze(args) -> int:
    async def go():
        async with AdmClient(_coord(args)) as adm:
            await adm.unfreeze(_shard(args))
            print("Unfrozen.")
        return 0
    return asyncio.run(go())


def cmd_reap(args) -> int:
    async def go():
        async with AdmClient(_coord(args)) as adm:
            new = await adm.reap(_shard(args), args.zonename,
                                 ip=args.ip)
            print("Reaped.  Deposed peers now: %s"
                  % json.dumps(new.get("deposed", [])))
        return 0
    return asyncio.run(go())


def cmd_set_onwm(args) -> int:
    """Flipping one-node-write mode requires cluster downtime and the
    sitter configs to agree with the state object — prompted unless -y
    (lib/adm.js:1161-1186)."""
    async def go():
        if not args.yes:
            print("!!! WARNING !!!\n"
                  "Enabling or disabling one-node-write mode requires "
                  "cluster downtime,\nand the mode in every sitter "
                  "config must match the cluster state object.\n"
                  "!!! WARNING !!!", file=sys.stderr)
            sys.stderr.write("Are you sure you want to proceed? "
                             "(yes/no): ")
            sys.stderr.flush()
            confirm_or_die()
        async with AdmClient(_coord(args)) as adm:
            await adm.set_onwm(_shard(args), args.mode)
            print("one-node-write mode: %s" % args.mode)
        return 0
    return asyncio.run(go())


def cmd_state_backfill(args) -> int:
    """Writes a brand-new cluster state derived from election order —
    shown and confirmed before committing unless -y
    (lib/adm.js:1278-1296)."""
    async def go():
        preview = None
        if not args.yes:
            # compute the preview, then CLOSE the session before the
            # blocking prompt: input() freezes the event loop, and an
            # open session would heartbeat-expire under a slow operator
            async with AdmClient(_coord(args)) as adm:
                preview = await adm.state_backfill(_shard(args),
                                                   dry_run=True)
            print("Computed new cluster state:", file=sys.stderr)
            print(json.dumps(preview, indent=4), file=sys.stderr)
            # prompt on stderr: stdout carries the JSON result
            sys.stderr.write("is this correct? (yes/no): ")
            sys.stderr.flush()
            confirm_or_die()
        async with AdmClient(_coord(args)) as adm:
            # write the object the operator confirmed, not a recompute
            new = await adm.state_backfill(_shard(args),
                                           precomputed=preview)
            print(json.dumps(new, indent=4))
        return 0
    return asyncio.run(go())


def cmd_promote(args) -> int:
    async def go():
        async with AdmClient(_coord(args)) as adm:
            print("Promotion requested.  Watching until the request has "
                  "been acknowledged and topology has changed.")
            await adm.promote(
                _shard(args), role=args.role, zonename=args.zonename,
                async_index=args.asyncIndex,
                lag_to_ignore=args.lagToIgnore,
                ignore_warnings=args.yes)
            print("Promotion complete.")
        return 0
    return asyncio.run(go())


def cmd_clear_promote(args) -> int:
    async def go():
        async with AdmClient(_coord(args)) as adm:
            await adm.clear_promote(_shard(args))
            print("Promotion request cleared.")
        return 0
    return asyncio.run(go())


def cmd_check_lock(args) -> int:
    async def go():
        async with AdmClient(_coord(args)) as adm:
            held = await adm.check_lock(args.path)
        # exit 1 when the lock exists (bin/manatee-adm:613-649)
        return 1 if held else 0
    return asyncio.run(go())


def cmd_history(args) -> int:
    """Cluster state history (bin/manatee-adm:651-802): rows sorted by
    coordination sequence (--sort zkSeq, default) or record time
    (--sort time); per-role zone columns; -v appends the per-transition
    SUMMARY annotation."""
    def zone8(p):
        return (p.get("zoneId") or p.get("id") or "-")[:8] if p else "-"

    async def go():
        async with AdmClient(_coord(args)) as adm:
            hist = await adm.get_history(_shard(args))
        if args.sort == "time":
            hist.sort(key=lambda h: h["time"])
        if args.json:
            for h in hist:
                print(json.dumps(h))
            return 0
        cols = [
            {"name": "time", "label": "TIME", "width": 24},
            {"name": "generation", "label": "G#", "width": 2},
            {"name": "mode", "label": "MODE", "width": 5},
            {"name": "freeze", "label": "FRZ", "width": 3},
            {"name": "primary", "label": "PRIMARY", "width": 8},
            {"name": "sync", "label": "SYNC", "width": 8},
            {"name": "async", "label": "ASYNC", "width": 8},
            {"name": "deposed", "label": "DEPOSED", "width": 8},
        ]
        if args.verbose:
            cols.append({"name": "annotation", "label": "SUMMARY",
                         "width": 40})
        rows = []
        for h in hist:
            st = h["state"]
            asyncs = st.get("async") or []
            deposed = st.get("deposed") or []
            rows.append({
                "time": h["time"],
                "generation": h["generation"],
                "mode": ("singl" if st.get("oneNodeWriteMode")
                         else "multi"),
                "freeze": "frz" if st.get("freeze") else "-",
                "primary": zone8(st.get("primary")),
                "sync": zone8(st.get("sync")),
                "async": ",".join(zone8(a) for a in asyncs) or "-",
                "deposed": ",".join(zone8(d) for d in deposed) or "-",
                "annotation": h["annotation"] or "-",
            })
        emit_table(cols, rows)
        return 0
    return asyncio.run(go())


def cmd_events(args) -> int:
    """Merged shard-wide event timeline (beyond-parity observability):
    fans out GET /events across every peer's status server, merges by
    timestamp, and prints one trace-correlated sequence — a takeover is
    reconstructed end-to-end with a single command instead of grepping
    per-peer bunyan logs.  --follow keeps polling, sending each peer
    its own ``since`` cursor so every poll ships only the ring's new
    tail (the journal's pagination contract, not a re-fetch)."""
    cols = [
        {"name": "time", "label": "TIME", "width": 24},
        {"name": "peer", "label": "PEER", "width": 21},
        {"name": "trace", "label": "TRACE", "width": 16},
        {"name": "event", "label": "EVENT", "width": 24},
        {"name": "detail", "label": "DETAIL", "width": 30},
    ]
    core = {"seq", "ts", "time", "peer", "event", "trace"}

    def wanted(events):
        if args.trace:
            events = [e for e in events
                      if e.get("trace") == args.trace]
        if args.event:
            events = [e for e in events
                      if args.event in str(e.get("event"))]
        return events

    def emit(events, *, first: bool) -> None:
        if args.json:
            for e in events:
                print(json.dumps(e))
        else:
            rows = []
            for e in events:
                detail = " ".join(
                    "%s=%s" % (k, e[k]) for k in sorted(e)
                    if k not in core and e[k] is not None)
                rows.append({
                    "time": e.get("time", "?"),
                    "peer": e.get("peer", "?"),
                    "trace": e.get("trace") or "-",
                    "event": e.get("event", "?"),
                    "detail": detail or "-",
                })
            if rows or first:
                emit_table(cols, rows,
                           omit_header=args.omit_header or not first)
        sys.stdout.flush()

    async def go():
        warned: set[str] = set()

        def warn(errors) -> None:
            # follow mode warns on each peer's TRANSITION to
            # unreachable, not every poll
            for peer_id, err in sorted(errors.items()):
                if peer_id not in warned:
                    sys.stderr.write("warning: no events from %s: %s\n"
                                     % (peer_id, err))
            warned.clear()
            warned.update(errors)

        async with AdmClient(_coord(args)) as adm:
            shard = _shard(args)
            out = await adm.shard_events(shard, limit=args.limit)
            emit(wanted(out["events"]), first=True)
            warn(out["errors"])
            if not args.follow:
                # exit nonzero only when NO peer answered (a dead
                # peer's ring died with it; partial timelines are
                # still the tool's job) — judged on the UNFILTERED
                # fetch, so a -t/-e filter matching nothing is not an
                # error
                return 0 if out["events"] or not out["errors"] else 1
            cursors: dict[str, int] = {}

            def advance(events) -> None:
                for e in events:
                    peer, seq = e.get("peer"), e.get("seq")
                    if peer and isinstance(seq, int):
                        cursors[peer] = max(cursors.get(peer, 0), seq)

            advance(out["events"])
            while True:
                await asyncio.sleep(args.interval)
                out = await adm.shard_events(shard, since=cursors)
                advance(out["events"])
                emit(wanted(out["events"]), first=False)
                warn(out["errors"])

    try:
        return asyncio.run(go())
    except KeyboardInterrupt:
        # Ctrl-C is how a follow tail ends; the tail shown is complete
        return 0


def cmd_trace(args) -> int:
    """Cross-peer span tree for one trace id (the failover
    post-mortem tool): fans out GET /spans across every peer's status
    AND backup servers, reassembles the tree, renders an ASCII
    waterfall, and computes the critical path — the chain of spans
    that actually bounds wall-clock time, with per-stage self times
    and percentages.  --last-failover resolves the most recent
    failover's trace id from the merged journals first."""
    from manatee_tpu.obs.spans import (
        assemble_tree,
        critical_path,
        render_waterfall,
    )

    if bool(args.trace_id) == bool(args.last_failover):
        die("provide a trace id or --last-failover (not both)")

    async def go():
        async with AdmClient(_coord(args)) as adm:
            if args.last_failover:
                tid = await adm.last_failover_trace(_shard(args))
            else:
                tid = args.trace_id
            out = await adm.shard_spans(_shard(args), trace=tid,
                                        limit=args.limit)
            if args.follow:
                # live tail of an in-flight trace: print each span as
                # it COMPLETES, polling until the trace has spans and
                # none remain open, then fall through to the normal
                # post-mortem rendering (Ctrl-C stops the wait)
                seen: set = set()

                def tail(batch) -> None:
                    new = [s for s in batch
                           if s.get("span") not in seen]
                    for s in sorted(new, key=lambda s:
                                    float(s.get("ts") or 0.0)):
                        seen.add(s.get("span"))
                        if not args.json:
                            print("%-24s %-24s %-21s %8.3fs"
                                  % (s.get("time") or "?",
                                     s.get("name") or "?",
                                     s.get("peer") or "-",
                                     float(s.get("dur") or 0.0)))
                    sys.stdout.flush()

                tail(out["spans"])
                while not (out["spans"] and not out["open"]):
                    await asyncio.sleep(args.interval)
                    out = await adm.shard_spans(_shard(args),
                                                trace=tid,
                                                limit=args.limit)
                    tail(out["spans"])
                if not args.json:
                    print("")
        spans = out["spans"]
        roots, children, orphans = assemble_tree(spans)
        # the critical path is computed over the tree's MAIN root: the
        # longest-running GENUINE root (parent None — for a failover
        # trace that is the `failover` span whose window IS the SLI
        # sample).  Orphans are roots only for rendering; a long
        # orphaned restore from a peer whose ring died must not
        # displace the failover root.  All-orphan forests (the whole
        # initiating peer's ring was lost) fall back to the longest.
        orphan_ids = {o["span"] for o in orphans}
        genuine = [r for r in roots if r["span"] not in orphan_ids]
        pool = genuine or roots
        main = max(pool, key=lambda r: float(r.get("dur") or 0.0)) \
            if pool else None
        cp = critical_path(main, children) if main else None

        if args.json:
            print(json.dumps({
                "trace": tid,
                "spans": spans,
                "roots": [r["span"] for r in roots],
                "orphans": [o["span"] for o in orphans],
                "open": out["open"],
                "critical_path": cp,
            }, indent=2))
        else:
            peers = {s.get("peer") for s in spans}
            print("TRACE %s: %d spans across %d peer%s"
                  % (tid, len(spans), len(peers),
                     "" if len(peers) == 1 else "s"))
            if spans:
                print("")
                for line in render_waterfall(roots, children):
                    print(line)
            if cp and cp["stages"]:
                print("")
                print("critical path (%.3fs total):" % cp["total_s"])
                print("%9s %9s %6s  %-24s %s"
                      % ("START", "SELF", "PCT", "SPAN", "PEER"))
                for st in cp["stages"]:
                    print("%+8.3fs %8.3fs %5.1f%%  %-24s %s"
                          % (st["start_s"], st["self_s"], st["pct"],
                             st["name"], st.get("peer") or "-"))
        for key, err in sorted(out["errors"].items()):
            sys.stderr.write("warning: no spans from %s: %s\n"
                             % (key, err))
        for o in orphans:
            sys.stderr.write("warning: span %s (%s) has an unresolved "
                             "parent %s (its recorder's ring may have "
                             "died); shown as a root\n"
                             % (o["span"], o["name"], o.get("parent")))
        for o in out["open"]:
            sys.stderr.write("warning: span %s (%s@%s) is still open\n"
                             % (o.get("span"), o.get("name"),
                                o.get("peer")))
        return 0 if spans else 1
    try:
        return asyncio.run(go())
    except KeyboardInterrupt:
        return 0


def cmd_fault(args) -> int:
    """Live fault injection (docs/fault-injection.md): arm, list, and
    clear named-failpoint rules on the shard's daemons over their
    ``/faults`` endpoints.  ``set`` arms spec strings
    (``point=action[:arg][,k=v...]``) on ONE peer (-n) or an explicit
    --url (e.g. coordd's metrics listener); ``list``/``clear`` default
    to the whole shard.  Specs come right after the verb, flags last
    (argparse cannot resume the spec list after an optional).  The
    partition drill in the docs is two specs::

        manatee-adm fault set coord.client.connect=drop \\
            coord.client.send=drop -n peer1
    """
    from manatee_tpu.faults import CATALOG, FaultSpecError, validate_spec

    async def go():
        if args.verb == "set":
            if not args.args:
                die("fault set requires at least one spec "
                    "(point=action[:arg][,k=v...])")
            for spec in args.args:
                # fail fast with the FULL arm-time checks (catalog
                # membership included), before any arming anywhere
                try:
                    validate_spec(spec)
                except FaultSpecError as e:
                    die(str(e))
            if not args.url and not args.zonename:
                die("fault set requires a target: -n ZONENAME (one "
                    "peer) or --url (one server)")
        elif args.verb == "clear":
            if len(args.args) > 1:
                die("fault clear takes at most one point name")
            if args.args and args.args[0] not in CATALOG:
                # same typo protection as set: a mistyped heal that
                # clears nothing while exiting 0 leaves the fault armed
                die("unknown failpoint %r (see docs/fault-injection.md)"
                    % args.args[0])
        elif args.args:
            die("fault list takes no positional arguments")
        if args.url and (args.zonename or args.backup):
            # silently preferring one target would leave the operator
            # believing the other was armed
            die("--url conflicts with -n/--backup: name exactly one "
                "target")

        skipped: dict = {}
        if args.url:
            targets = [(args.url, args.url.rstrip("/"))]
        else:
            async with AdmClient(_coord(args)) as adm:
                targets, skipped = await adm.fault_targets(
                    _shard(args), zonename=args.zonename,
                    backup=args.backup or args.verb != "set")
        if not targets:
            die("no targetable peer%s"
                % ("".join("; %s: %s" % kv
                           for kv in sorted(skipped.items()))))

        if args.verb == "set":
            results = await AdmClient.fault_request(
                targets, "POST", payload={"specs": list(args.args)})
        elif args.verb == "clear":
            q = "?point=%s" % args.args[0] if args.args else ""
            results = await AdmClient.fault_request(targets, "DELETE",
                                                    query=q)
        else:
            results = await AdmClient.fault_request(targets, "GET")
        # unmappable peers surface as errors (nonzero exit): a clear
        # that skipped a peer may have left it armed
        results.update({label: {"error": why}
                        for label, why in skipped.items()})

        if args.json:
            print(json.dumps(results, indent=2, sort_keys=True))
            return 0 if not any("error" in b for b in results.values()) \
                else 1

        rc = 0
        if args.verb == "list":
            cols = [
                {"name": "target", "label": "TARGET", "width": 27},
                {"name": "point", "label": "POINT", "width": 22},
                {"name": "action", "label": "ACTION", "width": 7},
                {"name": "hits", "label": "HITS", "width": 5},
                {"name": "count", "label": "COUNT", "width": 5},
                {"name": "prob", "label": "PROB", "width": 5},
                {"name": "source", "label": "SOURCE", "width": 7},
            ]
            rows = []
            for label in sorted(results):
                body = results[label]
                if "error" in body:
                    sys.stderr.write("warning: %s: %s\n"
                                     % (label, body["error"]))
                    rc = 1
                    continue
                for r in body.get("armed") or []:
                    rows.append({
                        "target": label,
                        "point": r["point"],
                        "action": (r["action"] + ("!" if r["exhausted"]
                                                  else "")),
                        "hits": r["hits"],
                        "count": ("-" if r["count"] is None
                                  else r["count"]),
                        "prob": ("-" if r["prob"] is None
                                 else "%.2f" % r["prob"]),
                        "source": r["source"],
                    })
            if rows:
                emit_table(cols, rows, omit_header=args.omit_header)
            else:
                print("no faults armed on %d target(s)" % len(targets))
            return rc

        for label in sorted(results):
            body = results[label]
            if "error" in body:
                sys.stderr.write("error: %s: %s\n"
                                 % (label, body["error"]))
                rc = 1
            elif args.verb == "set":
                for r in body.get("armed") or []:
                    print("%s: armed %s -> %s (rule %d)"
                          % (label, r["point"], r["action"], r["id"]))
            else:
                print("%s: cleared %d rule(s)"
                      % (label, body.get("cleared", 0)))
        return rc
    return asyncio.run(go())


# one exposition line: manatee_<name>{labels} <value> — the subset of
# the Prometheus text format our own MetricsBuilder emits (top's
# parser feeds on our own scrapes, never arbitrary expositions)
_PROM_SAMPLE = re.compile(
    r'^manatee_([A-Za-z0-9_]+?)(?:\{([^}]*)\})?[ \t]+'
    r'(-?[0-9][0-9.eE+-]*)[ \t]*$', re.M)
_PROM_LABEL = re.compile(r'([A-Za-z0-9_]+)="([^"]*)"')


def _prom_samples(text: str) -> list[tuple[str, dict, float]]:
    out = []
    for m in _PROM_SAMPLE.finditer(text):
        labels = dict(_PROM_LABEL.findall(m.group(2) or ""))
        try:
            out.append((m.group(1), labels, float(m.group(3))))
        except ValueError:
            continue
    return out


def _prom_pick(samples, name: str, peer: str | None = None
               ) -> float | None:
    """First sample of *name*; with *peer*, only the sample labeled
    for that peer — a fleet sitter's one registry holds every shard's
    gauges, and the scrape knows which peer it asked."""
    for n, labels, v in samples:
        if n == name and (peer is None
                          or labels.get("peer") == peer):
            return v
    return None


def _prom_quantile(samples, name: str, q: float) -> float | None:
    """Approximate quantile from a histogram's cumulative
    ``<name>_bucket`` samples: the upper bound of the bucket the
    target rank lands in (good enough for a dashboard column).  A
    rank landing in +Inf reports the largest finite bound — the
    truth is ">= that"."""
    pts = []
    for n, labels, v in samples:
        if n == name + "_bucket" and "le" in labels:
            le = labels["le"]
            try:
                ub = float("inf") if le == "+Inf" else float(le)
            except ValueError:
                continue
            pts.append((ub, v))
    if not pts:
        return None
    pts.sort()
    total = pts[-1][1]
    if total <= 0:
        return None
    rank = q * total
    best = None
    for ub, c in pts:
        if c >= rank:
            best = ub
            break
    if best == float("inf"):
        finite = [ub for ub, _c in pts if ub != float("inf")]
        best = max(finite) if finite else None
    return best


def _prober_url(args) -> str | None:
    url = getattr(args, "url", None) \
        or os.environ.get("MANATEE_PROBER_URL")
    return url.rstrip("/") if url else None


def _router_url(args) -> str | None:
    url = getattr(args, "router_url", None) \
        or os.environ.get("MANATEE_ROUTER_URL")
    return url.rstrip("/") if url else None


def cmd_slo(args) -> int:
    """Error budgets + burn-rate alerts, fleet-wide: one GET against a
    prober's /alerts (the prober is where the SLO engine runs — it
    fronts every shard over one coordination connection, so its one
    endpoint IS the fleet view).  Exits 1 while any alert is active,
    so the chaos drill and cron checks can gate on it."""
    base = _prober_url(args)
    if not base:
        die("prober URL required (-u/--url or MANATEE_PROBER_URL)")

    async def go():
        try:
            status, body = await AdmClient.http_json(base + "/alerts")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            die("cannot reach prober at %s: %s"
                % (base, str(e) or type(e).__name__))
        if status == 404:
            die(body.get("error") or "no SLO engine at %s" % base)
        if status != 200:
            die("%s/alerts answered HTTP %d" % (base, status))
        if args.json:
            print(json.dumps(body, indent=2, sort_keys=True))
            return 1 if body.get("alerts") else 0
        for c in body.get("configs") or []:
            rules = " ".join(
                "%s>=%gx(%gs/%gs)" % (sev, r["factor"], r["long_s"],
                                      r["short_s"])
                for sev, r in sorted(c["burn_rules"].items()))
            print("# %s: objective %.5g%% over %gs; %s"
                  % (c["name"], 100.0 * c["objective"], c["window_s"],
                     rules))
        cols = [
            {"name": "slo", "label": "SLO", "width": 20},
            {"name": "shard", "label": "SHARD", "width": 16},
            {"name": "objective", "label": "OBJECTIVE", "width": 9},
            {"name": "good", "label": "GOOD", "width": 8},
            {"name": "bad", "label": "BAD", "width": 6},
            {"name": "ratio", "label": "RATIO", "width": 8},
            {"name": "budget", "label": "BUDGET", "width": 7},
            {"name": "burn", "label": "BURN", "width": 6},
        ]
        rows = []
        for r in body.get("slos") or []:
            budget = r.get("budget_remaining")
            rows.append({
                "slo": r["slo"],
                "shard": r["shard"],
                "objective": "%.5g%%" % (100.0 * r["objective"]),
                "good": r["good"],
                "bad": r["bad"],
                "ratio": ("-" if r.get("ratio") is None
                          else "%.3f%%" % (100.0 * r["ratio"])),
                "budget": ("-" if budget is None
                           else "%.0f%%" % (100.0 * budget)),
                "burn": "%.1f" % r["burn"],
            })
        if rows:
            emit_table(cols, rows, omit_header=args.omit_header)
        else:
            print("no SLI events accounted yet at %s" % base)
        alerts = body.get("alerts") or []
        for a in alerts:
            print("ALERT %-7s %s shard=%s burn %.1fx/%.1fx "
                  "(>=%.1fx) for %ds"
                  % (a["severity"], a["slo"], a["shard"],
                     a["burn_long"], a["burn_short"], a["factor"],
                     int(body.get("now", 0) - a["since"])))
        return 1 if alerts else 0
    return asyncio.run(go())


def cmd_router(args) -> int:
    """Live route tables from a `manatee-router`'s /status: which peer
    each fronted shard's writes pin to, how many replicas serve its
    reads (and the worst observed lag among them), plus the serving
    counters — open client connections, writes parked right now,
    lifetime routed requests and parks.  Exits 1 while any shard has
    no primary route (its writes are parking), so failover drills and
    cron checks can gate on the serving plane the same way `slo` gates
    on the measurement plane."""
    base = _router_url(args)
    if not base:
        die("router URL required (-u/--url or MANATEE_ROUTER_URL)")

    async def go():
        try:
            status, body = await AdmClient.http_json(base + "/status")
        except asyncio.CancelledError:
            raise
        except Exception as e:
            die("cannot reach router at %s: %s"
                % (base, str(e) or type(e).__name__))
        if status != 200:
            die("%s/status answered HTTP %d" % (base, status))
        shards = body.get("shards") or []
        if args.json:
            print(json.dumps(body, indent=2, sort_keys=True))
            return 0 if all(s.get("primary") for s in shards) else 1
        cols = [
            {"name": "shard", "label": "SHARD", "width": 16},
            {"name": "listen", "label": "LISTEN", "width": 21},
            {"name": "gen", "label": "GEN", "width": 4},
            {"name": "primary", "label": "PRIMARY", "width": 21},
            {"name": "readers", "label": "READERS", "width": 7},
            {"name": "lag", "label": "LAG-MAX", "width": 7},
            {"name": "conns", "label": "CONNS", "width": 5},
            {"name": "parked", "label": "PARKED", "width": 6},
            {"name": "routed", "label": "ROUTED", "width": 8},
            {"name": "parks", "label": "PARKS", "width": 5},
        ]
        rows = []
        for s in shards:
            lags = [r.get("lag") for r in s.get("readers") or []
                    if r.get("lag") is not None]
            rows.append({
                "shard": s.get("shard", "?"),
                "listen": s.get("listen", "-"),
                "gen": s.get("gen", 0),
                "primary": s.get("primary") or "PARKING",
                "readers": len(s.get("readers") or []),
                "lag": "-" if not lags else "%.2fs" % max(lags),
                "conns": s.get("connections", 0),
                "parked": s.get("parked", 0),
                "routed": s.get("routed", 0),
                "parks": s.get("parks", 0),
            })
        if rows:
            emit_table(cols, rows, omit_header=args.omit_header)
        else:
            print("router at %s fronts no shards" % base)
        return 0 if all(s.get("primary") for s in shards) else 1
    return asyncio.run(go())


def cmd_top(args) -> int:
    """Fleet dashboard: one row per peer — role, uptime, CPU, RSS,
    open fds (obs/process.py's self-metrics), replication lag and
    health score — from the /metrics scrape every sitter already
    serves; plus the prober's per-shard client-observed SLIs when a
    prober URL is given (-u or MANATEE_PROBER_URL), and the router's
    serving-plane rows (route table + parked/routed counters) when a
    router URL is given (-r or MANATEE_ROUTER_URL)."""
    async def go():
        rc = 0
        async with AdmClient(_coord(args)) as adm:
            shard = _shard(args)
            state, _v = await adm.get_state(shard)
            texts, errors = await adm.shard_metrics(shard)
            # the RESHARD column: the durable step record of any
            # in-flight split (reshard/plan.py) — "-" when this shard
            # is not one of the op's owners
            reshard = None
            try:
                from manatee_tpu.coord.api import NoNodeError
                from manatee_tpu.reshard.plan import DEFAULT_RECORD_PATH
                raw, _rv = await adm._client.get(DEFAULT_RECORD_PATH)
                reshard = json.loads(raw.decode())
            except NoNodeError:
                pass
        reshard_step = "-"
        if reshard and "->" in str(reshard.get("op", "")):
            src, _, tgts = reshard["op"].partition("->")
            if shard == src or shard in tgts.split(","):
                reshard_step = str(reshard.get("step", "?"))
        roles: dict[str, str] = {}
        if state:
            for role, plist in (("primary", [state.get("primary")]),
                                ("sync", [state.get("sync")]),
                                ("async", state.get("async") or []),
                                ("deposed", state.get("deposed") or [])):
                for p in plist:
                    if p and p.get("id"):
                        roles[p["id"]] = role
        now = time.time()
        peers_out = []
        for label in sorted(texts):
            samples = _prom_samples(texts[label])
            start = _prom_pick(samples, "process_start_time_seconds")
            rss = _prom_pick(samples, "process_resident_memory_bytes")
            cpu = _prom_pick(samples, "process_cpu_seconds_total")
            fds = _prom_pick(samples, "process_open_fds")
            lag = _prom_pick(samples, "replication_lag_seconds",
                             peer=label)
            score = _prom_pick(samples, "health_score", peer=label)
            # event-loop health (obs/profile.py's monitor): scheduling
            # lag every coroutine in that process experiences, and how
            # often a callback blocked the loop outright
            loop_p99 = _prom_quantile(samples,
                                      "event_loop_lag_seconds", 0.99)
            stalls = _prom_pick(samples, "event_loop_stalls_total")
            peers_out.append({
                "peer": label,
                "role": roles.get(label, "-"),
                "uptime_s": (round(now - start, 1)
                             if start is not None else None),
                "cpu_s": cpu,
                "rss_bytes": rss,
                "fds": fds,
                "lag_s": lag,
                "health_score": score,
                "loop_p99_s": loop_p99,
                "loop_stalls": stalls,
            })
        slis = None
        skew_by_peer: dict[str, float] = {}
        base = _prober_url(args)
        if base:
            try:
                status, body = await AdmClient.http_json(
                    base + "/slis")
                if status == 200:
                    slis = body.get("shards")
                else:
                    errors[base] = "HTTP %d" % status
            except asyncio.CancelledError:
                raise
            except Exception as e:
                errors[base] = str(e) or type(e).__name__
            # the prober is the fleet's clock surveyor: its
            # clock_skew_seconds{peer} gauges (NTP-style offsets it
            # measures every clock-probe pass) feed the SKEW column
            texts2 = await AdmClient._gather_raw(
                [(base, base)], "/metrics", errors, timeout=5.0)
            for name, labels, v in _prom_samples(
                    texts2.get(base, "")):
                if name == "clock_skew_seconds" \
                        and labels.get("peer"):
                    skew_by_peer[labels["peer"]] = v
        for p in peers_out:
            p["skew_s"] = skew_by_peer.get(p["peer"])

        # the serving plane rides the same dashboard: the router's
        # /status is its route table — where writes pin, who serves
        # reads, and how many clients are parked mid-failover
        router = None
        rbase = _router_url(args)
        if rbase:
            try:
                status, body = await AdmClient.http_json(
                    rbase + "/status")
                if status == 200:
                    router = body.get("shards")
                else:
                    errors[rbase] = "HTTP %d" % status
            except asyncio.CancelledError:
                raise
            except Exception as e:
                errors[rbase] = str(e) or type(e).__name__

        if args.json:
            print(json.dumps({"now": round(now, 3),
                              "peers": peers_out, "slis": slis,
                              "router": router,
                              "reshard": reshard,
                              "errors": errors},
                             indent=2, sort_keys=True))
            return 0 if not errors else 1

        cols = [
            {"name": "peer", "label": "PEER", "width": 21},
            {"name": "role", "label": "ROLE", "width": 8},
            {"name": "up", "label": "UP", "width": 8},
            {"name": "cpu", "label": "CPU", "width": 8},
            {"name": "rss", "label": "RSS", "width": 7},
            {"name": "fds", "label": "FDS", "width": 5},
            {"name": "lag", "label": "LAG", "width": 6},
            {"name": "skew", "label": "SKEW", "width": 7},
            {"name": "pred", "label": "PRED", "width": 5},
            {"name": "loop", "label": "LOOP-P99", "width": 8},
            {"name": "stalls", "label": "STALLS", "width": 6},
            {"name": "reshard", "label": "RESHARD", "width": 8},
        ]
        rows = []
        for p in peers_out:
            rows.append({
                "peer": p["peer"],
                "role": p["role"],
                "up": pg_duration(p["uptime_s"]),
                "cpu": ("-" if p["cpu_s"] is None
                        else "%.1fs" % p["cpu_s"]),
                "rss": ("-" if p["rss_bytes"] is None
                        else "%.0fM" % (p["rss_bytes"] / 1048576.0)),
                "fds": ("-" if p["fds"] is None
                        else "%d" % p["fds"]),
                "lag": pg_duration(p["lag_s"]),
                "skew": ("-" if p["skew_s"] is None
                         else "%+.2fs" % p["skew_s"]),
                "pred": ("-" if p["health_score"] is None
                         else "%.2f" % p["health_score"]),
                "loop": ("-" if p["loop_p99_s"] is None
                         else "%.3gs" % p["loop_p99_s"]),
                "stalls": ("-" if p["loop_stalls"] is None
                           else "%d" % p["loop_stalls"]),
                "reshard": reshard_step,
            })
        emit_table(cols, rows, omit_header=args.omit_header)
        if slis is not None:
            scols = [
                {"name": "shard", "label": "SHARD", "width": 16},
                {"name": "primary", "label": "PRIMARY", "width": 21},
                {"name": "wok", "label": "W-OK", "width": 8},
                {"name": "werr", "label": "W-ERR", "width": 6},
                {"name": "p50", "label": "ACK-P50", "width": 8},
                {"name": "p99", "label": "ACK-P99", "width": 8},
                {"name": "stale", "label": "MAX-STALE", "width": 9},
                {"name": "outage", "label": "OUTAGE", "width": 7},
            ]
            srows = []
            for s in slis:
                staleness = [v for v in (s.get("staleness") or
                                         {}).values()
                             if v is not None]
                open_win = s.get("error_window_open")
                last_win = s.get("last_error_window_s")
                srows.append({
                    "shard": s.get("shard", "?"),
                    "primary": s.get("primary") or "-",
                    "wok": s.get("writes_ok", 0),
                    "werr": s.get("writes_error", 0),
                    "p50": ("-" if s.get("ack_p50_s") is None
                            else "%.3fs" % s["ack_p50_s"]),
                    "p99": ("-" if s.get("ack_p99_s") is None
                            else "%.3fs" % s["ack_p99_s"]),
                    "stale": ("-" if not staleness
                              else "%.2fs" % max(staleness)),
                    "outage": ("OPEN" if open_win
                               else "-" if last_win is None
                               else "%.2fs" % last_win),
                })
            print("")
            emit_table(scols, srows, omit_header=args.omit_header)
        if router is not None:
            rcols = [
                {"name": "shard", "label": "SHARD", "width": 16},
                {"name": "primary", "label": "ROUTE-PRIMARY",
                 "width": 21},
                {"name": "readers", "label": "READERS", "width": 7},
                {"name": "conns", "label": "CONNS", "width": 5},
                {"name": "parked", "label": "PARKED", "width": 6},
                {"name": "routed", "label": "ROUTED", "width": 8},
                {"name": "parks", "label": "PARKS", "width": 5},
            ]
            rrows = []
            for s in router:
                rrows.append({
                    "shard": s.get("shard", "?"),
                    "primary": s.get("primary") or "PARKING",
                    "readers": len(s.get("readers") or []),
                    "conns": s.get("connections", 0),
                    "parked": s.get("parked", 0),
                    "routed": s.get("routed", 0),
                    "parks": s.get("parks", 0),
                })
            print("")
            emit_table(rcols, rrows, omit_header=args.omit_header)
        if reshard and reshard.get("step") not in (None, "done",
                                                   "aborted"):
            print("\nreshard in flight: %s at step %r "
                  "(manatee-adm shardmap / reshard --resume)"
                  % (reshard.get("op", "?"), reshard.get("step")))
        for label, err in sorted(errors.items()):
            sys.stderr.write("warning: no metrics from %s: %s\n"
                             % (label, err))
            rc = 1
        return rc
    return asyncio.run(go())


async def _introspection_bodies(args, path: str, *, timeout: float,
                                as_json: bool = False
                                ) -> tuple[dict, dict[str, str]]:
    """(bodies-by-label, errors) for one introspection GET (/profile,
    /tasks): --url targets a single daemon directly (coordd's metrics
    listener, a backupserver, a prober); -n narrows the shard fan-out
    to one peer; default is every peer's status server."""
    errors: dict[str, str] = {}
    if getattr(args, "url", None):
        base = args.url.rstrip("/")
        out = await AdmClient._gather_raw(
            [(base, base)], path, errors, timeout=timeout,
            as_json=as_json)
        return out, errors
    async with AdmClient(_coord(args)) as adm:
        targets, errors = await adm.fault_targets(
            _shard(args), zonename=getattr(args, "zonename", None))
        out = await adm._gather_raw(targets, path, errors,
                                    timeout=timeout, as_json=as_json)
    return out, errors


def cmd_profile(args) -> int:
    """Folded wall-clock stacks from the always-on sampling profiler
    (obs/profile.py) on every peer's status server — or one peer with
    -n, or any single daemon with --url.  Output is flamegraph food:
    pipe it to tools/flamegraph (or use `make flamegraph`).  In the
    fan-out form each line gains a ``peer:<id>`` root frame so one
    merged flamegraph shows where the whole shard's CPU time went."""
    async def go():
        out, errors = await _introspection_bodies(
            args, "/profile?seconds=%g" % args.seconds,
            timeout=args.seconds + 10.0)
        # one explicit target -> raw folded body (round-trippable);
        # a fan-out merge needs the per-peer root frame
        single = bool(args.url or args.zonename)
        for label in sorted(out):
            for line in out[label].splitlines():
                if not line.strip():
                    continue
                print(line if single
                      else "peer:%s;%s" % (label, line))
        rc = 0
        for label, err in sorted(errors.items()):
            sys.stderr.write("warning: no profile from %s: %s\n"
                             % (label, err))
            rc = 1
        return rc
    return asyncio.run(go())


def cmd_tasks(args) -> int:
    """Live asyncio task census (GET /tasks) per peer: every task's
    name, age, suspension point, and bound trace id.  The leaked-task
    triage view — after a failover this should shrink back to the
    steady-state set, exactly like `manatee-adm trace`'s open-span
    check."""
    async def go():
        path = "/tasks"
        if args.name:
            from urllib.parse import quote
            path += "?name=%s" % quote(args.name)
        out, errors = await _introspection_bodies(
            args, path, timeout=5.0, as_json=True)
        if args.json:
            print(json.dumps({"peers": out, "errors": errors},
                             indent=2, sort_keys=True))
            return 0 if not errors else 1
        cols = [
            {"name": "peer", "label": "PEER", "width": 21},
            {"name": "task", "label": "TASK", "width": 24},
            {"name": "age", "label": "AGE", "width": 8},
            {"name": "trace", "label": "TRACE", "width": 16},
            {"name": "where", "label": "WHERE", "width": 40},
        ]
        rows = []
        for label in sorted(out):
            for t in out[label].get("tasks") or []:
                rows.append({
                    "peer": label,
                    "task": t.get("name") or "-",
                    "age": pg_duration(t.get("age_s")),
                    "trace": t.get("trace") or "-",
                    "where": t.get("where") or "-",
                })
        emit_table(cols, rows, omit_header=args.omit_header)
        rc = 0
        for label, err in sorted(errors.items()):
            sys.stderr.write("warning: no task census from %s: %s\n"
                             % (label, err))
            rc = 1
        return rc
    return asyncio.run(go())


def cmd_doctor(args) -> int:
    """Store integrity verifier (docs/crash-recovery.md): offline
    checks of coordd data dirs (--coord-data) and dir-backend store
    roots (--store-root / -c sitter config), plus online cluster-state
    schema/generation checks against the durable history and the
    merged event journal.  Exit 0 when no DAMAGE was found (notes and
    warnings are recoverable crash leftovers); nonzero otherwise —
    the crash-recovery sweep runs this after every recovery."""
    from manatee_tpu.doctor import (
        NOTE,
        WARNING,
        check_cluster,
        check_coordd_store,
        check_dirstore,
        check_history,
        check_introspection,
        check_shard_map,
        check_skew,
        finding,
        summarize,
    )

    findings: list[dict] = []
    store_roots = list(args.store_root or [])
    cfgpath = args.config or os.environ.get("MANATEE_SITTER_CONFIG")
    if cfgpath:
        from manatee_tpu.utils.validation import load_json_config
        cfg = load_json_config(cfgpath, None, name="sitter config")
        if cfg.get("storageBackend", "zfs") == "dir":
            store_roots.append(cfg["storageRoot"])
        else:
            findings.append(finding(
                NOTE, "store-not-dir", cfgpath,
                "storageBackend %r has no offline verifier (zfs "
                "scrub owns that); skipping the store checks"
                % cfg.get("storageBackend", "zfs")))
    for d in args.coord_data or []:
        findings.extend(check_coordd_store(d))
    for root in store_roots:
        findings.extend(check_dirstore(root))
    for d in args.history_dir or []:
        findings.extend(check_history(d))

    coord_addr = args.coord or os.environ.get("COORD_ADDR") \
        or os.environ.get("ZK_IPS")
    online = not args.offline and coord_addr
    if online:
        shard = _shard(args)

        async def go():
            async with AdmClient(coord_addr) as adm:
                state, _v = await adm.get_state(shard)
                hist = await adm.get_history(shard)
                events: list[dict] = []
                skew: dict = {}
                if state is not None:
                    try:
                        out = await adm.shard_events(shard)
                        events = out["events"]
                        skew = out.get("skew") or {}
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        findings.append(finding(
                            NOTE, "journal-unavailable", "cluster",
                            "no event journal reachable (%s); "
                            "generation checks ran against the "
                            "history only" % e))
                # the shard-map integrity surface (reshard/plan.py):
                # map + step record + any parked boot holds
                from manatee_tpu.coord.api import NoNodeError
                from manatee_tpu.reshard.orchestrator import hold_path
                from manatee_tpu.reshard.plan import (
                    DEFAULT_MAP_PATH,
                    DEFAULT_RECORD_PATH,
                )
                smap = record = None
                holds: list[str] = []
                try:
                    raw, _ = await adm._client.get(DEFAULT_MAP_PATH)
                    smap = json.loads(raw.decode())
                except NoNodeError:
                    pass
                try:
                    raw, _ = await adm._client.get(DEFAULT_RECORD_PATH)
                    record = json.loads(raw.decode())
                except NoNodeError:
                    pass
                paths = {r.get("shardPath")
                         for r in (smap or {}).get("ranges") or []
                         if isinstance(r, dict)}
                if record and isinstance(record.get("plan"), dict):
                    paths.add(record["plan"].get("targetPath"))
                for sp in sorted(p for p in paths if p):
                    hp = hold_path(sp)
                    if await adm._client.exists(hp) is not None:
                        holds.append(hp)
                return state, hist, events, skew, smap, record, holds
        try:
            state, hist, events, skew, smap, record, holds = \
                asyncio.run(go())
        except KeyboardInterrupt:
            raise
        except Exception as e:
            # an unreachable coordination service is NOT store damage:
            # keep the offline findings, report the online phase as
            # skipped, and let the exit code reflect the stores alone
            # (use --offline to silence this when coordd is known down)
            findings.append(finding(
                WARNING, "coord-unreachable", coord_addr,
                "online cluster checks skipped: %s" % e))
        else:
            findings.extend(check_cluster(state, hist, events))
            findings.extend(check_introspection(events))
            findings.extend(check_skew(skew))
            findings.extend(check_shard_map(smap, record, holds))
    elif not (args.coord_data or store_roots or args.history_dir
              or findings):
        # findings counts: a zfs-backend -c config produced a
        # store-not-dir NOTE — that is an answer, not a usage error
        die("nothing to verify: provide --coord-data, --store-root, "
            "--history-dir or -c for offline checks, and/or a "
            "coordination address (-z/COORD_ADDR) for the online "
            "checks")

    s = summarize(findings)
    if args.json:
        print(json.dumps(s, indent=2))
    else:
        for f in findings:
            print("%-8s %-22s %s" % (f["level"].upper(), f["check"],
                                     f["target"]))
            print("         %s" % f["detail"])
        print("doctor: %d damage, %d warning(s), %d note(s) — %s"
              % (s["damage"], s["warnings"], s["notes"],
                 "CLEAN" if s["ok"] else "DAMAGED"))
    return 0 if s["ok"] else 1


def cmd_incident(args) -> int:
    """Automated incident reconstruction (docs/observability.md,
    "Incident forensics"): collect HLC-stamped evidence from every
    standard obs surface — the merged journals and spans, the prober's
    burn-rate alerts and metric history, doctor findings, crash
    fingerprints — into one causally-ordered fleet timeline, then walk
    it backward from the client-visible symptom through the failover's
    critical path to the initiating evidence (an injected fault, a
    crash fingerprint, a loop stall, partition-era backoff, a session
    expiry).  --last-alert (the default) starts from the freshest
    symptom; --around reconstructs everything sharing one trace id;
    --window bounds the investigation to [A, B] unix seconds."""
    from manatee_tpu.doctor import check_cluster
    from manatee_tpu.obs.incident import (
        analyze,
        build_timeline,
        collect_evidence,
        render_report,
        write_report_file,
    )

    if sum(map(bool, (args.last_alert, args.around,
                      args.window))) > 1:
        die("choose one of --last-alert / --around / --window")
    mode = ("around" if args.around
            else "window" if args.window else "last-alert")
    window = tuple(args.window) if args.window else None

    async def go():
        async with AdmClient(_coord(args)) as adm:
            shard = _shard(args)
            base = _prober_url(args)

            # Extra obs journals beyond the sitter fan-out: the fleet's
            # fault.injected / crash evidence is not all in sitter
            # rings — a prober.write outage lives in the PROBER's
            # journal, a coordd.oplog.append error in COORDD's.  The
            # prober (when -u names one) and every --source URL join
            # the events stream so those classes attribute too.
            extras: list[tuple[str, str]] = []
            if base:
                extras.append(("prober", base))
            for spec in args.source or []:
                label, sep, url = spec.partition("=")
                if sep and "://" not in label:
                    extras.append((label, url))
                else:
                    extras.append((spec, spec))

            async def fetch_extra_events(out):
                from manatee_tpu.obs.causal import (
                    merge_remote_sync,
                    observe_peer_clock,
                )
                q = "?limit=%d" % args.limit if args.limit else ""
                for label, url in extras:
                    t0 = time.time()
                    try:
                        status, body = await AdmClient.http_json(
                            url.rstrip("/") + "/events" + q)
                        if status != 200:
                            raise AdmError(
                                "%s/events answered HTTP %d"
                                % (url, status))
                    except asyncio.CancelledError:
                        raise
                    except Exception as e:
                        out.setdefault("errors", {})[label] = \
                            str(e) or type(e).__name__
                        continue
                    t1 = time.time()
                    merge_remote_sync(body.get("hlc"))
                    peer = str(body.get("peer") or label)
                    off = observe_peer_clock(peer, body.get("now"),
                                             t0, t1)
                    if off is not None:
                        out.setdefault("skew", {})[peer] = \
                            round(off, 6)
                    for e in body.get("events") or []:
                        if isinstance(e, dict):
                            ent = dict(e)
                            ent.setdefault("peer", peer)
                            out.setdefault("events", []).append(ent)

            extras_fetched = False

            async def events_source(since):
                nonlocal extras_fetched
                out = await adm.shard_events(
                    shard, since=since or None, limit=args.limit)
                # extra journals are fetched whole, once — no paging
                if extras and not extras_fetched:
                    extras_fetched = True
                    await fetch_extra_events(out)
                return out

            async def spans_source():
                return await adm.shard_spans(shard, limit=args.limit)

            async def doctor_source():
                state, _v = await adm.get_state(shard)
                hist = await adm.get_history(shard)
                # journal-vs-store checks run over the durable data
                # only; journal evidence is already on the timeline
                return check_cluster(state, hist, [])

            sources = {"events": events_source,
                       "spans": spans_source,
                       "doctor": doctor_source}
            if base:
                async def alerts_source():
                    status, body = await AdmClient.http_json(
                        base + "/alerts")
                    if status != 200:
                        raise AdmError("%s/alerts answered HTTP %d"
                                       % (base, status))
                    return body

                async def history_source():
                    status, body = await AdmClient.http_json(
                        base + "/history")
                    if status != 200:
                        raise AdmError("%s/history answered HTTP %d"
                                       % (base, status))
                    return body

                sources["alerts"] = alerts_source
                sources["history"] = history_source

            crash_dir = args.crash_dir \
                or os.environ.get("MANATEE_CRASH_DIR")
            collected = await collect_evidence(sources,
                                               crash_dir=crash_dir)
        timeline = build_timeline(collected["evidence"])
        report = analyze(timeline, mode=mode, trace=args.around,
                         window=window, skew=collected["skew"],
                         errors=collected["errors"])
        report["shard"] = shard
        report["generated_ts"] = collected["collected_ts"]
        if args.output:
            await asyncio.to_thread(write_report_file, args.output,
                                    report)
        if args.json:
            print(json.dumps(report, indent=2, sort_keys=True))
        else:
            for line in render_report(report):
                print(line)
            if args.output:
                print("report written to %s" % args.output)
        # 0 for any completed reconstruction (quiet included); 1 only
        # for a symptom the analyzer could not attribute
        return 0 if report["verdict"] != "symptom-unattributed" else 1

    return asyncio.run(go())


def cmd_rebuild(args) -> int:
    """Guarded rebuild flow (lib/adm.js:1319-1684): refuse on the
    primary; deposed peers get their dataset destroyed and their deposed
    entry removed; others get their dataset isolated.  The (restarted)
    sitter then restores from its upstream; we watch the restore job."""
    from manatee_tpu.shard import build_ident, build_storage
    from manatee_tpu.utils.validation import load_json_config

    async def go():
        cfgpath = args.config or os.environ.get("MANATEE_SITTER_CONFIG")
        if not cfgpath:
            die("sitter config required (-c or MANATEE_SITTER_CONFIG)")
        cfg = load_json_config(cfgpath, None, name="sitter config")
        ident = build_ident(cfg)
        storage = build_storage(cfg)
        shard = cfg["shardPath"].rsplit("/", 1)[-1]

        if not args.yes:
            # prompt with NO session open: input() blocks the event
            # loop, and an open session would heartbeat-expire under a
            # slow operator.  The guard checks run on a fresh session
            # after confirmation, so a topology change mid-prompt (this
            # peer becoming primary) is still caught.
            print("This operation will remove all local data and "
                  "rebuild this peer from its upstream.")
            confirm_or_die("Are you sure you want to proceed? "
                           "(yes/no): ")

        async with AdmClient(_coord(args)) as adm:
            state, _ = await adm.get_state(shard)
            if state is None:
                die("no cluster state")
            if state["primary"]["id"] == ident["id"]:
                die("this peer is the primary; will not rebuild")
            deposed_ids = [d["id"] for d in state.get("deposed") or []]
            is_deposed = ident["id"] in deposed_ids

            ds = cfg["dataset"]
            if is_deposed:
                print("Removing deposed dataset")
                if await storage.exists(ds):
                    if await storage.is_mounted(ds):
                        await storage.unmount(ds)
                    await storage.destroy(ds, recursive=True)
                def mutate(st):
                    st["deposed"] = [d for d in st.get("deposed") or []
                                     if d["id"] != ident["id"]]
                    return st
                await adm._update_state(shard, mutate)
                print("Removed from deposed list")
            else:
                print("Attempting to isolate any existing dataset")
                from manatee_tpu.backup.client import RestoreClient
                rc = RestoreClient(storage, dataset=ds,
                                   mountpoint=cfg["dataDir"])
                # the "rebuild-" prefix is what the restore plane
                # recognizes as an incremental-base source; --full
                # isolates under "fullrebuild-", which it never
                # offers — the negotiation is skipped and the classic
                # full stream runs
                name = await rc.isolate(
                    "fullrebuild" if args.full else "rebuild")
                print("Isolated existing dataset as: %s" % name
                      if name else "No existing dataset detected.")
                if name and args.full:
                    print("(--full: isolated snapshots will not be "
                          "offered as incremental bases)")

            # watch the sitter recover naturally (restore progress via
            # its status server, lib/adm.js:1550-1678); a restore that
            # keeps FAILING is a diagnosis, not something to retry
            # silently — count failed attempts and abort after
            # RESTORE_RETRIES with escalating warnings (lib/adm.js:71,
            # :1603-1630)
            import aiohttp
            status = "http://%s:%d" % (cfg["ip"],
                                       int(cfg["postgresPort"]) + 1)
            print("Waiting for peer to rejoin and restore...")
            deadline = time.monotonic() + args.timeout
            last_pct = None
            failures = 0
            failed_attempts: set = set()   # job ids (or attempt #s)
            async with aiohttp.ClientSession() as http:
                while time.monotonic() < deadline:
                    try:
                        async with http.get(
                                status + "/restore",
                                timeout=aiohttp.ClientTimeout(
                                    total=5)) as r:
                            job = (await r.json()).get("restore")
                        if job and job.get("size"):
                            pct = 100.0 * job.get("completed", 0) / \
                                max(1, job["size"])
                            if pct != last_pct:
                                print("restore: %5.1f%%" % pct)
                                last_pct = pct
                        job_key = job and (job.get("id")
                                           or job.get("attempt"))
                        if job and job.get("done") == "failed" and \
                                job_key is not None and \
                                job_key not in failed_attempts:
                            failed_attempts.add(job_key)
                            failures += 1
                            if failures >= RESTORE_RETRIES:
                                # no "0 attempts remaining" tease: the
                                # final failure IS the abort — but its
                                # error detail must not be dropped
                                die("restore failed %d times (last: "
                                    "%s); giving up — investigate the "
                                    "upstream's backup server and "
                                    "storage before retrying"
                                    % (failures,
                                       job.get("error",
                                               "unknown error")))
                            remaining = RESTORE_RETRIES - failures
                            print("warning: restore attempt failed "
                                  "(%s); %d attempt%s remaining"
                                  % (job.get("error", "unknown error"),
                                     remaining,
                                     "" if remaining == 1 else "s"),
                                  file=sys.stderr)
                        async with http.get(
                                status + "/ping",
                                timeout=aiohttp.ClientTimeout(
                                    total=5)) as r:
                            if r.status == 200:
                                print("Peer is healthy again.")
                                return 0
                    except (aiohttp.ClientError, OSError,
                            asyncio.TimeoutError):
                        pass
                    await asyncio.sleep(1.0)
            die("timed out waiting for the peer to recover")
        return 0
    return asyncio.run(go())


def _reshard_cfg(args, shard: str) -> dict:
    """The Resharder config from the CLI surface (docs/resharding.md,
    docs/man/manatee-adm-reshard.md)."""
    cfg: dict = {
        "source": shard,
        "mapPath": args.map_path,
        "recordPath": args.record_path,
        "cutoverBudget": args.cutover_budget,
        "maxRounds": args.max_rounds,
        "freezeGrace": args.freeze_grace,
        "flipTimeout": args.flip_timeout,
        "routers": [u.rstrip("/") for u in (args.router or [])],
    }
    if args.into:
        cfg["into"] = [s.strip() for s in args.into.split(",")
                       if s.strip()]
    if args.at:
        cfg["splitKey"] = args.at
    tc = args.target_config \
        or os.environ.get("MANATEE_RESHARD_TARGET_CONFIG")
    if tc:
        from manatee_tpu.utils.validation import load_json_config
        cfg["target"] = load_json_config(tc, None,
                                         name="target shard config")
    return cfg


def cmd_reshard(args) -> int:
    """Automated live resharding (docs/resharding.md): split one
    shard's key range in place with a prober-measured cutover window.
    A fresh run needs --into a,b (one of them the source) and
    --target-config (the target shard's first sitter config — it
    names the shardPath the split hands the high half to, and the
    dataset the seed restores into).  --resume continues a crashed
    run from its durable step record; --abort rolls a pre-flip run
    back (map restored, seeded target dataset destroyed)."""
    from manatee_tpu.reshard.orchestrator import Resharder, ReshardError

    if sum(map(bool, (args.resume, args.abort, bool(args.into)))) > 1:
        die("choose one of --into a,b / --resume / --abort")
    if not (args.resume or args.abort or args.into):
        die("a fresh reshard needs --into a,b "
            "(or --resume / --abort an existing one)")
    shard = _shard(args)

    async def go():
        async with AdmClient(_coord(args)) as adm:
            cfg = _reshard_cfg(args, shard)
            cfg.setdefault("sourcePath", adm._shard_path(shard))
            r = Resharder(adm._client, cfg)
            if args.abort:
                rec = await r.abort()
            elif args.resume:
                rec = await r.resume()
            else:
                rec = await r.run()
        step = rec.get("step")
        stats = rec.get("stats") or {}
        print("reshard %s: %s%s"
              % (rec.get("op", "?"), step,
                 (" (%d bytes moved over %d round(s))"
                  % (stats["bytesMoved"], stats["rounds"])
                  if step == "done" and stats else "")))
        return 0

    if not (args.yes or args.resume):
        verb = "abort (and DESTROY the seeded target dataset of)" \
            if args.abort else "live-reshard"
        print("This will %s shard %s." % (verb, shard))
        confirm_or_die("Are you sure you want to proceed? (yes/no): ")
    try:
        return asyncio.run(go())
    except ReshardError as e:
        die(str(e), 1)


def cmd_shardmap(args) -> int:
    """The shard map (reshard/plan.py): `shardmap init` bootstraps
    the single-range map (the named shard owns the whole key space);
    `shardmap show` prints the ranges plus any in-flight reshard's
    step record."""
    from manatee_tpu.reshard.plan import (
        ShardMapError,
        ShardMapStore,
    )

    async def go():
        async with AdmClient(_coord(args)) as adm:
            store = ShardMapStore(adm._client,
                                  map_path=args.map_path,
                                  record_path=args.record_path)
            if args.action == "init":
                shard = _shard(args)
                m = await store.init(shard, adm._shard_path(shard))
                ver = 0
                rec = None
            else:
                m, ver = await store.load()
                rec, _rv = await store.load_record()
        if args.json:
            print(json.dumps({"map": m, "version": ver,
                              "record": rec},
                             indent=2, sort_keys=True))
            return 0
        cols = [
            {"name": "lo", "label": "LO", "width": 12},
            {"name": "hi", "label": "HI", "width": 12},
            {"name": "shard", "label": "SHARD", "width": 16},
            {"name": "state", "label": "STATE", "width": 8},
            {"name": "path", "label": "PATH", "width": 24},
        ]
        rows = [{"lo": r["lo"] or "-inf",
                 "hi": "+inf" if r.get("hi") is None else r["hi"],
                 "shard": r["shard"], "state": r["state"],
                 "path": r["shardPath"]} for r in m["ranges"]]
        print("epoch %d (version %d)" % (m["epoch"], ver))
        emit_table(cols, rows, omit_header=args.omit_header)
        if rec is not None and rec.get("step") != "done":
            print("reshard in flight: %s at step %r (resume/abort "
                  "with `manatee-adm reshard`)"
                  % (rec.get("op", "?"), rec.get("step")))
        return 0

    try:
        return asyncio.run(go())
    except ShardMapError as e:
        die(str(e), 1)


# ---- argument parsing ----

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="manatee-adm",
        description="Administer a manatee HA-PostgreSQL shard")
    p.add_argument("-z", "--coord", metavar="HOST:PORT",
                   help="coordination service address "
                        "(env: COORD_ADDR / ZK_IPS)")
    sub = p.add_subparsers(dest="cmd", metavar="COMMAND")

    def add(name, fn, help_, *, shard=True, aliases=()):
        sp = sub.add_parser(name, help=help_, aliases=list(aliases))
        sp.set_defaults(fn=fn)
        if shard:
            sp.add_argument("-s", "--shard", help="shard name "
                                                  "(env: SHARD)")
        return sp

    add("version", cmd_version, "print version", shard=False)

    sp = add("show", cmd_show, "show cluster summary")
    sp.add_argument("-v", "--verbose", action="store_true")

    sp = add("peers", cmd_peers, "list peers")
    sp.add_argument("-o", "--columns")
    sp.add_argument("-r", "--role")
    sp.add_argument("-H", "--omit-header", action="store_true",
                    dest="omit_header")

    sp = add("pg-status", cmd_pg_status, "postgres status per peer")
    sp.add_argument("-o", "--columns")
    sp.add_argument("-r", "--role")
    sp.add_argument("-w", "--wide", action="store_true")
    sp.add_argument("-H", "--omit-header", action="store_true",
                    dest="omit_header")
    sp.add_argument("period", nargs="?", type=float, default=None)
    sp.add_argument("count", nargs="?", type=int, default=None)

    sp = add("verify", cmd_verify, "verify cluster health")
    sp.add_argument("-v", "--verbose", action="store_true")

    sp = add("status", cmd_status, "(deprecated) JSON status")
    sp.set_defaults(shard=None)
    sp.add_argument("-l", "--legacyOrderMode", action="store_true",
                    dest="legacy_order_mode",
                    help="derive topology from election order (v1 "
                         "semantics) instead of cluster state")

    add("zk-state", cmd_zk_state, "dump raw cluster state")
    add("zk-active", cmd_zk_active, "dump active peers")
    sp = add("coord-status", cmd_coord_status,
             "probe coordination ensemble members", shard=False)
    sp.add_argument("-H", "--omit-header", dest="omit_header",
                    action="store_true", help="omit the header row")

    sp = add("freeze", cmd_freeze, "freeze the cluster")
    sp.add_argument("-r", "--reason", required=True)

    add("unfreeze", cmd_unfreeze, "unfreeze the cluster")

    sp = add("reap", cmd_reap, "remove gone peers from the deposed list")
    sp.add_argument("-n", "--zonename", default=None)
    sp.add_argument("-i", "--ip", default=None,
                    help="the IP of the peer to reap")

    sp = add("set-onwm", cmd_set_onwm, "set one-node-write mode")
    sp.add_argument("-m", "--mode", required=True,
                    choices=["on", "off"])
    sp.add_argument("-y", "--yes", action="store_true")

    sp = add("state-backfill", cmd_state_backfill,
             "create initial state from election order")
    sp.add_argument("-y", "--yes", action="store_true",
                    help="skip the confirmation prompt")

    sp = add("promote", cmd_promote, "request a peer promotion")
    sp.add_argument("-n", "--zonename", required=True)
    sp.add_argument("-r", "--role", required=True,
                    choices=["sync", "async"])
    sp.add_argument("-i", "--asyncIndex", type=int, default=None)
    sp.add_argument("-l", "--lagToIgnore", type=float,
                    default=DEFAULT_LAG_TO_IGNORE)
    sp.add_argument("-y", "--yes", action="store_true")

    add("clear-promote", cmd_clear_promote,
        "clear an ignored promotion request")

    sp = add("check-lock", cmd_check_lock,
             "exit 1 if a lock node exists", shard=False)
    sp.add_argument("-p", "--path", required=True)

    sp = add("events", cmd_events,
             "merged shard-wide event timeline (trace-correlated)")
    sp.add_argument("-j", "--json", action="store_true",
                    help="one JSON object per event")
    sp.add_argument("-t", "--trace", default=None,
                    help="only events carrying this trace id")
    sp.add_argument("-e", "--event", default=None,
                    help="only events whose name contains this string")
    sp.add_argument("-n", "--limit", type=int, default=None,
                    help="newest N events per peer")
    sp.add_argument("-H", "--omit-header", action="store_true",
                    dest="omit_header")
    sp.add_argument("-f", "--follow", action="store_true",
                    help="keep polling, printing only each ring's new "
                         "tail (Ctrl-C to stop)")
    sp.add_argument("--interval", type=float, default=1.0,
                    metavar="SECONDS",
                    help="follow poll interval (default 1.0)")

    sp = add("trace", cmd_trace,
             "cross-peer span tree + critical path for one trace")
    sp.add_argument("trace_id", nargs="?", default=None,
                    help="trace id (16 hex chars) to reconstruct")
    sp.add_argument("--last-failover", action="store_true",
                    dest="last_failover",
                    help="resolve the most recent failover's trace id "
                         "from the merged journals")
    sp.add_argument("-j", "--json", action="store_true",
                    help="machine-readable spans + critical path")
    sp.add_argument("-n", "--limit", type=int, default=None,
                    help="newest N spans per peer")
    sp.add_argument("-f", "--follow", action="store_true",
                    help="tail spans as they complete; render the "
                         "tree once the trace has no open spans")
    sp.add_argument("--interval", type=float, default=1.0,
                    metavar="SECONDS",
                    help="follow poll interval (default 1.0)")

    sp = add("slo", cmd_slo,
             "error budgets + active burn-rate alerts (from a prober)",
             shard=False)
    sp.add_argument("-u", "--url", default=None, metavar="URL",
                    help="prober base URL "
                         "(env: MANATEE_PROBER_URL)")
    sp.add_argument("-j", "--json", action="store_true")
    sp.add_argument("-H", "--omit-header", action="store_true",
                    dest="omit_header")

    sp = add("router", cmd_router,
             "route tables + serving counters (from a manatee-router)",
             shard=False)
    sp.add_argument("-u", "--url", dest="router_url", default=None,
                    metavar="URL",
                    help="router status URL "
                         "(env: MANATEE_ROUTER_URL)")
    sp.add_argument("-j", "--json", action="store_true")
    sp.add_argument("-H", "--omit-header", action="store_true",
                    dest="omit_header")

    sp = add("top", cmd_top,
             "fleet dashboard: per-peer resources + client-observed "
             "SLIs")
    sp.add_argument("-u", "--url", default=None, metavar="URL",
                    help="also render per-shard SLIs from this "
                         "prober (env: MANATEE_PROBER_URL)")
    sp.add_argument("-r", "--router-url", dest="router_url",
                    default=None, metavar="URL",
                    help="also render the router's route table + "
                         "serving counters (env: MANATEE_ROUTER_URL)")
    sp.add_argument("-j", "--json", action="store_true")
    sp.add_argument("-H", "--omit-header", action="store_true",
                    dest="omit_header")

    sp = add("profile", cmd_profile,
             "folded-stack CPU profile from the always-on sampler "
             "(flamegraph food)")
    sp.add_argument("-n", "--zonename", default=None,
                    help="profile one peer (zoneId or full peer id)")
    sp.add_argument("--url", default=None,
                    help="profile one server directly, e.g. coordd's "
                         "metrics listener http://host:port")
    sp.add_argument("--seconds", type=float, default=30.0,
                    metavar="N",
                    help="window of samples to fold (default 30)")

    sp = add("tasks", cmd_tasks,
             "live asyncio task census per peer (leak triage)")
    sp.add_argument("-n", "--zonename", default=None,
                    help="census one peer (zoneId or full peer id)")
    sp.add_argument("--url", default=None,
                    help="census one server directly")
    sp.add_argument("-e", "--name", default=None,
                    help="only tasks whose name contains this string")
    sp.add_argument("-j", "--json", action="store_true")
    sp.add_argument("-H", "--omit-header", action="store_true",
                    dest="omit_header")

    sp = add("history", cmd_history, "annotated cluster state history")
    sp.add_argument("-j", "--json", action="store_true")
    sp.add_argument("--sort", choices=["zkSeq", "time"],
                    default="zkSeq", metavar="SORTFIELD",
                    help='sort field: "zkSeq" (default) or "time"')
    sp.add_argument("-v", "--verbose", action="store_true",
                    help="include the per-transition SUMMARY column")

    sp = add("fault", cmd_fault,
             "arm/list/clear live fault injection on the shard")
    sp.add_argument("verb", choices=["set", "list", "clear"],
                    help="set = arm specs on one target; list/clear = "
                         "whole shard by default")
    sp.add_argument("args", nargs="*",
                    help="set: spec strings "
                         "(point=action[:arg][,k=v...]); clear: an "
                         "optional point name")
    sp.add_argument("-n", "--zonename", default=None,
                    help="target one peer (zoneId or full peer id)")
    sp.add_argument("--backup", action="store_true",
                    help="for set: also arm the peer's backupserver "
                         "process (list/clear always include it)")
    sp.add_argument("--url", default=None,
                    help="target one server directly, e.g. coordd's "
                         "metrics listener http://host:port")
    sp.add_argument("-j", "--json", action="store_true")
    sp.add_argument("-H", "--omit-header", action="store_true",
                    dest="omit_header")

    sp = add("doctor", cmd_doctor,
             "verify store integrity (coordd op log, dir-backend "
             "datasets, cluster state vs history/journal)")
    sp.add_argument("--coord-data", action="append", default=None,
                    metavar="DIR",
                    help="verify a coordd --data-dir offline "
                         "(repeatable)")
    sp.add_argument("--store-root", action="append", default=None,
                    metavar="DIR",
                    help="verify a dir-backend store root offline "
                         "(repeatable)")
    sp.add_argument("--history-dir", action="append", default=None,
                    metavar="DIR",
                    help="verify a metric-history segment ring "
                         "offline (repeatable)")
    sp.add_argument("-c", "--config", default=None,
                    help="sitter config to derive the store root from "
                         "(env: MANATEE_SITTER_CONFIG)")
    sp.add_argument("--offline", action="store_true",
                    help="skip the online cluster-state checks even "
                         "when a coordination address is available")
    sp.add_argument("-j", "--json", action="store_true",
                    help="machine-readable findings + summary")

    sp = add("incident", cmd_incident,
             "reconstruct an incident from the HLC-ordered fleet "
             "timeline (symptom -> root cause)")
    sp.add_argument("--last-alert", action="store_true",
                    dest="last_alert",
                    help="walk back from the freshest client-visible "
                         "symptom (the default mode)")
    sp.add_argument("--around", default=None, metavar="TRACE",
                    help="reconstruct everything sharing this trace "
                         "id")
    sp.add_argument("--window", nargs=2, type=float, default=None,
                    metavar=("A", "B"),
                    help="bound the investigation to [A, B] unix "
                         "seconds")
    sp.add_argument("-u", "--url", default=None, metavar="URL",
                    help="prober base URL for alert/history evidence "
                         "(env: MANATEE_PROBER_URL); its journal "
                         "joins the events timeline too")
    sp.add_argument("--source", action="append", default=None,
                    metavar="[LABEL=]URL",
                    help="extra obs base URL (a coordd metrics "
                         "listener, a backup server) whose /events "
                         "journal should join the timeline; "
                         "repeatable")
    sp.add_argument("--crash-dir", default=None, metavar="DIR",
                    dest="crash_dir",
                    help="crash-fingerprint directory "
                         "(env: MANATEE_CRASH_DIR)")
    sp.add_argument("-n", "--limit", type=int, default=None,
                    help="newest N records per peer per page")
    sp.add_argument("-o", "--output", default=None, metavar="FILE",
                    help="also write the machine-readable report "
                         "atomically to FILE")
    sp.add_argument("-j", "--json", action="store_true",
                    help="print the machine-readable report instead "
                         "of the postmortem text")

    sp = add("rebuild", cmd_rebuild, "rebuild this peer from upstream")
    sp.add_argument("-c", "--config",
                    help="sitter config (env: MANATEE_SITTER_CONFIG)")
    sp.add_argument("-y", "--yes", action="store_true")
    sp.add_argument("--timeout", type=float, default=3600.0)
    sp.add_argument("--full", action="store_true",
                    help="skip common-snapshot negotiation: isolate "
                         "the dataset under a name the restore plane "
                         "never offers as a delta base, forcing the "
                         "classic full stream")

    from manatee_tpu.reshard.plan import (
        DEFAULT_MAP_PATH,
        DEFAULT_RECORD_PATH,
    )

    sp = add("reshard", cmd_reshard,
             "split this shard's key range live "
             "(docs/resharding.md)")
    sp.add_argument("--into", metavar="A,B", default=None,
                    help="the two owners after the split; one must be "
                         "the source shard (it keeps the low half)")
    sp.add_argument("--at", metavar="KEY", default=None,
                    help="split key (default: median of the source's "
                         "sampled keys)")
    sp.add_argument("--target-config", default=None, metavar="FILE",
                    dest="target_config",
                    help="the target shard's first sitter config "
                         "(env: MANATEE_RESHARD_TARGET_CONFIG); names "
                         "the shardPath and the dataset the seed "
                         "restores into")
    sp.add_argument("--router", action="append", default=None,
                    metavar="URL",
                    help="router status base URL to confirm the "
                         "write drain against (repeatable)")
    sp.add_argument("--map-path", default=DEFAULT_MAP_PATH,
                    dest="map_path")
    sp.add_argument("--record-path", default=DEFAULT_RECORD_PATH,
                    dest="record_path")
    sp.add_argument("--cutover-budget", type=float, default=5.0,
                    dest="cutover_budget", metavar="SECONDS",
                    help="freeze writes only once a catch-up round "
                         "fits this window (default 5s)")
    sp.add_argument("--max-rounds", type=int, default=8,
                    dest="max_rounds")
    sp.add_argument("--freeze-grace", type=float, default=1.0,
                    dest="freeze_grace")
    sp.add_argument("--flip-timeout", type=float, default=120.0,
                    dest="flip_timeout")
    sp.add_argument("--resume", action="store_true",
                    help="continue a crashed reshard from its durable "
                         "step record")
    sp.add_argument("--abort", action="store_true",
                    help="roll a pre-flip reshard back (map restored, "
                         "seeded target dataset destroyed)")
    sp.add_argument("-y", "--yes", action="store_true")

    sp = add("shardmap", cmd_shardmap,
             "inspect or bootstrap the key-range shard map")
    sp.add_argument("action", choices=("show", "init"), nargs="?",
                    default="show")
    sp.add_argument("--map-path", default=DEFAULT_MAP_PATH,
                    dest="map_path")
    sp.add_argument("--record-path", default=DEFAULT_RECORD_PATH,
                    dest="record_path")
    sp.add_argument("-j", "--json", action="store_true")
    sp.add_argument("-H", "--omit-header", action="store_true",
                    dest="omit_header")

    return p


def main(argv: list[str] | None = None) -> None:
    parser = build_parser()
    args = parser.parse_args(argv)
    if not getattr(args, "fn", None):
        parser.print_help()
        sys.exit(2)
    try:
        rc = args.fn(args)
    except AdmError as e:
        die(str(e), 1)
    except KeyboardInterrupt:
        sys.exit(130)
    sys.exit(rc or 0)


if __name__ == "__main__":
    main()
