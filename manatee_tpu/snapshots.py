"""Periodic snapshot service.

Reference parity: lib/snapShotter.js — every ``pollInterval`` take a
storage snapshot named with epoch-ms, but skip if the local sitter's
``/ping`` reports unhealthy (:122-152, :445-512); an independent,
self-rescheduling cleanup pass lists snapshots by creation time, only
ever touches 13-digit-epoch names, keeps the newest ``snapshotNumber``,
and keeps per-snapshot stuck-destroy accounting with a fatal alarm when
NO candidate can be deleted (:177-433).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable

import aiohttp

from manatee_tpu.obs import get_registry
from manatee_tpu.storage.base import (
    StorageBackend,
    StorageError,
    is_epoch_ms_snapshot,
)

log = logging.getLogger("manatee.snapshotter")

# epoch-ms snapshots still held after a cleanup pass: the pool of
# candidate delta bases this peer can offer or serve (one dataset per
# snapshotter process, so no labels)
SNAPS_RETAINED = get_registry().gauge(
    "snapshots_retained",
    "epoch-ms snapshots retained after the last cleanup pass")


class SnapShotter:
    def __init__(self, storage: StorageBackend, *, dataset: str,
                 poll_interval: float = 3600.0,
                 snapshot_number: int = 50,
                 sitter_ping_url: str | None = None):
        self.storage = storage
        self.dataset = dataset
        self.poll_interval = poll_interval
        self.snapshot_number = snapshot_number
        self.sitter_ping_url = sitter_ping_url
        self._tasks: list[asyncio.Task] = []
        self._stuck: dict[str, int] = {}   # snapshot name -> failed destroys
        self._listeners: dict[str, list[Callable]] = {}

    def on(self, event: str, cb: Callable) -> None:
        self._listeners.setdefault(event, []).append(cb)

    def _emit(self, event: str, payload=None) -> None:
        for cb in self._listeners.get(event, []):
            try:
                cb(payload)
            except Exception:
                log.exception("snapshotter listener failed")

    def start(self) -> None:
        self._tasks = [
            asyncio.create_task(self._create_loop()),
            asyncio.create_task(self._cleanup_loop()),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        for t in self._tasks:
            try:
                await t
            except asyncio.CancelledError:
                pass       # the cancel we just requested
            except Exception:
                log.exception("snapshot loop died uncleanly")

    # -- creation --

    async def _create_loop(self) -> None:
        while True:
            try:
                await self.create_snapshot()
            except asyncio.CancelledError:
                raise
            except Exception:
                # the loops are self-rescheduling and must survive
                # anything (snapShotter.js parity): a dataset being
                # isolated/recreated under us mid-rebuild raced a
                # cleanup pass into a raw OSError once, silently
                # killing the task while its sibling kept running —
                # snapshots then piled up unbounded (chaos seed 6)
                log.exception("snapshot pass failed; continuing")
            await asyncio.sleep(self.poll_interval)

    async def create_snapshot(self) -> bool:
        """One snapshot attempt; returns whether one was taken."""
        if self.sitter_ping_url:
            if not await self._sitter_healthy():
                log.info("sitter unhealthy; skipping snapshot "
                         "(snapShotter.js:122-152)")
                return False
        try:
            snap = await self.storage.snapshot(self.dataset)
            log.info("took snapshot %s", snap.full)
            self._emit("snapshot", snap)
            return True
        except StorageError as e:
            log.warning("snapshot of %s failed: %s", self.dataset, e)
            return False

    async def _sitter_healthy(self) -> bool:
        try:
            async with aiohttp.ClientSession() as http:
                async with http.get(
                        self.sitter_ping_url,
                        timeout=aiohttp.ClientTimeout(total=5)) as r:
                    return r.status == 200
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError):
            return False

    # -- cleanup --

    async def _cleanup_loop(self) -> None:
        while True:
            await asyncio.sleep(self.poll_interval)
            try:
                await self.cleanup_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                log.exception("cleanup pass failed; continuing")

    async def cleanup_once(self) -> None:
        try:
            snaps = await self.storage.list_snapshots(self.dataset)
        except StorageError as e:
            log.warning("cannot list snapshots: %s", e)
            return
        # only 13-digit epoch names are ours to manage
        # (snapShotter.js:251)
        ours = [s for s in snaps if is_epoch_ms_snapshot(s.name)]
        # RETENTION PIN: the newest epoch-ms snapshot is the best
        # common-base candidate a peer can offer for an incremental
        # rebuild (and the one the backup sender streams) — the
        # cleanup pass must NEVER destroy it, even under a zero/absurd
        # snapshotNumber.  keep-newest-N mostly covers this already;
        # the floor makes it explicit.
        keep = max(1, self.snapshot_number)
        excess = len(ours) - keep
        if excess <= 0:
            SNAPS_RETAINED.set(len(ours))
            return
        victims = ours[:excess]   # list is creation-ascending
        any_deleted = False
        for v in victims:
            try:
                await self.storage.destroy_snapshot(self.dataset, v.name)
                self._stuck.pop(v.name, None)
                any_deleted = True
                log.info("deleted old snapshot %s", v.full)
            except StorageError as e:
                self._stuck[v.name] = self._stuck.get(v.name, 0) + 1
                log.warning("cannot delete snapshot %s (attempt %d): %s",
                            v.full, self._stuck[v.name], e)
        deleted = sum(1 for v in victims if v.name not in self._stuck)
        SNAPS_RETAINED.set(len(ours) - deleted)
        if not any_deleted and victims:
            # every deletable candidate is stuck: fatal alarm path
            # (snapShotter.js:370-404)
            log.critical("ALL %d excess snapshots are stuck; manual "
                         "intervention required", len(victims))
            self._emit("stuck", [v.name for v in victims])
