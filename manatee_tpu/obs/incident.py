"""Incident forensics: one HLC-ordered fleet timeline, reconstructed
backward from symptom to root cause.

The paper's operators reconstruct incidents by hand: grep per-peer
logs, guess at clock skew, correlate a client-visible outage with
whatever the control plane was doing at "about that time".  Everything
this tree already exports — journals, spans, burn-rate alerts, metric
history, doctor findings, crash fingerprints — carries a hybrid
logical clock stamp (obs/causal.py), so the guesswork is mechanical
now:

- :func:`collect_evidence` fans out over the standard obs routes
  (every payload a ``manatee-adm`` fan-out already fetches) and the
  crash-fingerprint directory, normalizing each record into one
  kind-tagged evidence list;
- :func:`build_timeline` merges it all into a single fleet timeline
  ordered by :func:`~manatee_tpu.obs.causal.hlc_sort_key` — cause
  before effect at any wall-clock skew;
- :func:`analyze` walks that timeline backward from the client-visible
  symptom (a fired burn-rate alert, a measured error window) through
  the failover root span's critical path to the initiating evidence:
  an injected fault, a crash fingerprint, a loop stall, partition-era
  reconnect backoff, or a session expiry;
- :func:`render_report` emits the human postmortem;
  ``manatee-adm incident -j`` prints the machine form.

Degradation contract: collection is fan-out over lossy HTTP — partial
peer failure yields a partial (but honest) report with the failures
named.  The ``obs.incident.collect`` failpoint sits before the
fan-out; a crash there must leave no partial report artifact, which is
why :func:`write_report_file` lands reports via tmp+fsync+rename only.

A quiet fleet must analyze to a quiet verdict: the closed-loop chaos
drill asserts both directions — every injected fault class is named as
root cause, and a soak with nothing armed attributes nothing.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

from manatee_tpu.obs.causal import MERGE_SKEW_BOUND_S, hlc_sort_key
from manatee_tpu.obs.spans import assemble_tree, critical_path

# evidence kinds, in collection order
EVIDENCE_KINDS = ("event", "span", "alert", "history", "doctor",
                  "crash")

# how many event pages a paginated collect will pull per fan-out
# before declaring the ring drained (each page advances per-peer seq
# cursors, so a page that adds nothing new ends the loop early)
DEFAULT_MAX_PAGES = 8

# the chain filter: timeline entries that narrate a failover even when
# they carry no trace id (plus anything sharing the symptom's or the
# root cause's trace)
_CHAIN_EVENTS = frozenset((
    "failover.detected", "failover.complete", "failover.aborted",
    "takeover.begin", "transition.begin", "transition.committed",
    "transition.conflict", "role.change",
    "pg.reconfigure.begin", "pg.reconfigure.done",
    "pg.reconfigure.failed", "pg.reconfigure.cancelled",
    "restore.start", "restore.done", "restore.failed",
    "coord.session.connected", "coord.session.disconnected",
    "coord.session.expired",
    "fault.armed", "fault.injected",
    "prober.error_window",
    "slo.alert.fired", "slo.alert.resolved",
    "obs.loop.stall", "probe.flip",
))

_MAX_CHAIN = 200


class IncidentError(Exception):
    pass


# ---- collection ----

def read_crash_fingerprints(crash_dir) -> tuple[list[dict],
                                                dict[str, str]]:
    """The breadcrumbs dying processes leave (faults._crash_now writes
    one JSON file per crash into ``MANATEE_CRASH_DIR``): (entries,
    errors).  A crashed peer's in-memory journal died with it, so
    these files are the ONLY evidence naming the seam it died at."""
    entries: list[dict] = []
    errors: dict[str, str] = {}
    if not crash_dir:
        return entries, errors
    try:
        names = sorted(os.listdir(crash_dir))
    except FileNotFoundError:
        return entries, errors
    except OSError as e:
        errors["crash:" + str(crash_dir)] = str(e)
        return entries, errors
    for name in names:
        if not (name.startswith("crash-") and name.endswith(".json")):
            continue
        path = os.path.join(crash_dir, name)
        try:
            with open(path) as f:
                fp = json.load(f)
        except (OSError, ValueError) as e:
            errors["crash:" + name] = str(e)
            continue
        if isinstance(fp, dict):
            fp["kind"] = "crash"
            entries.append(fp)
    return entries, errors


async def _collect_events(fetch, evidence: list, errors: dict,
                          skew: dict, max_pages: int) -> None:
    """Drain every peer's journal ring through a paginated source:
    *fetch(since)* mirrors ``AdmClient.shard_events`` (per-peer seq
    cursors), so each page ships only new tail and a ring larger than
    one page's limit is still collected whole."""
    cursors: dict[str, int] = {}
    for _page in range(max_pages):
        out = await fetch(dict(cursors))
        for k, v in (out.get("errors") or {}).items():
            errors["events:%s" % k] = str(v)
        for k, v in (out.get("skew") or {}).items():
            skew[str(k)] = v
        fresh = 0
        for e in out.get("events") or []:
            if not isinstance(e, dict):
                continue
            peer, seq = e.get("peer"), e.get("seq")
            if peer is not None and isinstance(seq, int):
                if seq <= cursors.get(peer, 0):
                    continue           # page-overlap duplicate
                cursors[peer] = max(cursors.get(peer, 0), seq)
            ent = dict(e)
            ent["kind"] = "event"
            evidence.append(ent)
            fresh += 1
        if not fresh:
            return


async def collect_evidence(sources: dict, *, crash_dir=None,
                           max_pages: int = DEFAULT_MAX_PAGES) -> dict:
    """Fan out over the standard obs surfaces and assemble the raw
    evidence set.  *sources* maps source name -> async callable:

    - ``events``: called with a per-peer ``since`` cursor dict,
      returns ``{"events", "errors", "skew"}`` (shard_events);
    - ``spans``: returns ``{"spans", "open", "errors", "skew"}``;
    - ``alerts``: returns the prober's ``/alerts`` body (or None);
    - ``history``: returns a ``/history`` body (``{"records": []}``)
      or a per-peer mapping of such bodies;
    - ``doctor``: returns a list of doctor findings.

    Absent sources are skipped (a fleet without a prober still gets a
    journal+span timeline).  Per-peer fetch failures land in the
    ``errors`` map namespaced by source — a partial fleet yields a
    partial report, never an exception.  Returns ``{"evidence",
    "errors", "skew", "collected_ts"}``."""
    from manatee_tpu import faults

    # the collector seam: crash here (the sweep's scenario) must leave
    # no partial report artifact — reports only ever land via
    # write_report_file's tmp+rename
    await faults.point("obs.incident.collect")

    evidence: list[dict] = []
    errors: dict[str, str] = {}
    skew: dict[str, float] = {}
    now = time.time()

    async def run(name, coro):
        try:
            return await coro
        except asyncio.CancelledError:
            raise
        except Exception as e:
            errors[name] = str(e) or type(e).__name__
            return None

    if sources.get("events"):
        try:
            await _collect_events(sources["events"], evidence, errors,
                                  skew, max_pages)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            errors["events"] = str(e) or type(e).__name__

    if sources.get("spans"):
        out = await run("spans", sources["spans"]())
        if out:
            for k, v in (out.get("errors") or {}).items():
                errors["spans:%s" % k] = str(v)
            for k, v in (out.get("skew") or {}).items():
                skew.setdefault(str(k), v)
            for s in out.get("spans") or []:
                if isinstance(s, dict):
                    ent = dict(s)
                    ent["kind"] = "span"
                    evidence.append(ent)

    if sources.get("alerts"):
        body = await run("alerts", sources["alerts"]())
        if isinstance(body, dict):
            for a in body.get("alerts") or []:
                if not isinstance(a, dict):
                    continue
                ent = dict(a)
                ent["kind"] = "alert"
                ent.setdefault("ts", a.get("since"))
                ent.setdefault("peer", "prober")
                ent.setdefault(
                    "event", "slo.alert.active")
                evidence.append(ent)

    if sources.get("history"):
        body = await run("history", sources["history"]())
        if isinstance(body, dict):
            # one body, or a per-peer mapping of bodies
            bodies = ([body] if "records" in body
                      else [b for b in body.values()
                            if isinstance(b, dict)])
            for b in bodies:
                for r in b.get("records") or []:
                    if isinstance(r, dict):
                        ent = dict(r)
                        ent["kind"] = "history"
                        ent.setdefault("peer", b.get("peer"))
                        evidence.append(ent)

    if sources.get("doctor"):
        findings = await run("doctor", sources["doctor"]())
        for f in findings or []:
            if isinstance(f, dict):
                ent = dict(f)
                ent["kind"] = "doctor"
                # findings carry no timestamp of their own: they are
                # observations made NOW about durable state
                ent.setdefault("ts", round(now, 3))
                ent.setdefault("peer", f.get("target"))
                evidence.append(ent)

    crashes, crash_errors = await asyncio.to_thread(
        read_crash_fingerprints, crash_dir)
    evidence.extend(crashes)
    errors.update(crash_errors)

    return {"evidence": evidence, "errors": errors, "skew": skew,
            "collected_ts": round(now, 3)}


# ---- timeline ----

def build_timeline(evidence: list[dict]) -> list[dict]:
    """The single fleet timeline: every kind-tagged evidence record in
    HLC order (wall-clock fallback for unstamped records), cause
    before effect at any skew."""
    return sorted((e for e in evidence if isinstance(e, dict)),
                  key=hlc_sort_key)


def _in_window(ent: dict, window) -> bool:
    if window is None:
        return True
    a, b = window
    try:
        ts = float(ent.get("ts") or 0.0)
    except (TypeError, ValueError):
        return False
    return (a is None or ts >= a) and (b is None or ts <= b)


# ---- analysis ----

# root-cause classes by evidence tier: ground truth (the thing that
# was actually done to the fleet) beats mechanism (how the damage
# propagated), and within a tier the cause NEAREST before the symptom
# wins.
def _classify_cause(ent: dict) -> tuple[int, str] | None:
    kind = ent.get("kind")
    event = str(ent.get("event") or "")
    if kind == "crash":
        return 0, "crash-at-seam"
    if event == "fault.injected":
        return 0, "injected-fault"
    if event == "obs.loop.stall":
        return 1, "loop-stall"
    if kind == "doctor" and str(ent.get("level")) == "damage":
        return 1, "store-damage"
    if event == "coord.session.expired":
        return 2, "session-expiry"
    if kind == "span" and ent.get("name") == "retry.backoff" \
            and "coord" in str(ent.get("op") or ""):
        return 2, "partition-backoff"
    return None


def _is_symptom(ent: dict) -> bool:
    """Client-visible symptoms only: a fired burn-rate alert, an
    active alert, or a measured write-outage window — the things a
    USER of the shard felt, not control-plane internals."""
    if ent.get("kind") == "alert":
        return True
    return str(ent.get("event") or "") in ("slo.alert.fired",
                                           "prober.error_window")


def _cause_summary(ent: dict, cls: str) -> dict:
    out = {
        "class": cls,
        "peer": ent.get("peer"),
        "ts": ent.get("ts"),
        "hlc": ent.get("hlc"),
        "evidence": ent,
    }
    if cls in ("crash-at-seam", "injected-fault"):
        # the closed loop: name the actually-injected failpoint
        out["point"] = ent.get("point")
        out["action"] = ent.get("action")
        if cls == "crash-at-seam":
            out["action"] = "crash"
            out["variant"] = ent.get("variant")
            out["status"] = ent.get("status")
    elif cls == "loop-stall":
        out["detail"] = "event loop stalled %.3fs" % float(
            ent.get("seconds") or ent.get("stall_s") or 0.0) \
            if (ent.get("seconds") or ent.get("stall_s")) \
            else "event loop stall"
    elif cls == "store-damage":
        out["detail"] = "%s: %s" % (ent.get("check"),
                                    ent.get("detail"))
    elif cls == "session-expiry":
        out["detail"] = "coordination session expired (%s)" \
            % (ent.get("session") or "?")
    elif cls == "partition-backoff":
        out["detail"] = ("reconnect backoff op=%s attempt=%s — the "
                         "partition-era signature"
                         % (ent.get("op"), ent.get("attempt")))
    return out


def _failover_analysis(timeline: list[dict], upto: int) -> dict | None:
    """The failover root span's critical path, when a failover is in
    evidence at or before the symptom: find the freshest
    failover.complete/.detected event, gather that trace's spans, and
    reuse the `manatee-adm trace` machinery."""
    tid = None
    for ent in reversed(timeline[:upto + 1]):
        if str(ent.get("event") or "") in ("failover.complete",
                                           "failover.detected") \
                and ent.get("trace"):
            tid = ent["trace"]
            break
    if tid is None:
        return None
    spans = [e for e in timeline
             if e.get("kind") == "span" and e.get("trace") == tid]
    if not spans:
        return {"trace": tid, "critical_path": None}
    roots, children, orphans = assemble_tree(spans)
    orphan_ids = {o["span"] for o in orphans}
    genuine = [r for r in roots if r["span"] not in orphan_ids]
    pool = genuine or roots
    main = max(pool, key=lambda r: float(r.get("dur") or 0.0)) \
        if pool else None
    return {"trace": tid,
            "root": main.get("name") if main else None,
            "critical_path": (critical_path(main, children)
                              if main else None)}


def analyze(timeline: list[dict], *, mode: str = "last-alert",
            trace: str | None = None,
            window: tuple[float | None, float | None] | None = None,
            skew: dict | None = None,
            errors: dict | None = None) -> dict:
    """The reconstruction: pick the symptom the *mode* asks about,
    walk the HLC-ordered *timeline* backward to the initiating
    evidence, and return the report dict (render_report's input, and
    `manatee-adm incident -j`'s output).

    Modes: ``last-alert`` (freshest client-visible symptom),
    ``around`` (everything sharing *trace*), ``window`` (symptoms
    inside ``[a, b]``).  A timeline with no symptom yields verdict
    ``quiet`` with NO root cause — a quiet soak must not attribute."""
    if mode == "around" and not trace:
        raise IncidentError("mode 'around' requires a trace id")
    scoped = [e for e in timeline if _in_window(e, window)]
    if mode == "around":
        in_trace = [e for e in scoped if e.get("trace") == trace]
        # the symptom is the trace's last consequence; the
        # investigation window is everything up to then
        symptom = in_trace[-1] if in_trace else None
    else:
        symptom = None
        for ent in reversed(scoped):
            if _is_symptom(ent):
                symptom = ent
                break

    skew = dict(skew or {})
    skew_warnings = sorted(
        p for p, off in skew.items()
        if abs(off) > MERGE_SKEW_BOUND_S)
    base = {
        "mode": mode,
        "trace": trace,
        "window": list(window) if window else None,
        "skew": skew,
        "skew_warnings": skew_warnings,
        "errors": dict(errors or {}),
        "counts": {k: sum(1 for e in timeline if e.get("kind") == k)
                   for k in EVIDENCE_KINDS},
    }
    if symptom is None:
        base.update(verdict="quiet", symptom=None, root_cause=None,
                    chain=[], failover=None)
        return base

    sym_idx = next(i for i, e in enumerate(scoped) if e is symptom)
    best: tuple[int, int] | None = None     # (tier, index); latest
    best_cls = None
    for i in range(sym_idx, -1, -1):
        got = _classify_cause(scoped[i])
        if got is None:
            continue
        tier, cls = got
        if best is None or tier < best[0]:
            best = (tier, i)
            best_cls = cls
            if tier == 0:
                break                       # ground truth: done
    root_cause = (_cause_summary(scoped[best[1]], best_cls)
                  if best is not None else None)

    lo = best[1] if best is not None else 0
    involved = {t for t in (symptom.get("trace"),
                            (scoped[lo].get("trace")
                             if best is not None else None))
                if t}
    chain = []
    for ent in scoped[lo:sym_idx + 1]:
        if ent.get("kind") in ("crash", "alert") \
                or str(ent.get("event") or "") in _CHAIN_EVENTS \
                or (ent.get("trace") and ent["trace"] in involved):
            chain.append(ent)
    if len(chain) > _MAX_CHAIN:
        chain = chain[:1] + chain[-(_MAX_CHAIN - 1):]

    base.update(
        verdict="incident" if root_cause else "symptom-unattributed",
        symptom=symptom,
        root_cause=root_cause,
        chain=chain,
        failover=_failover_analysis(scoped, sym_idx),
    )
    return base


# ---- rendering / persistence ----

def _ent_line(ent: dict) -> str:
    kind = ent.get("kind") or "?"
    what = (ent.get("event") or ent.get("name")
            or ent.get("check") or ent.get("point") or "?")
    extra = ""
    if kind == "crash":
        what = "crash@%s" % ent.get("point")
        extra = " status=%s" % ent.get("status")
    elif kind == "alert":
        extra = " %s/%s" % (ent.get("slo"), ent.get("severity"))
    elif ent.get("event") == "fault.injected":
        what = "fault.injected %s=%s" % (ent.get("point"),
                                         ent.get("action"))
    elif kind == "span":
        extra = " %.3fs" % float(ent.get("dur") or 0.0)
    return "%-24s %-21s %-7s %s%s" % (
        ent.get("time") or ent.get("ts") or "?",
        ent.get("peer") or "-", kind, what, extra)


def render_report(report: dict) -> list[str]:
    """The human postmortem, one line per list element (the CLI's
    non-JSON output)."""
    lines = ["INCIDENT REPORT (mode=%s)" % report.get("mode"),
             "verdict: %s" % report.get("verdict")]
    sym = report.get("symptom")
    if sym is None:
        lines.append("no client-visible symptom in the collected "
                     "window: nothing to attribute")
    else:
        lines.append("symptom:")
        lines.append("  " + _ent_line(sym))
    rc = report.get("root_cause")
    if rc is not None:
        head = "root cause: %s" % rc["class"]
        if rc.get("point"):
            head += " at failpoint %s" % rc["point"]
            if rc.get("action"):
                head += " (action=%s)" % rc["action"]
        if rc.get("peer"):
            head += " on %s" % rc["peer"]
        lines.append(head)
        if rc.get("detail"):
            lines.append("  %s" % rc["detail"])
        lines.append("  evidence: " + _ent_line(rc["evidence"]))
    elif sym is not None:
        lines.append("root cause: NOT FOUND (no initiating evidence "
                     "survives in the collected rings)")
    chain = report.get("chain") or []
    if chain:
        lines.append("")
        lines.append("causal chain (%d entries, HLC order):"
                     % len(chain))
        for ent in chain:
            lines.append("  " + _ent_line(ent))
    fo = report.get("failover")
    if fo and fo.get("critical_path"):
        cp = fo["critical_path"]
        lines.append("")
        lines.append("failover %s critical path (%.3fs total):"
                     % (fo["trace"], cp["total_s"]))
        for st in cp["stages"]:
            lines.append("  %+8.3fs %8.3fs %5.1f%%  %-24s %s"
                         % (st["start_s"], st["self_s"], st["pct"],
                            st["name"], st.get("peer") or "-"))
    skew = report.get("skew") or {}
    if skew:
        lines.append("")
        lines.append("clock skew (remote minus local): "
                     + "  ".join("%s %+0.3fs" % (p, skew[p])
                                 for p in sorted(skew)))
    for p in report.get("skew_warnings") or []:
        lines.append("WARNING: measured skew on %s exceeds the "
                     "journal-merge safety bound (%.1fs): pre-HLC "
                     "peers' records may misorder" %
                     (p, MERGE_SKEW_BOUND_S))
    errors = report.get("errors") or {}
    for k in sorted(errors):
        lines.append("warning: evidence from %s unavailable: %s"
                     % (k, errors[k]))
    return lines


def write_report_file(path: str, report: dict) -> None:
    """Atomic report persistence: tmp + fsync + rename, so a collector
    (or the process around it) dying mid-write leaves either the
    previous report or none — never a torn artifact the crash sweep
    could mistake for a finding."""
    tmp = "%s.tmp.%d" % (path, os.getpid())
    try:
        with open(tmp, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
