"""Continuous profiling plane: sampling profiler, event-loop health,
live task census.

The rest of ``manatee_tpu/obs`` can say *that* time was spent (spans,
critical path) and *that* clients were hurt (prober, burn rates); this
module says what the CPU and the event loop were actually *doing*.
Three always-on surfaces, wired into every daemon's listener by
``daemons/common.attach_obs_routes``:

- a **sampling wall-clock profiler** (:class:`SamplingProfiler`): a
  background thread samples ``sys._current_frames()`` at a configurable
  rate, folds each thread's stack into a collapsed-stack string, and
  accumulates counts.  An async drain task moves the accumulated
  counts into a bounded time-bucketed ring about once a second (the
  ``obs.profile.sample`` failpoint seam), so ``GET /profile?seconds=N``
  can answer for any recent window in folded-stack format — one
  ``frame;frame;frame count`` line per distinct stack, ready for
  ``tools/flamegraph`` or any flamegraph renderer.  The sampler meters
  its own CPU (``profiler_self_seconds_total``) so the overhead budget
  is a measured number, not a promise;
- an **event-loop health monitor** (:class:`LoopMonitor`): a self-timing
  tick coroutine (the ``obs.loop.tick`` seam) feeds the overshoot of
  every sleep into the ``event_loop_lag_seconds`` histogram, while a
  watchdog thread detects a *blocked* loop — a callback holding the
  loop past ``stall_threshold`` — and, while the loop is still stuck,
  captures the loop thread's running frame and journals
  ``obs.loop.stall`` with the offending stack.  The runtime detector
  also audits the static analysis (lint/summaries.py): a stalled frame
  that mnt-lint's blocking rules *exempt* (path-disable or an inline
  suppression), or whose culprit is not derivable from the
  interprocedural may-block summaries, is journaled as
  ``obs.lint.discrepancy`` for `manatee-adm doctor`;
- a **live task census** (:func:`tasks_payload`, ``GET /tasks``): every
  asyncio task's name, age, innermost frame, and bound trace/span id —
  task leaks become observable the way open spans already are.

Everything here is stdlib-only and allocation-light, and every loop
swallows its own errors: observability must never be able to hurt HA.
"""

from __future__ import annotations

import asyncio
import logging
import os
import sys
import threading
import time
import weakref
from collections import deque
from pathlib import Path

from manatee_tpu import faults
from manatee_tpu.obs import spans as _spans_mod
from manatee_tpu.obs import trace as _trace_mod
from manatee_tpu.obs.journal import get_journal
from manatee_tpu.obs.metrics import get_registry

log = logging.getLogger("manatee.obs.profile")

DEFAULT_HZ = 20.0          # sampling passes per second (0 = off)
DEFAULT_TICK = 0.25        # loop-monitor tick interval, seconds
DEFAULT_STALL = 1.0        # loop blocked longer than this = a stall
DRAIN_INTERVAL = 1.0       # pending samples -> ring, seconds
RING_WINDOW = 600.0        # how far back GET /profile can reach
MAX_STACK_DEPTH = 64

_REPO_ROOT = Path(__file__).resolve().parents[2]

# code object -> collapsed-stack frame label (code objects are few and
# long-lived; caching them bounds per-sample allocation)
_LABELS: dict = {}

# (root, code-object chain) -> folded string.  Labels carry no line
# numbers, so the same call path always folds identically; caching the
# whole fold turns the hot sampling path into one tuple build + one
# dict hit.  Distinct call paths are finite but unbounded in theory,
# so the cache is dropped wholesale if it ever balloons.
_FOLDS: dict = {}
_FOLDS_MAX = 4096


def _short_path(filename: str) -> str:
    """Repo-relative path for tree files, basename for everything
    else — short enough to read in a flamegraph box."""
    for marker in ("/manatee_tpu/", "/tests/", "/tools/"):
        i = filename.rfind(marker)
        if i >= 0:
            return filename[i + 1:]
    return os.path.basename(filename)


def _label(code) -> str:
    lbl = _LABELS.get(code)
    if lbl is None:
        name = getattr(code, "co_qualname", None) or code.co_name
        lbl = "%s:%s" % (_short_path(code.co_filename), name)
        # ';' separates frames and ' ' separates stack from count in
        # the folded format; neither may leak out of a label
        lbl = lbl.replace(";", ":").replace(" ", "_")
        _LABELS[code] = lbl
    return lbl


def _fold_stack(frame, root: str) -> str:
    """One thread's stack as a collapsed-stack string, outermost
    first, rooted at the thread name."""
    codes = []
    f = frame
    while f is not None and len(codes) < MAX_STACK_DEPTH:
        codes.append(f.f_code)
        f = f.f_back
    key = (root, tuple(codes))
    folded = _FOLDS.get(key)
    if folded is None:
        if len(_FOLDS) >= _FOLDS_MAX:
            _FOLDS.clear()
        parts = [_label(c) for c in codes]
        parts.append(root.replace(";", ":").replace(" ", "_"))
        parts.reverse()
        folded = ";".join(parts)
        _FOLDS[key] = folded
    return folded


def _frame_list(frame, limit: int = MAX_STACK_DEPTH) -> list[tuple]:
    """Innermost-first ``(path, line, func)`` triples for a captured
    frame — what the stall journal entry and the lint cross-check
    consume."""
    out = []
    f = frame
    while f is not None and len(out) < limit:
        code = f.f_code
        out.append((_short_path(code.co_filename), f.f_lineno,
                    code.co_name))
        f = f.f_back
    return out


def render_folded(agg: dict) -> str:
    """Folded-stack text: ``stack count`` per line, hottest first."""
    lines = ["%s %d" % (stack, count)
             for stack, count in sorted(agg.items(),
                                        key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines) + ("\n" if lines else "")


def top_self_stack(agg: dict) -> tuple[str, int] | None:
    """The hottest collapsed stack (self time = sample count, since
    every sample attributes to exactly one leaf stack)."""
    if not agg:
        return None
    stack = max(agg, key=lambda s: (agg[s], s))
    return stack, agg[stack]


# ---- sampling profiler ----

class SamplingProfiler:
    """Wall-clock sampling of every thread but its own.

    The sampler thread folds stacks into a lock-protected pending dict;
    :meth:`drain_forever` (run on the event loop, so the
    ``obs.profile.sample`` seam is awaitable) moves pending counts into
    a bounded ring of ``(ts, counts, n_samples)`` buckets about once a
    second.  :meth:`folded` merges the buckets newer than a cutoff.
    """

    def __init__(self, hz: float = DEFAULT_HZ,
                 window: float = RING_WINDOW):
        self.hz = float(hz)
        self.window = float(window)
        self._lock = threading.Lock()
        self._pending: dict[str, int] = {}
        self._pending_n = 0
        self._buckets: deque = deque(
            maxlen=max(2, int(window / DRAIN_INTERVAL) + 1))
        self._thread: threading.Thread | None = None
        self._stop_evt = threading.Event()
        self._names: dict[int, str] = {}
        # tid -> (frame, f_lasti, folded): a thread parked at the
        # same bytecode position (an idle selector poll, a waiting
        # Event) has by definition the same stack — the caller chain
        # of a live activation is immutable — so the previous fold is
        # reused without walking a single frame.  The held frame
        # reference pins that activation for at most one sample
        # interval (it is replaced or pruned on the next pass).
        self._last: dict[int, tuple] = {}
        self.started_at: float | None = None
        reg = get_registry()
        self._c_samples = reg.counter(
            "profiler_samples_total",
            "sampling passes the wall-clock profiler has taken")
        self._c_self = reg.counter(
            "profiler_self_seconds_total",
            "CPU consumed by the profiler's own sampling thread — "
            "the measured overhead the bench budget is judged against")

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def start(self) -> None:
        if self.running or self.hz <= 0:
            return
        self.started_at = time.time()
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name="manatee-profiler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop_evt.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=2.0)

    def _run(self) -> None:
        interval = 1.0 / self.hz
        # metric updates are batched to ~1/s: a contended counter lock
        # at sampling rate would itself show up in the overhead budget
        flush_every = max(1, int(self.hz))
        passes, self_cpu = 0, 0.0
        while not self._stop_evt.wait(interval):
            t0 = time.thread_time()
            try:
                self.sample_once()
            except Exception:           # pragma: no cover - paranoia
                pass                    # sampling must never hurt HA
            self_cpu += max(0.0, time.thread_time() - t0)
            passes += 1
            if passes >= flush_every:
                self._c_samples.inc(passes)
                self._c_self.inc(self_cpu)
                passes, self_cpu = 0, 0.0
        if passes:
            self._c_samples.inc(passes)
            self._c_self.inc(self_cpu)

    def sample_once(self) -> None:
        me = threading.get_ident()
        frames = sys._current_frames()
        # thread names change ~never: refresh the tid->name map only
        # when a tid is missing (a new thread) instead of paying
        # threading.enumerate() every sample
        names = self._names
        if any(tid not in names for tid in frames):
            names = {t.ident: t.name for t in threading.enumerate()}
            self._names = names
        last = self._last
        folded = []
        for tid, frame in frames.items():
            if tid == me:
                continue
            prev = last.get(tid)
            if prev is not None and prev[0] is frame \
                    and prev[1] == frame.f_lasti:
                folded.append(prev[2])
                continue
            name = names.get(tid)
            if name is None:
                name = "thread-%d" % tid
            s = _fold_stack(frame, name)
            last[tid] = (frame, frame.f_lasti, s)
            folded.append(s)
        if len(last) > len(frames):
            # dead threads must not pin their final frame forever
            for tid in [t for t in last if t not in frames]:
                del last[tid]
        with self._lock:
            for s in folded:
                self._pending[s] = self._pending.get(s, 0) + 1
            self._pending_n += 1

    def drain_once(self) -> None:
        with self._lock:
            if not self._pending_n:
                return
            counts, n = self._pending, self._pending_n
            self._pending, self._pending_n = {}, 0
        self._buckets.append((time.time(), counts, n))

    async def drain_forever(self,
                            interval: float = DRAIN_INTERVAL) -> None:
        while True:
            try:
                await asyncio.sleep(interval)
                await faults.point("obs.profile.sample")
                self.drain_once()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # an injected error must not kill the drain (and must
                # not spin it either: the sleep above already paced us)
                log.debug("profile drain failed: %s", e)

    def folded(self, seconds: float = 30.0) -> tuple[dict, int]:
        """``(stack -> count, total samples)`` over the trailing
        *seconds*, undrained pending samples included."""
        cutoff = time.time() - float(seconds)
        with self._lock:
            buckets = list(self._buckets)
            agg = dict(self._pending)
            total = self._pending_n
        for ts, counts, n in buckets:
            if ts < cutoff:
                continue
            total += n
            for s, c in counts.items():
                agg[s] = agg.get(s, 0) + c
        return agg, total


# ---- event-loop health monitor ----

def _loop_is_idle(frames: list[tuple]) -> bool:
    """True when the loop thread's innermost frame is the selector
    poll — the loop is *waiting*, not blocked (seen when the tick
    coroutine itself is wedged, e.g. by an armed ``obs.loop.tick``
    stall: the loop stays healthy, so no stall may be reported)."""
    return bool(frames) and frames[0][0] in ("selectors.py",
                                             "selector_events.py")


class LoopMonitor:
    """Self-timing tick coroutine + blocked-loop watchdog thread.

    The tick coroutine measures how late every ``sleep(interval)``
    wakes (``event_loop_lag_seconds``) and stamps ``_last_tick``; the
    watchdog thread notices the stamp going stale past
    ``stall_threshold`` and — while the loop is still blocked —
    captures the loop thread's frame via ``sys._current_frames()``,
    bumps ``event_loop_stalls_total``, and journals ``obs.loop.stall``
    with the offending stack (once per stall episode).  Journal and
    metric writes are plain dict/deque operations, safe from a thread.
    """

    def __init__(self, tick_interval: float = DEFAULT_TICK,
                 stall_threshold: float = DEFAULT_STALL,
                 lint_check: bool = True):
        self.tick_interval = float(tick_interval)
        self.stall_threshold = float(stall_threshold)
        self.lint_check = lint_check
        self._task: asyncio.Task | None = None
        self._watchdog: threading.Thread | None = None
        self._stop_evt = threading.Event()
        self._factory_loop = None
        self._prev_factory = None
        self._factory = None
        self._loop_tid: int | None = None
        self._last_tick: float | None = None
        self._stall_open = False
        self._first_seen: weakref.WeakKeyDictionary = \
            weakref.WeakKeyDictionary()
        # recent captured stalls, newest last (tests and /tasks don't
        # need to trawl the journal for them)
        self.stalls: deque = deque(maxlen=64)
        reg = get_registry()
        self._h_lag = reg.histogram(
            "event_loop_lag_seconds",
            "how late the monitor's event-loop tick wakes up — "
            "scheduling lag every coroutine on this loop experiences")
        self._c_stalls = reg.counter(
            "event_loop_stalls_total",
            "times a callback blocked the event loop past the stall "
            "threshold (each journaled as obs.loop.stall)")

    @property
    def running(self) -> bool:
        return self._task is not None and not self._task.done()

    def start(self) -> None:
        if self.running:
            return
        self._stop_evt.clear()
        loop = asyncio.get_running_loop()
        # trace/span capture for the census (see _census_task_factory)
        self._factory_loop = loop
        self._prev_factory = loop.get_task_factory()
        self._factory = _census_task_factory(self._prev_factory)
        loop.set_task_factory(self._factory)
        self._task = loop.create_task(
            self._tick_loop(), name="obs-loop-tick")
        if self.stall_threshold > 0:
            self._watchdog = threading.Thread(
                target=self._watch, name="manatee-loop-watchdog",
                daemon=True)
            self._watchdog.start()

    async def stop(self) -> None:
        self._stop_evt.set()
        loop = self._factory_loop
        if loop is not None and not loop.is_closed() \
                and loop.get_task_factory() is self._factory:
            # restore only if still ours: never clobber a factory
            # someone installed on top of the census wrapper
            loop.set_task_factory(self._prev_factory)
        self._factory_loop = None
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass
        wd, self._watchdog = self._watchdog, None
        if wd is not None:
            wd.join(timeout=2.0)

    def first_seen(self, task) -> float | None:
        """Epoch time this task was first observed by a tick (None
        until the monitor has ticked over it) — the census's age."""
        return self._first_seen.get(task)

    async def _tick_loop(self) -> None:
        self._loop_tid = threading.get_ident()
        self._last_tick = time.monotonic()
        while True:
            try:
                await faults.point("obs.loop.tick")
                t0 = time.monotonic()
                await asyncio.sleep(self.tick_interval)
                lag = max(0.0,
                          time.monotonic() - t0 - self.tick_interval)
                self._h_lag.observe(lag)
                self._last_tick = time.monotonic()
                self._note_tasks()
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # an injected error must not kill (or spin) the tick
                log.debug("loop tick failed: %s", e)
                await asyncio.sleep(self.tick_interval)
                self._last_tick = time.monotonic()

    def _note_tasks(self) -> None:
        now = time.time()
        for t in asyncio.all_tasks():
            if t not in self._first_seen:
                self._first_seen[t] = now

    def _watch(self) -> None:
        interval = max(0.02, min(self.stall_threshold / 4.0, 0.25))
        while not self._stop_evt.wait(interval):
            last, tid = self._last_tick, self._loop_tid
            if last is None or tid is None:
                continue
            blocked = time.monotonic() - last - self.tick_interval
            if blocked <= self.stall_threshold:
                self._stall_open = False
                continue
            if self._stall_open:
                continue        # one journal entry per stall episode
            try:
                frame = sys._current_frames().get(tid)
            except Exception:   # pragma: no cover - paranoia
                continue
            if frame is None:
                continue
            frames = _frame_list(frame)
            if _loop_is_idle(frames):
                continue
            self._stall_open = True
            try:
                self._record_stall(blocked, frames)
            except Exception:   # pragma: no cover - paranoia
                pass            # the watchdog must never hurt HA

    def _record_stall(self, blocked: float,
                      frames: list[tuple]) -> None:
        file, line, func = frames[0]
        stack = ";".join("%s:%s" % (p, fn)
                         for p, _ln, fn in reversed(frames))
        ent = {"blocked_s": round(blocked, 3), "file": file,
               "line": line, "func": func, "stack": stack}
        self._c_stalls.inc()
        get_journal().record("obs.loop.stall", **ent)
        self.stalls.append(dict(ent))
        if self.lint_check:
            disc = find_lint_exemption(frames)
            if disc is not None:
                get_journal().record("obs.lint.discrepancy", **disc)


# ---- runtime <-> static cross-check (mnt-lint audit) ----

_AUDIT: dict = {"loaded": False, "audit": None}


def _get_audit():
    """Lazy singleton StaticBlockingAudit over the repo checkout, or
    None when the lint package is unavailable (stripped install)."""
    if not _AUDIT["loaded"]:
        _AUDIT["loaded"] = True
        try:
            from manatee_tpu.lint.summaries import StaticBlockingAudit
            _AUDIT["audit"] = StaticBlockingAudit(_REPO_ROOT)
        except Exception:           # pragma: no cover - partial tree
            _AUDIT["audit"] = None
    return _AUDIT["audit"]


def find_lint_exemption(frames: list[tuple]) -> dict | None:
    """The static side's account of a stall, per the two-sided
    contract (docs/lint.md): a discrepancy dict when mnt-lint's
    blocking rules were told to ignore the stalled frame
    (``via=path-disable`` / ``via=suppression``), or when the culprit
    is not derivable from the interprocedural may-block summaries at
    all (``via=not-derived``) — or None when the static analysis
    already predicted this stall.

    *frames* is innermost-first ``(path, line, func)`` with
    repo-relative paths.  Runs only on the rare stall path, so lazily
    building the summary database is fine — and an exemption verdict
    never needs it at all.
    """
    audit = _get_audit()
    if audit is None:
        return None
    try:
        return audit.verdict(frames)
    except Exception:               # pragma: no cover - paranoia
        return None


# ---- live task census ----

# task -> (trace id, span id) captured at creation.  Before 3.12
# (Task.get_context) a C task's snapshotted context is unreadable from
# outside, so the loop monitor wraps the loop's task factory and reads
# the ids in the CREATING context — by definition the values the new
# task snapshots.  Weak keys: the census must never keep a task alive.
_TASK_IDS: weakref.WeakKeyDictionary = weakref.WeakKeyDictionary()


def _census_task_factory(prev):
    def factory(loop, coro, **kw):
        task = (prev(loop, coro, **kw) if prev is not None
                else asyncio.Task(coro, loop=loop, **kw))
        try:
            ids = (_trace_mod._current.get(),
                   _spans_mod._current_span.get())
            if ids != (None, None):
                _TASK_IDS[task] = ids
        except Exception:       # pragma: no cover - paranoia
            pass                # the census must never hurt HA
        return task
    return factory


def _task_where(task) -> str | None:
    """``path:func:line`` of the innermost frame of the task's
    coroutine chain (follow ``cr_await`` down to where it is actually
    suspended)."""
    try:
        obj = task.get_coro()
    except Exception:
        return None
    frame = None
    for _ in range(MAX_STACK_DEPTH):
        if obj is None:
            break
        f = getattr(obj, "cr_frame", None) \
            or getattr(obj, "gi_frame", None)
        if f is not None:
            frame = f
        obj = getattr(obj, "cr_await", None) \
            or getattr(obj, "gi_yieldfrom", None)
    if frame is None:
        return None
    code = frame.f_code
    return "%s:%s:%d" % (_short_path(code.co_filename), code.co_name,
                         frame.f_lineno)


def _task_context_ids(task) -> tuple:
    """(trace id, span id) bound in the task's snapshotted context —
    ``Task.get_context`` where available (3.12+), the private
    ``_context`` on pure-Python tasks, else the loop monitor's
    creation-time capture (``_census_task_factory``).
    ``contextvars.Context`` is a mapping, so no path enters the
    context."""
    get_ctx = getattr(task, "get_context", None)
    try:
        ctx = (get_ctx() if callable(get_ctx)
               else getattr(task, "_context", None))
        if ctx is not None:
            return (ctx.get(_trace_mod._current, None),
                    ctx.get(_spans_mod._current_span, None))
    except Exception:
        pass
    # a C task before 3.12: fall back to the creation-time capture
    return _TASK_IDS.get(task, (None, None))


def tasks_payload() -> dict:
    """Every live asyncio task on the running loop: name, age (since
    the loop monitor first saw it), innermost frame, bound trace/span.
    Must be called from the loop (the HTTP handlers are)."""
    now = round(time.time(), 3)
    mon = get_loop_monitor()
    try:
        live = asyncio.all_tasks()
    except RuntimeError:
        live = set()
    items = []
    for t in live:
        trace_id, span_id = _task_context_ids(t)
        first = mon.first_seen(t) if mon is not None else None
        items.append({
            "name": t.get_name(),
            "age_s": (round(now - first, 3)
                      if first is not None else None),
            "where": _task_where(t),
            "trace": trace_id,
            "span": span_id,
        })
    items.sort(key=lambda i: (-(i["age_s"] or 0.0), i["name"]))
    return {"peer": get_journal().peer, "now": now,
            "count": len(items), "tasks": items}


# ---- pure HTTP endpoint helpers (one contract on every listener) ----

def profile_http_reply(profiler, query) -> tuple:
    """``GET /profile?seconds=N`` -> (body, status): folded-stack text
    (str body) on 200, an error object (dict body) on 400/503."""
    if profiler is None or not profiler.running:
        return {"error": "profiler not running"}, 503
    raw = query.get("seconds", "30")
    try:
        seconds = float(raw)
        if not seconds > 0:
            raise ValueError(raw)
    except (TypeError, ValueError):
        return {"error": "seconds must be a positive number"}, 400
    agg, _total = profiler.folded(seconds)
    return render_folded(agg), 200


def tasks_http_reply(query) -> tuple:
    """``GET /tasks?name=SUBSTR`` -> (body, status)."""
    body = tasks_payload()
    substr = query.get("name")
    if substr:
        body["tasks"] = [t for t in body["tasks"]
                         if substr in (t["name"] or "")]
        body["count"] = len(body["tasks"])
    return body, 200


# ---- daemon wiring ----

_PROFILER: SamplingProfiler | None = None
_MONITOR: LoopMonitor | None = None


def get_profiler() -> SamplingProfiler | None:
    return _PROFILER


def get_loop_monitor() -> LoopMonitor | None:
    return _MONITOR


class Introspection:
    """Handle returned by :func:`start_introspection`; ``await
    stop()`` unwinds everything it started."""

    def __init__(self, profiler, monitor, drain_task):
        self.profiler = profiler
        self.monitor = monitor
        self._drain = drain_task

    async def stop(self) -> None:
        global _PROFILER, _MONITOR
        if self._drain is not None:
            self._drain.cancel()
            try:
                await self._drain
            except asyncio.CancelledError:
                pass
            self._drain = None
        if self.monitor is not None:
            await self.monitor.stop()
        if self.profiler is not None:
            self.profiler.stop()
        if _PROFILER is self.profiler:
            _PROFILER = None
        if _MONITOR is self.monitor:
            _MONITOR = None


def start_introspection(cfg: dict | None = None) -> Introspection:
    """Wire the always-on introspection plane for this process (called
    from every daemon's startup, inside the running loop).  Config
    keys, all optional: ``profileHz`` (0 disables the sampler),
    ``loopTickInterval``, ``loopStallThreshold`` (0 disables the
    blocked-loop watchdog; the lag histogram stays on)."""
    global _PROFILER, _MONITOR
    cfg = cfg or {}
    hz = float(cfg.get("profileHz", DEFAULT_HZ))
    loop = asyncio.get_running_loop()
    profiler = None
    drain = None
    if hz > 0:
        profiler = SamplingProfiler(hz=hz)
        profiler.start()
        drain = loop.create_task(profiler.drain_forever(),
                                 name="obs-profile-drain")
    monitor = LoopMonitor(
        tick_interval=float(cfg.get("loopTickInterval", DEFAULT_TICK)),
        stall_threshold=float(cfg.get("loopStallThreshold",
                                      DEFAULT_STALL)))
    monitor.start()
    _PROFILER, _MONITOR = profiler, monitor
    return Introspection(profiler, monitor, drain)
