"""Structured spans: per-stage timing attribution across peers.

PR 1's flat trace ids answer *which* log lines belong to a failover;
spans answer the operator's real question — *where did the time go*.
A span is one timed stage (name, trace id, span id, parent id, peer,
wall-clock start, monotonic duration, free-form attrs, status) and the
parent links compose into a tree that crosses process and peer
boundaries:

- in-process, the current span id lives in a :mod:`contextvars` var, so
  a span opened inside another nests under it without plumbing — and
  ``asyncio.create_task`` snapshots the context, so background work
  (a pg reconfigure task, the catchup watcher) parents correctly;
- across the coord wire, RPC frames carry ``span`` next to ``trace``
  and coordd binds it while dispatching, so the server-side handling
  nests under the client's span;
- across peers, the written cluster-state object carries the
  transition span's id (``span`` key, next to ``trace``): every peer
  reacting to the watch binds it as the foreign parent, so the
  reconfigure/restore spans a takeover causes on *other* peers hang
  off the initiator's transition span — that is what makes
  ``manatee-adm trace`` a single rooted cross-peer tree.

Completed spans land in a per-process ring (:class:`SpanStore`,
``GET /spans``); spans still running are tracked separately so a leak
is observable (``open`` in the endpoint payload, and the chaos suite
asserts a finished failover leaves none behind).

The analysis half of this module (:func:`assemble_tree`,
:func:`critical_path`, :func:`render_waterfall`) is pure functions over
fetched span records, shared by ``manatee-adm trace`` and the tests:
the critical path walks backward from the root's end, descending into
the child whose completion bounds each moment, and partitions the
root's wall-clock window into per-stage self-time segments — the
chain that actually bounds failover time, with percentages.

Everything here is stdlib-only and allocation-light: observability must
never be able to hurt HA.
"""

from __future__ import annotations

import asyncio
import contextlib
import contextvars
import functools
import time
import uuid
from collections import deque

from manatee_tpu.obs.causal import hlc_now
from manatee_tpu.obs.journal import _iso_ms
from manatee_tpu.obs.trace import bind_trace, current_trace

DEFAULT_CAPACITY = 4096

# span record keys detail attrs may not shadow
_RESERVED = frozenset(("seq", "span", "parent", "trace", "name", "peer",
                       "ts", "time", "hlc", "dur", "status"))

_current_span: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "manatee_span_id", default=None)


def new_span_id() -> str:
    """16 hex chars, same shape as trace ids."""
    return uuid.uuid4().hex[:16]


def current_span_id() -> str | None:
    return _current_span.get()


@contextlib.contextmanager
def bind_parent(span_id: str | None):
    """Adopt *span_id* — typically a FOREIGN id read off an RPC frame or
    the cluster-state object — as the current parent for the block, so
    locally-opened spans nest under work that started on another peer.
    None = leave the current binding untouched (optional passthrough,
    like :func:`bind_trace`)."""
    if span_id is None:
        yield _current_span.get()
        return
    token = _current_span.set(span_id)
    try:
        yield span_id
    finally:
        _current_span.reset(token)


class Span:
    """One in-flight span.  Created by :meth:`SpanStore.start`; call
    :meth:`end` exactly once (the :func:`span` context manager does
    both, and is the API everything but callback-split lifecycles —
    the failover clock — should use)."""

    __slots__ = ("name", "trace", "span_id", "parent_id", "ts", "_t0",
                 "attrs", "_store", "_done")

    def __init__(self, store: "SpanStore", name: str, *,
                 trace_id: str | None, parent_id: str | None,
                 attrs: dict):
        self._store = store
        self.name = name
        self.trace = trace_id
        self.span_id = new_span_id()
        self.parent_id = parent_id
        self.ts = round(time.time(), 3)
        self._t0 = time.monotonic()
        self.attrs = attrs
        self._done = False

    def end(self, status: str = "ok", **attrs) -> dict | None:
        """Finish the span (idempotent) and commit it to the store."""
        return self._store.finish(self, status=status, **attrs)


class SpanStore:
    """Fixed-size ring of COMPLETED spans plus an open-span registry
    (observability must never grow without bound inside an HA daemon;
    an unfinished span is a bug the registry makes visible)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._buf: deque[dict] = deque(maxlen=capacity)
        self._seq = 0
        self.peer: str | None = None
        self._open: dict[str, Span] = {}

    def start(self, name: str, *, trace_id: str | None = None,
              parent_id: str | None = None, root: bool = False,
              **attrs) -> Span:
        """Open a span.  *trace_id* defaults to the bound trace,
        *parent_id* to the bound (possibly foreign) span; *root* forces
        parent None — the top of a new tree (the failover clock)."""
        if parent_id is None and not root:
            parent_id = _current_span.get()
        sp = Span(self, name,
                  trace_id=(trace_id if trace_id is not None
                            else current_trace()),
                  parent_id=None if root else parent_id,
                  attrs=attrs)
        self._open[sp.span_id] = sp
        return sp

    def finish(self, sp: Span, *, status: str = "ok",
               **attrs) -> dict | None:
        if sp._done:
            return None
        sp._done = True
        self._open.pop(sp.span_id, None)
        dur = time.monotonic() - sp._t0
        merged = dict(sp.attrs)
        merged.update(attrs)
        return self._commit(sp.name, trace=sp.trace, span_id=sp.span_id,
                            parent_id=sp.parent_id, ts=sp.ts, dur=dur,
                            status=status, attrs=merged)

    def record(self, name: str, *, ts: float, dur: float,
               status: str = "ok", trace_id: str | None = None,
               parent_id: str | None = None, **attrs) -> dict:
        """Commit an already-measured span post-hoc (no open-span
        bookkeeping).  The hot probe loop uses this so a span is only
        materialized for the ticks worth keeping (failures and verdict
        flips), not every healthy heartbeat."""
        return self._commit(
            name,
            trace=trace_id if trace_id is not None else current_trace(),
            span_id=new_span_id(),
            parent_id=(parent_id if parent_id is not None
                       else _current_span.get()),
            ts=round(ts, 3), dur=dur, status=status, attrs=attrs)

    def _commit(self, name: str, *, trace, span_id, parent_id, ts, dur,
                status, attrs) -> dict:
        self._seq += 1
        rec = {
            "seq": self._seq,
            "span": span_id,
            "parent": parent_id,
            "trace": trace,
            "name": name,
            "peer": self.peer,
            "ts": ts,
            "time": _iso_ms(ts),
            # stamped at COMMIT (span end): a span's completion is the
            # causal moment its record announces
            "hlc": hlc_now(),
            "dur": round(dur, 6),
            "status": status,
        }
        for k, v in attrs.items():
            if k not in _RESERVED:
                rec[k] = v
        self._buf.append(rec)
        return rec

    def spans(self, *, since: int = 0, limit: int | None = None,
              trace: str | None = None) -> list[dict]:
        """Completed spans with seq > *since*, oldest first, newest
        *limit* — the same pagination contract as the event journal."""
        out = [s for s in self._buf if s["seq"] > since
               and (trace is None or s["trace"] == trace)]
        if limit is not None and limit >= 0:
            # NOT out[-limit:]: -0 slices the whole list, so limit=0
            # would return everything instead of nothing
            out = out[-limit:] if limit else []
        return out

    def open_spans(self) -> list[dict]:
        """The spans currently in flight (leak visibility; served in
        the ``GET /spans`` payload and asserted empty-for-a-trace by
        the chaos suite)."""
        return [{"span": sp.span_id, "name": sp.name, "trace": sp.trace,
                 "parent": sp.parent_id, "ts": sp.ts}
                for sp in self._open.values()]

    def __len__(self) -> int:
        return len(self._buf)


_STORE = SpanStore()


def get_span_store() -> SpanStore:
    """The process-wide span store every component records into."""
    return _STORE


def set_span_peer(peer_id: str) -> None:
    _STORE.peer = peer_id


@contextlib.contextmanager
def span(name: str, *, trace_id: str | None = None, root: bool = False,
         **attrs):
    """THE span API: times the block, nests under the current (possibly
    foreign) span, and binds itself as the parent for anything opened —
    or spawned via ``create_task`` — inside.  *trace_id* additionally
    binds the trace for the block (None = inherit).  Status is derived
    from how the block exits: ok / cancelled / error."""
    store = _STORE
    with bind_trace(trace_id):
        sp = store.start(name, root=root, **attrs)
        token = _current_span.set(sp.span_id)
        try:
            yield sp
        except asyncio.CancelledError:
            sp.end(status="cancelled")
            raise
        except BaseException as e:
            sp.end(status="error", error=type(e).__name__)
            raise
        finally:
            _current_span.reset(token)
            sp.end()        # idempotent: no-op on the error paths above


def record_span(name: str, *, ts: float, dur: float, status: str = "ok",
                **attrs) -> dict:
    """Module-level convenience for :meth:`SpanStore.record`."""
    return _STORE.record(name, ts=ts, dur=dur, status=status, **attrs)


def traced(name: str | None = None, **attrs):
    """Decorator form of :func:`span` for whole functions (sync or
    async)."""
    def deco(fn):
        label = name or fn.__qualname__
        if _is_coroutine_fn(fn):
            @functools.wraps(fn)
            async def aw(*a, **kw):
                with span(label, **attrs):
                    return await fn(*a, **kw)
            return aw

        @functools.wraps(fn)
        def w(*a, **kw):
            with span(label, **attrs):
                return fn(*a, **kw)
        return w
    return deco


def _is_coroutine_fn(fn) -> bool:
    import inspect
    return inspect.iscoroutinefunction(fn)


def parse_page_query(query) -> tuple[int, int | None]:
    """The shared ``?since=SEQ&limit=N`` parse for every /events and
    /spans endpoint (*query* is any mapping, e.g. an aiohttp request's
    ``.query``).  Raises ValueError on non-integer values — each server
    maps that to its 400 reply.  One definition so the endpoints'
    pagination contract cannot drift across the three servers that
    expose it."""
    since = int(query.get("since", 0))
    limit = int(query["limit"]) if "limit" in query else None
    return since, limit


def spans_payload(store: SpanStore, *, since: int = 0,
                  limit: int | None = None,
                  trace: str | None = None) -> dict:
    """The ``GET /spans`` body — shared by the status server, the
    backup REST server, and coordd so the endpoints cannot drift."""
    return {
        "peer": store.peer,
        "now": round(time.time(), 3),
        "hlc": hlc_now(),
        "open": store.open_spans(),
        "spans": store.spans(since=since, limit=limit, trace=trace),
    }


def spans_http_reply(store: SpanStore, query) -> tuple[dict, int]:
    """The WHOLE ``GET /spans`` endpoint minus the web framework:
    (json body, HTTP status) for a request's query mapping.  The three
    servers that expose the endpoint (status, backup REST, coordd
    metrics) each wrap this in one json_response call, so the contract
    lives in exactly one place."""
    try:
        since, limit = parse_page_query(query)
    except ValueError:
        return {"error": "since/limit must be integers"}, 400
    return spans_payload(store, since=since, limit=limit,
                         trace=query.get("trace")), 200


# ---------------------------------------------------------------------------
# analysis: tree assembly, critical path, waterfall — pure functions over
# fetched span records, shared by `manatee-adm trace` and the tests
# ---------------------------------------------------------------------------

_EPS = 1e-9


def _end(rec: dict) -> float:
    return rec["ts"] + float(rec.get("dur") or 0.0)


def assemble_tree(spans: list[dict]
                  ) -> tuple[list[dict], dict[str, list[dict]],
                             list[dict]]:
    """(roots, children-by-span-id, orphans) for a fan-out's merged
    span records.  Duplicates (a peer fetched twice) are dropped by
    span id.  An *orphan* — parent id set but not present in the fetch
    (e.g. a span recorded by a process whose ring died with it) — is
    surfaced separately AND treated as a root so the waterfall still
    renders everything."""
    by_id: dict[str, dict] = {}
    for s in spans:
        sid = s.get("span")
        if sid and sid not in by_id:
            by_id[sid] = s
    children: dict[str, list[dict]] = {}
    roots: list[dict] = []
    orphans: list[dict] = []
    for s in by_id.values():
        parent = s.get("parent")
        if parent is None:
            roots.append(s)
        elif parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            orphans.append(s)
            roots.append(s)
    for kids in children.values():
        kids.sort(key=lambda k: (k["ts"], str(k.get("peer")),
                                 k.get("seq") or 0))
    roots.sort(key=lambda k: (k["ts"], str(k.get("peer"))))
    return roots, children, orphans


def critical_path(root: dict, children: dict[str, list[dict]]) -> dict:
    """The chain of spans that bounds the root's wall-clock window.

    Walks backward from the window's end: at every moment, descend into
    the child whose SUBTREE completes latest (that completion is what
    the parent was waiting on — a grandchild that outlives its parent,
    like the catchup watcher outliving the reconfigure that spawned it,
    still bounds the takeover), attribute the uncovered remainder to
    the parent itself, and recurse.  The resulting segments PARTITION
    the root's window, so the per-stage self times sum exactly to the
    total — percentages are honest.

    Returns ``{"total_s", "root_dur_s", "stages": [{"name", "peer",
    "span", "start_s", "self_s", "pct"}, ...]}`` with stages in
    chronological order of first contribution."""
    eff: dict[str, float] = {}

    def eff_end(rec: dict) -> float:
        """Latest completion in *rec*'s subtree."""
        sid = rec["span"]
        if sid not in eff:
            eff[sid] = _end(rec)           # pre-seed: cycle-proof
            eff[sid] = max([_end(rec)]
                           + [eff_end(c)
                              for c in children.get(sid, ())
                              if c.get("dur") is not None])
        return eff[sid]

    segs: list[tuple[dict, float, float]] = []

    def walk(rec: dict, t: float) -> None:
        start = rec["ts"]
        while t > start + _EPS:
            kids = [c for c in children.get(rec["span"], ())
                    if c.get("dur") is not None and c["ts"] < t - _EPS]
            if not kids:
                break
            # what bounded the frontier is a COMPLETION: prefer the
            # child whose subtree finished latest within the window.  A
            # child still running at t (a restore outliving the root)
            # completed nothing by then — it only explains waiting when
            # no child finishes in the remaining window at all.
            done = [c for c in kids if eff_end(c) <= t + _EPS]
            c = max(done, key=eff_end) if done \
                else max(kids, key=lambda k: min(eff_end(k), t))
            ce = min(eff_end(c), t)
            if ce <= c["ts"] + _EPS or ce <= start + _EPS:
                break
            if t - ce > _EPS:
                segs.append((rec, ce, t))     # waiting after the child
            walk(c, ce)
            t = max(c["ts"], start)
        if t > start + _EPS:
            segs.append((rec, start, t))

    # the walk is CLAMPED to the root's own end: a descendant that
    # outlives the root (an async peer still restoring after the
    # failover completed) is that peer's catch-up work, not part of
    # the window being explained — without the clamp it would inflate
    # the total past the SLI sample and evict the real bounding stage.
    # Below the root, eff ends still apply (ce may exceed a CHILD's own
    # end so the walk can descend into the grandchild that bounded it).
    walk(root, _end(root))
    agg: dict[str, dict] = {}
    for rec, s, e in segs:
        st = agg.setdefault(rec["span"], {
            "name": rec["name"], "peer": rec.get("peer"),
            "span": rec["span"], "start_s": s, "self_s": 0.0})
        st["self_s"] += e - s
        st["start_s"] = min(st["start_s"], s)
    total = sum(st["self_s"] for st in agg.values())
    stages = sorted(agg.values(), key=lambda st: st["start_s"])
    t0 = root["ts"]
    for st in stages:
        st["start_s"] = round(st["start_s"] - t0, 6)
        st["self_s"] = round(st["self_s"], 6)
        st["pct"] = round(100.0 * st["self_s"] / total, 1) if total \
            else 0.0
    return {"total_s": round(total, 6),
            "root_dur_s": round(float(root.get("dur") or 0.0), 6),
            "stages": stages}


def render_waterfall(roots: list[dict], children: dict[str, list[dict]],
                     *, width: int = 32) -> list[str]:
    """ASCII waterfall of the whole forest: one line per span, indented
    by depth, with start offset, duration, and a proportional bar over
    the forest's wall-clock window."""
    flat: list[tuple[int, dict]] = []

    def walk(rec: dict, depth: int) -> None:
        flat.append((depth, rec))
        for c in children.get(rec["span"], ()):
            walk(c, depth + 1)

    for r in roots:
        walk(r, 0)
    if not flat:
        return ["(no spans)"]
    t0 = min(rec["ts"] for _d, rec in flat)
    t1 = max(_end(rec) for _d, rec in flat)
    window = max(t1 - t0, _EPS)
    scale = width / window
    lines = ["%-38s %-22s %9s %9s  %s"
             % ("SPAN", "PEER", "START", "DUR",
                "0s%*s" % (width - 2, "+%.3fs" % window))]
    for depth, rec in flat:
        label = ("  " * depth + rec["name"])[:38]
        off = int((rec["ts"] - t0) * scale)
        bar_w = max(1, int(round(float(rec.get("dur") or 0.0) * scale)))
        bar_w = min(bar_w, width - min(off, width - 1))
        bar = " " * min(off, width - 1) + "=" * bar_w
        status = rec.get("status", "ok")
        lines.append("%-38s %-22s %+8.3fs %8.3fs  |%-*s|%s"
                     % (label, str(rec.get("peer") or "-")[:22],
                        rec["ts"] - t0, float(rec.get("dur") or 0.0),
                        width, bar,
                        "" if status == "ok" else " " + status))
    return lines
