"""Trace ids: correlate one topology transition across peers and layers.

A trace id is minted where a transition originates (the state machine's
durable write, an operator action in ``manatee-adm``) and then:

- bound to the current task via a :mod:`contextvars` context var, so
  everything the transition causes in-process (pg reconfigure, restore,
  journal events) inherits it without plumbing;
- attached to every coord RPC frame the client sends (``trace`` field),
  so coordd's logs carry it;
- embedded in the written cluster state (``trace`` key), so *other*
  peers reacting to the watch fire bind the same id — that is what
  makes the shard-wide ``manatee-adm events`` timeline line up;
- stamped on every bunyan log record by :class:`TraceLogFilter`
  (installed by ``logutil.setup_logging``).
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import uuid

_current: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "manatee_trace_id", default=None)


def new_trace_id() -> str:
    """16 hex chars — short enough to read in a log line, unique enough
    for a shard's lifetime of transitions."""
    return uuid.uuid4().hex[:16]


def current_trace() -> str | None:
    return _current.get()


def ensure_trace() -> str:
    """The bound trace id, or a freshly minted one (NOT bound)."""
    return _current.get() or new_trace_id()


@contextlib.contextmanager
def bind_trace(trace_id: str | None):
    """Bind *trace_id* for the duration of the block (None = leave the
    current binding untouched, so callers can pass through an optional
    id without branching)."""
    if trace_id is None:
        yield _current.get()
        return
    token = _current.set(trace_id)
    try:
        yield trace_id
    finally:
        _current.reset(token)


class TraceLogFilter(logging.Filter):
    """Stamps the bound trace id onto every record that does not already
    carry one — the bunyan formatter's generic extra passthrough then
    emits it as ``trace_id``."""

    def filter(self, record: logging.LogRecord) -> bool:
        tid = _current.get()
        if tid is not None and not hasattr(record, "trace_id"):
            record.trace_id = tid
        return True
