"""Process-wide metrics registry.

Components get-or-create named instruments at import/wiring time and
update them on hot paths with plain dict writes — no locks, no I/O, no
allocation beyond the first touch of a label set (asyncio runs them on
one thread).  The status server renders the whole registry through the
shared Prometheus text builder on scrape.

Naming is enforced at registration, not left to review: counters must
end in ``_total`` and histograms observing durations must be base-unit
``_seconds`` (the Prometheus conventions the satellite audit fixed in
``utils/prom.py``).
"""

from __future__ import annotations

import contextlib
import time

# Latency buckets for control-plane operations: sub-ms RPCs up through
# multi-minute restores.  One fixed scale everywhere so histograms from
# different peers are mergeable.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0,
)

_INF = float("inf")


def _labels_key(label_names: tuple[str, ...], labels: dict) -> tuple:
    if set(labels) != set(label_names):
        raise ValueError("expected labels %r, got %r"
                         % (label_names, sorted(labels)))
    return tuple(str(labels[n]) for n in label_names)


class _Instrument:
    kind = "untyped"

    def __init__(self, name: str, help_: str,
                 label_names: tuple[str, ...] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)


class Counter(_Instrument):
    kind = "counter"

    def __init__(self, name: str, help_: str,
                 label_names: tuple[str, ...] = ()):
        if not name.endswith("_total"):
            raise ValueError("counter %r must end in _total" % name)
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _labels_key(self.label_names, labels)
        self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(_labels_key(self.label_names, labels), 0.0)

    def samples(self) -> list[tuple[dict, float]]:
        if not self.label_names and not self._values:
            return [({}, 0.0)]   # an untouched plain counter still exports
        return [(dict(zip(self.label_names, k)), v)
                for k, v in sorted(self._values.items())]


class Gauge(_Instrument):
    kind = "gauge"

    def __init__(self, name: str, help_: str,
                 label_names: tuple[str, ...] = ()):
        super().__init__(name, help_, label_names)
        self._values: dict[tuple, float] = {}

    def set(self, value: float, **labels) -> None:
        self._values[_labels_key(self.label_names, labels)] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = _labels_key(self.label_names, labels)
        self._values[key] = self._values.get(key, 0.0) + amount

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        return self._values.get(_labels_key(self.label_names, labels), 0.0)

    def samples(self) -> list[tuple[dict, float]]:
        return [(dict(zip(self.label_names, k)), v)
                for k, v in sorted(self._values.items())]


class Histogram(_Instrument):
    """Cumulative fixed-bucket histogram; durations observed in seconds
    measured on the monotonic clock (use :meth:`time`)."""

    kind = "histogram"

    def __init__(self, name: str, help_: str,
                 label_names: tuple[str, ...] = (),
                 buckets: tuple[float, ...] = DEFAULT_BUCKETS):
        if "duration" in name and not name.endswith("_seconds"):
            raise ValueError(
                "duration histogram %r must end in _seconds" % name)
        super().__init__(name, help_, label_names)
        self.buckets = tuple(sorted(buckets))
        if not self.buckets:
            raise ValueError("histogram needs at least one bucket")
        self._series: dict[tuple, dict] = {}

    def _series_for(self, labels: dict) -> dict:
        key = _labels_key(self.label_names, labels)
        s = self._series.get(key)
        if s is None:
            s = {"counts": [0] * len(self.buckets), "sum": 0.0,
                 "count": 0}
            self._series[key] = s
        return s

    def observe(self, value: float, **labels) -> None:
        s = self._series_for(labels)
        s["sum"] += value
        s["count"] += 1
        for i, ub in enumerate(self.buckets):
            if value <= ub:
                s["counts"][i] += 1

    @contextlib.contextmanager
    def time(self, **labels):
        t0 = time.monotonic()
        try:
            yield
        finally:
            self.observe(time.monotonic() - t0, **labels)

    def snapshot(self, **labels) -> dict:
        """{'count', 'sum', 'counts'} for one label set (zeros if never
        observed) — for tests and acceptance probes."""
        key = _labels_key(self.label_names, labels)
        s = self._series.get(key)
        if s is None:
            return {"counts": [0] * len(self.buckets), "sum": 0.0,
                    "count": 0}
        return {"counts": list(s["counts"]), "sum": s["sum"],
                "count": s["count"]}

    def series(self) -> list[tuple[dict, dict]]:
        return [(dict(zip(self.label_names, k)), s)
                for k, s in sorted(self._series.items())]


class Registry:
    """Get-or-create instrument registry.  Re-registering the same name
    with the same kind returns the existing instrument (components wire
    independently and must converge on one series); a kind clash is a
    programming error and raises."""

    def __init__(self):
        self._instruments: dict[str, _Instrument] = {}

    def _get_or_create(self, cls, name: str, help_: str,
                       label_names: tuple[str, ...], **kw):
        inst = self._instruments.get(name)
        if inst is not None:
            if not isinstance(inst, cls):
                raise ValueError("metric %r already registered as %s"
                                 % (name, inst.kind))
            return inst
        inst = cls(name, help_, label_names, **kw)
        self._instruments[name] = inst
        return inst

    def counter(self, name: str, help_: str,
                label_names: tuple[str, ...] = ()) -> Counter:
        return self._get_or_create(Counter, name, help_, label_names)

    def gauge(self, name: str, help_: str,
              label_names: tuple[str, ...] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help_, label_names)

    def histogram(self, name: str, help_: str,
                  label_names: tuple[str, ...] = (),
                  buckets: tuple[float, ...] = DEFAULT_BUCKETS
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help_, label_names,
                                   buckets=buckets)

    def instruments(self) -> list[_Instrument]:
        return [self._instruments[k]
                for k in sorted(self._instruments)]

    def render_into(self, builder) -> None:
        """Append every instrument to a ``utils.prom.MetricsBuilder``."""
        from manatee_tpu.utils.prom import label_str

        for inst in self.instruments():
            if inst.kind in ("counter", "gauge"):
                samples = [(label_str(**labels), _fmt(v))
                           for labels, v in inst.samples()]
                builder.metric(inst.name, inst.kind, inst.help, samples)
            else:
                series = [(labels, s) for labels, s in inst.series()]
                builder.histogram(inst.name, inst.help, inst.buckets,
                                  series)


def _fmt(v: float) -> str:
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


_REGISTRY = Registry()


def get_registry() -> Registry:
    """The process-wide registry every component registers into."""
    return _REGISTRY
