"""Hybrid logical clocks: recoverable happens-before across the fleet.

The journals, spans, alerts, and history snapshots are merged across
peers on wall-clock timestamps, and wall clocks skew: under a few
seconds of drift a takeover's *effect* on one peer can sort before its
*cause* on another, and every downstream consumer — ``manatee-adm
events``, the doctor's journal cross-checks, the incident analyzer —
inherits the lie.  This module gives every process one hybrid logical
clock (Kulkarni et al.: a physical component in milliseconds plus a
logical counter) with the two HLC operations:

- :func:`hlc_now` advances the clock for a local event / outbound
  message and returns the encoded stamp;
- :func:`merge_remote` folds a received stamp in, so the local clock
  never falls behind anything it has *seen*.

Causality then rides the exact boundaries the trace id already
crosses, at the same near-zero marginal cost (one small string per
frame): coord RPC frames client<->coordd (both directions), the
written cluster-state object, ``POST /backup`` and its reply, and the
obs-route payloads the prober and the adm fan-out already fetch.  With
every boundary covered, ``e happened-before f`` implies
``stamp(e) < stamp(f)`` regardless of skew, so the merged fleet
timeline can sort by stamp and place every effect after its cause —
:func:`hlc_sort_key` is that order, with a wall-clock fallback for
records from old peers that predate HLC stamping.

Degradation contract: a stamp is advisory metadata.  The
``coord.hlc.merge`` failpoint sits on the merge seam and an injected
error (or a garbage stamp from a hostile peer) degrades that merge to
wall-clock ordering — it must never wedge or fail the RPC path
carrying it.

Skew visibility: :func:`observe_peer_clock` turns any fetched
``now``-bearing obs payload into a measured per-peer offset, exported
as ``clock_skew_seconds{peer}`` (the prober measures its shard's peers
every lag-scrape pass).  :data:`MERGE_SKEW_BOUND_S` is the
journal-merge safety bound: old-peer records fall back to wall-clock
ordering, so once measured skew exceeds the bound the doctor warns
that pre-HLC merges may misorder.

Encoding: ``"%013x.%05x" % (physical_ms, logical)`` — fixed-width hex,
so the string ordering equals the numeric ordering and the stamp costs
19 bytes on the wire.
"""

from __future__ import annotations

import asyncio
import time

from manatee_tpu.obs.metrics import get_registry

_REG = get_registry()
_SKEW = _REG.gauge(
    "clock_skew_seconds",
    "measured peer wall-clock offset (remote minus local, RTT-"
    "compensated)", ("peer",))
_MERGES = _REG.counter(
    "hlc_merge_total",
    "inbound HLC stamp merges", ("outcome",))

# The journal-merge safety bound (seconds): records from pre-HLC peers
# merge on wall clocks alone, so measured skew beyond this can misorder
# cause and effect for THOSE records (HLC-stamped records stay correct
# at any skew).  The doctor warns past it (`skew-exceeds-merge-bound`).
MERGE_SKEW_BOUND_S = 0.5

# fixed widths: 13 hex ms digits reach the year 4147, 5 hex logical
# digits allow 131k same-millisecond events before the width (not the
# ordering — sort keys decode) would grow
_ENC = "%013x.%05x"


def encode(pt_ms: int, logical: int) -> str:
    return _ENC % (pt_ms, logical)


def decode(stamp) -> tuple[int, int] | None:
    """(physical_ms, logical) from an encoded stamp, or None for
    anything malformed — old peers send nothing, hostile peers could
    send garbage, and both must degrade to wall-clock ordering rather
    than raise mid-merge."""
    if not isinstance(stamp, str):
        return None
    head, sep, tail = stamp.partition(".")
    if not sep:
        return None
    try:
        return int(head, 16), int(tail, 16)
    except ValueError:
        return None


class HybridClock:
    """One process's HLC state.  Everything is event-loop-thread
    confined, like the obs registries."""

    __slots__ = ("pt", "c")

    def __init__(self):
        self.pt = 0
        self.c = 0

    def _wall_ms(self) -> int:
        return int(time.time() * 1000)

    def now(self) -> str:
        """Advance for a local/send event and return the stamp."""
        wall = self._wall_ms()
        if wall > self.pt:
            self.pt, self.c = wall, 0
        else:
            self.c += 1
        return encode(self.pt, self.c)

    def observe(self, remote_pt: int, remote_c: int) -> str:
        """Fold a received stamp in (the HLC receive rule) and return
        the advanced local stamp."""
        wall = self._wall_ms()
        if wall > self.pt and wall > remote_pt:
            self.pt, self.c = wall, 0
        elif remote_pt > self.pt:
            self.pt, self.c = remote_pt, remote_c + 1
        elif self.pt > remote_pt:
            self.c += 1
        else:
            self.c = max(self.c, remote_c) + 1
        return encode(self.pt, self.c)


_CLOCK = HybridClock()


def get_clock() -> HybridClock:
    """The process-wide hybrid clock every stamp comes from."""
    return _CLOCK


def hlc_now() -> str:
    """THE stamping API: advance the process clock and return the
    encoded stamp (journal records, spans, snapshots, outbound
    frames)."""
    return _CLOCK.now()


async def merge_remote(stamp, *, source: str | None = None) -> str | None:
    """THE merge API for piggybacked stamps: fold *stamp* (as read off
    a frame/state object/reply — possibly absent or garbage) into the
    process clock.  Returns the advanced stamp, or None when nothing
    merged.  Carries the ``coord.hlc.merge`` failpoint; ANY failure
    degrades to wall-clock ordering (the clock simply does not
    advance) — it never propagates into the RPC path."""
    if stamp is None:
        return None
    try:
        from manatee_tpu import faults
        await faults.point("coord.hlc.merge")
        decoded = decode(stamp)
        if decoded is None:
            _MERGES.inc(outcome="garbage")
            return None
        out = _CLOCK.observe(*decoded)
        _MERGES.inc(outcome="ok")
        return out
    except asyncio.CancelledError:
        raise
    except Exception:
        # injected error or anything unforeseen: the stamp is advisory
        # — degrade, never wedge the frame carrying it
        _MERGES.inc(outcome="degraded")
        return None


def merge_remote_sync(stamp) -> str | None:
    """Synchronous merge for call sites with no await point (the
    CLI's fan-out parsers).  No failpoint — the seam is the live RPC
    path, not the offline reader."""
    decoded = decode(stamp)
    if decoded is None:
        return None
    return _CLOCK.observe(*decoded)


def observe_peer_clock(peer: str, remote_now: float, t0: float,
                       t1: float) -> float | None:
    """Measured skew from one fetched obs payload: *remote_now* is the
    peer's reported wall clock (the ``now`` field every obs route
    already serves), *t0*/*t1* bracket the request locally.  The
    remote read is assumed to sit at the RTT midpoint — the classic
    NTP offset estimate.  Exports ``clock_skew_seconds{peer}`` and
    returns the offset (remote minus local), or None for junk."""
    try:
        skew = float(remote_now) - (t0 + t1) / 2.0
    except (TypeError, ValueError):
        return None
    _SKEW.set(round(skew, 6), peer=str(peer))
    return skew


def hlc_sort_key(rec: dict) -> tuple:
    """The fleet-merge total order for any stamped record (journal
    event, span, alert, snapshot, timeline entry): HLC when present,
    wall-clock fallback for old peers, then ``(ts, peer, seq)`` so the
    order is deterministic under every mix.  Old records slot in at
    their wall time (logical -1 sorts them before same-millisecond
    stamped records)."""
    ts = rec.get("ts") or 0.0
    try:
        ts = float(ts)
    except (TypeError, ValueError):
        ts = 0.0
    decoded = decode(rec.get("hlc"))
    if decoded is None:
        pt, logical = int(ts * 1000), -1
    else:
        pt, logical = decoded
    return (pt, logical, ts, str(rec.get("peer")), rec.get("seq") or 0)
