"""Shard-wide observability: metrics registry, trace ids, event
journal, spans.

The reference manatee has none of this — its operators reconstruct a
failover by grepping per-peer bunyan logs (PAPER.md §0).  This package
gives every component in the peer four shared primitives:

- a process-wide metrics **registry** (`get_registry()`): counters,
  gauges, and monotonic-clock latency histograms with fixed buckets,
  rendered through the shared Prometheus text builder by the status
  server's ``GET /metrics`` (and coordd's);
- **trace ids** (`new_trace_id()` / `bind_trace()`): every
  state-machine transition mints one; it rides the coord RPC frames,
  the cluster-state object itself (so *other* peers' reactions to the
  transition carry the initiator's id), every bunyan log record, and
  the pg/backup operations the transition causes;
- an in-memory ring-buffer event **journal** (`get_journal()`):
  transition begun/committed, role changes, coord session events,
  probe state flips, restore start/finish — exposed as ``GET /events``
  per peer and merged shard-wide by ``manatee-adm events``;
- structured **spans** (`span()` / `get_span_store()`): per-stage
  timing with parent links that cross RPC frames and the cluster-state
  object, served at ``GET /spans`` and reassembled into one cross-peer
  tree (waterfall + critical path) by ``manatee-adm trace``.

Everything here is stdlib-only and allocation-light: observability must
never be able to hurt HA.
"""

from manatee_tpu.obs.causal import (
    MERGE_SKEW_BOUND_S,
    HybridClock,
    get_clock,
    hlc_now,
    hlc_sort_key,
    merge_remote,
    merge_remote_sync,
    observe_peer_clock,
)
from manatee_tpu.obs.journal import EventJournal, get_journal
from manatee_tpu.obs.journal import set_peer as _set_journal_peer
from manatee_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
)
from manatee_tpu.obs.spans import (
    Span,
    SpanStore,
    bind_parent,
    current_span_id,
    get_span_store,
    new_span_id,
    record_span,
    set_span_peer,
    span,
    traced,
)
from manatee_tpu.obs.trace import (
    TraceLogFilter,
    bind_trace,
    current_trace,
    ensure_trace,
    new_trace_id,
)

# imported last: profile.py reads the journal/metrics/spans/trace
# singletons above (the sampling profiler, event-loop monitor, and
# task census — the runtime introspection plane)
from manatee_tpu.obs.profile import (  # noqa: E402
    LoopMonitor,
    SamplingProfiler,
    get_loop_monitor,
    get_profiler,
    profile_http_reply,
    start_introspection,
    tasks_http_reply,
    tasks_payload,
)


def set_peer(peer_id: str) -> None:
    """Stamp this process's peer identity onto subsequent journal
    events AND spans (called once at daemon wiring time)."""
    _set_journal_peer(peer_id)
    set_span_peer(peer_id)


__all__ = [
    "Counter",
    "DEFAULT_BUCKETS",
    "EventJournal",
    "Gauge",
    "Histogram",
    "HybridClock",
    "LoopMonitor",
    "MERGE_SKEW_BOUND_S",
    "Registry",
    "SamplingProfiler",
    "Span",
    "SpanStore",
    "TraceLogFilter",
    "bind_parent",
    "bind_trace",
    "current_span_id",
    "current_trace",
    "ensure_trace",
    "get_clock",
    "get_journal",
    "get_loop_monitor",
    "get_profiler",
    "get_registry",
    "get_span_store",
    "hlc_now",
    "hlc_sort_key",
    "merge_remote",
    "merge_remote_sync",
    "new_span_id",
    "new_trace_id",
    "observe_peer_clock",
    "profile_http_reply",
    "record_span",
    "set_peer",
    "set_span_peer",
    "span",
    "start_introspection",
    "tasks_http_reply",
    "tasks_payload",
    "traced",
]
